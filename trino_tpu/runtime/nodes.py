"""Node discovery, heartbeat failure detection, graceful drain.

Reference blueprint: io.trino.node CoordinatorNodeManager.refreshNodes
(CoordinatorNodeManager.java:142 — active set from announcements),
failuredetector/HeartbeatFailureDetector.java:77, and server/NodeStateManager
graceful shutdown (SURVEY.md §5.3). Workers announce themselves periodically;
nodes whose announcements expire leave the active set; draining nodes accept no
new work but stay visible until tasks finish.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional


class NodeState(Enum):
    ACTIVE = "ACTIVE"
    # heartbeat-loss grace window: ONE missed announcement marks a node
    # SUSPECT — held out of new dispatch but never blacklist-struck, so a
    # GC pause costs nothing; only the full heartbeat timeout makes it GONE
    # (the blacklist hard strike)
    SUSPECT = "SUSPECT"
    DRAINING = "DRAINING"
    GONE = "GONE"


@dataclass
class NodeInfo:
    node_id: str
    uri: str
    coordinator: bool = False
    last_heartbeat: float = field(default_factory=time.time)
    state: NodeState = NodeState.ACTIVE
    # network location path, e.g. "region1/rack2/host7" (ref:
    # execution/scheduler/NetworkLocation.java)
    location: str = ""
    # announced engine version + accelerator kind ("tpu"/"gpu"/"cpu") —
    # surfaced by system.runtime.nodes (ref: NodeVersion in ServerInfo)
    version: str = ""
    device: str = ""
    # memory-pool state reported on the announcement (ref: MemoryInfo riding
    # the Trino heartbeat) — the ClusterMemoryManager's per-node view
    pool_max_bytes: int = 0
    reserved_bytes: int = 0
    revocable_bytes: int = 0
    peak_bytes: int = 0
    blocked_queries: int = 0

    def apply_memory(self, memory: Optional[dict]) -> None:
        """Fold an announcement's ``memory`` payload into this node."""
        if not isinstance(memory, dict):
            return
        def _i(key: str, alt: str = "") -> int:
            try:
                return int(memory.get(key, memory.get(alt, 0)) or 0)
            except (TypeError, ValueError):
                return 0
        self.pool_max_bytes = _i("maxBytes")
        self.reserved_bytes = _i("reservedBytes", "reserved")
        self.revocable_bytes = _i("revocableBytes", "revocable")
        self.peak_bytes = _i("peakBytes", "peak")
        self.blocked_queries = _i("blockedQueries", "blocked")


class InternalNodeManager:
    """Active worker set from announcements with heartbeat expiry.

    ``suspect_timeout`` is the grace window: a node silent past it (one
    missed announcement) turns SUSPECT — no new dispatch, no blacklist
    strike — and only past ``heartbeat_timeout`` turns GONE. Default from
    ``$TRINO_TPU_HEARTBEAT_SUSPECT_SECS``, clamped below the hard timeout.
    """

    def __init__(self, heartbeat_timeout: float = 30.0,
                 suspect_timeout: Optional[float] = None):
        from .. import knobs

        self.heartbeat_timeout = heartbeat_timeout
        if suspect_timeout is None:
            suspect_timeout = knobs.env_float(
                "TRINO_TPU_HEARTBEAT_SUSPECT_SECS", heartbeat_timeout / 3.0
            )
        self.suspect_timeout = min(float(suspect_timeout), heartbeat_timeout)
        self._nodes: Dict[str, NodeInfo] = {}
        self._lock = threading.Lock()

    def announce(
        self, node_id: str, uri: str, coordinator: bool = False,
        location: str = "", version: str = "", device: str = "",
        memory: Optional[dict] = None,
    ) -> None:
        """ref: node/Announcer.java — a node's periodic self-announcement.
        ``memory`` carries the node's pool state (reserved/revocable/peak/
        blocked bytes), the ClusterMemoryManager's per-worker feed."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                node = NodeInfo(
                    node_id, uri, coordinator, location=location,
                    version=version, device=device,
                )
                self._nodes[node_id] = node
            else:
                node.last_heartbeat = time.time()
                node.uri = uri
                if location:
                    node.location = location
                if version:
                    node.version = version
                if device:
                    node.device = device
                if node.state in (NodeState.GONE, NodeState.SUSPECT):
                    # a fresh announcement is the SUSPECT recovery path —
                    # no blacklist TTL to wait out
                    node.state = NodeState.ACTIVE
            if memory is not None:
                node.apply_memory(memory)

    def drain(self, node_id: str) -> bool:
        """Graceful shutdown entry (NodeStateManager.waitActiveTasksToFinish)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return False
            node.state = NodeState.DRAINING
            return True

    def refresh(self) -> None:
        """Expire silent nodes (HeartbeatFailureDetector's decay loop):
        past the suspect window -> SUSPECT (grace, no new dispatch), past
        the hard timeout -> GONE (blacklist hard strike)."""
        now = time.time()
        gone_cutoff = now - self.heartbeat_timeout
        suspect_cutoff = now - self.suspect_timeout
        with self._lock:
            for node in self._nodes.values():
                if node.state == NodeState.DRAINING:
                    continue
                if node.last_heartbeat < gone_cutoff:
                    node.state = NodeState.GONE
                elif (
                    node.last_heartbeat < suspect_cutoff
                    and node.state == NodeState.ACTIVE
                ):
                    node.state = NodeState.SUSPECT

    def active_nodes(self) -> List[NodeInfo]:
        self.refresh()
        with self._lock:
            return [n for n in self._nodes.values() if n.state == NodeState.ACTIVE]

    def all_nodes(self) -> List[NodeInfo]:
        self.refresh()
        with self._lock:
            return list(self._nodes.values())


class NodeBlacklist:
    """Per-query bad-worker set with timed re-admission (ref:
    HeartbeatFailureDetector.java:77 — the detector's decay window — plus
    EventDrivenFaultTolerantQueryScheduler's per-query node exclusion:
    retries must steer AWAY from the node that just failed them).

    Fed from two directions: heartbeat expiry (``sync_nodes`` blacklists
    every GONE node the manager reports) and observed task failures
    (``strike``: transport-category failures blacklist immediately —
    ``hard`` — while task-level failures accumulate ``max_strikes`` first,
    since one bad task does not condemn a worker). Entries expire after
    ``ttl`` seconds — a flaky-but-recovered worker re-admits itself — and
    ``readmit`` clears a node early (a successful liveness probe).

    Thread-safe; the FTE scheduler consults it on every worker pick.
    """

    def __init__(self, ttl: float = 60.0, max_strikes: int = 2):
        self.ttl = ttl
        self.max_strikes = max(1, max_strikes)
        self._lock = threading.Lock()
        self._until: Dict[str, float] = {}     # url -> blacklisted-until
        self._reasons: Dict[str, str] = {}
        self._strikes: Dict[str, int] = {}
        self.blacklisted_total = 0  # lifetime count of NEW blacklist entries

    @staticmethod
    def _key(url: str) -> str:
        return (url or "").rstrip("/")

    def strike(self, url: str, reason: str = "", hard: bool = False) -> bool:
        """Record a failure observed on ``url``. Returns True when this
        strike NEWLY blacklisted the node (metrics hook)."""
        key = self._key(url)
        if not key:
            return False
        now = time.time()
        with self._lock:
            strikes = self._strikes.get(key, 0) + 1
            self._strikes[key] = strikes
            if not hard and strikes < self.max_strikes:
                return False
            fresh = self._until.get(key, 0.0) <= now
            self._until[key] = now + self.ttl
            self._reasons[key] = reason
            if fresh:
                self.blacklisted_total += 1
            return fresh

    def readmit(self, url: str) -> None:
        """Early re-admission (e.g. a liveness probe succeeded)."""
        key = self._key(url)
        with self._lock:
            self._until.pop(key, None)
            self._strikes.pop(key, None)
            self._reasons.pop(key, None)

    def is_blacklisted(self, url: str) -> bool:
        key = self._key(url)
        now = time.time()
        with self._lock:
            until = self._until.get(key)
            if until is None:
                return False
            if until <= now:  # timed re-admission
                del self._until[key]
                self._strikes.pop(key, None)
                self._reasons.pop(key, None)
                return False
            return True

    def filter(self, urls) -> List[str]:
        """The given urls minus currently-blacklisted ones (may be [])."""
        return [u for u in urls if not self.is_blacklisted(u)]

    def sync_nodes(self, manager) -> int:
        """Blacklist every worker whose heartbeat expired (NodeState.GONE).
        Returns how many nodes were newly blacklisted."""
        fresh = 0
        try:
            nodes = manager.all_nodes()
        except Exception:  # noqa: BLE001 — a dead registry can't kill a query
            return 0
        for n in nodes:
            if getattr(n, "coordinator", False):
                continue
            if getattr(n, "state", None) is NodeState.GONE and n.uri:
                if self.strike(n.uri, reason="heartbeat expired", hard=True):
                    fresh += 1
        return fresh

    def snapshot(self) -> List[dict]:
        """Current entries (observability)."""
        now = time.time()
        with self._lock:
            return [
                {"url": k, "reason": self._reasons.get(k, ""),
                 "expires_in": max(0.0, until - now)}
                for k, until in sorted(self._until.items())
                if until > now
            ]


def suspect_uris(manager) -> List[str]:
    """Worker uris currently in the heartbeat-loss grace window (SUSPECT):
    the FTE scheduler steers NEW dispatch away from them without burning a
    blacklist strike. Defensive against non-InternalNodeManager registries
    (the scheduler also accepts a NodeRegistry)."""
    out: List[str] = []
    try:
        nodes = manager.all_nodes()
    except Exception:  # noqa: BLE001 — a dead registry can't kill a query
        return out
    for n in nodes:
        if getattr(n, "coordinator", False):
            continue
        if getattr(n, "state", None) is NodeState.SUSPECT and getattr(n, "uri", ""):
            out.append(n.uri)
    return out


def topology_distance(a: str, b: str) -> int:
    """Distance between two network-location paths: path length minus twice
    the shared prefix depth (ref: execution/scheduler/NetworkLocation.java +
    TopologyAwareNodeSelector.java:51 — the selector fills slots nearest
    first: same host, same rack, same region, anywhere)."""
    pa = [x for x in a.split("/") if x]
    pb = [x for x in b.split("/") if x]
    shared = 0
    for x, y in zip(pa, pb):
        if x != y:
            break
        shared += 1
    return (len(pa) - shared) + (len(pb) - shared)


def topology_order(origin: str, candidates):
    """Candidates (any object with .location) ordered nearest-first, stable
    within equal distance."""
    return sorted(candidates, key=lambda n: topology_distance(origin, n.location))


class TopologyPlacement:
    """Counter-based nearest-first task placement with per-worker capacity
    and tier SPILL-OVER (ref: TopologyAwareNodeSelector.java:51 — per-tier
    fill targets via topologicalSplitCounters; the round-4 nearest-tier-
    exclusive placement modeled unbounded capacity and could never spill).

    assign(key) is memoized: consumers asking where producer (fid, p) landed
    get the same answer the dispatch did. Within a tier, tasks balance to
    the least-loaded worker; a task goes to a farther tier only when every
    nearer worker is at capacity; when EVERY worker is saturated the
    least-loaded overall takes it (capacity is a target, not an error)."""

    def __init__(self, origin: str, urls, locations, capacity: int = 0):
        far = 1 << 30
        locs = {k.rstrip("/"): v for k, v in (locations or {}).items()}

        def dist(u: str) -> int:
            loc = locs.get(u.rstrip("/"), "")
            return topology_distance(origin, loc) if loc else far

        self._urls = list(urls)
        self._dist = {u: dist(u) for u in self._urls}
        self.capacity = capacity
        self.counts = {u: 0 for u in self._urls}
        self.assignments = {}

    def assign(self, key) -> str:
        got = self.assignments.get(key)
        if got is not None:
            return got
        candidates = [
            u for u in self._urls
            if self.capacity <= 0 or self.counts[u] < self.capacity
        ]
        order = {u: i for i, u in enumerate(self._urls)}
        if candidates:
            pick = min(
                candidates, key=lambda u: (self._dist[u], self.counts[u], order[u])
            )
        else:
            # EVERY worker saturated: least-loaded overall (distance only
            # tie-breaks) — nearest-first here would re-concentrate the
            # entire overflow on one near worker
            pick = min(
                self._urls, key=lambda u: (self.counts[u], self._dist[u], order[u])
            )
        self.counts[pick] += 1
        self.assignments[key] = pick
        return pick
