"""Metadata facade + catalog management + session.

Reference blueprint: io.trino.metadata.{Metadata,MetadataManager} (SURVEY.md §2.6
"Metadata facade") and io.trino.connector.StaticCatalogManager ("Catalog mgmt").
Routes engine metadata operations to per-catalog ConnectorMetadata, and resolves
unqualified table names against the session's catalog/schema defaults, exactly as
MetadataManager does.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import knobs
from .spi.connector import (
    Connector,
    SchemaTableName,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from .spi.predicate import TupleDomain
from .sql.tree import QualifiedName


@dataclass
class Session:
    """ref: io.trino.Session — catalog/schema defaults + session properties
    (SystemSessionProperties.java:61 analogue, see properties dict)."""

    catalog: Optional[str] = None
    schema: Optional[str] = None
    user: str = "user"
    properties: Dict[str, object] = field(default_factory=dict)

    # typed session properties, declared (name/type/default/description)
    # in the central knob registry (trino_tpu.knobs.SESSION_PROPERTIES, the
    # SystemSessionProperties.java analogue); DEFAULTS is built from it so a
    # property cannot exist without a documented declaration
    DEFAULTS = {p.name: p.default for p in knobs.SESSION_PROPERTIES}

    def get(self, name: str):
        if name in self.properties:
            return self.properties[name]
        # defaults resolved from the environment at LOOKUP time — an env var
        # set after `import trino_tpu` must still take effect, exactly like
        # the lazily-built memory pool (runtime.memory.default_pool)
        env = knobs.ENV_SESSION_DEFAULTS.get(name)
        if env is not None:
            n = knobs.env_bytes(env)
            if n:
                return n
        # dynamically-resolved defaults (validate_plan: on under pytest)
        dyn = knobs.DYNAMIC_SESSION_DEFAULTS.get(name)
        if dyn is not None:
            return dyn()
        if name in self.DEFAULTS:
            return self.DEFAULTS[name]
        raise KeyError(f"unknown session property: {name}")

    def set(self, name: str, value) -> None:
        if name not in self.DEFAULTS:
            raise KeyError(f"unknown session property: {name}")
        self.properties[name] = value


class CatalogManager:
    """ref: io.trino.connector.StaticCatalogManager — named connectors."""

    def __init__(self):
        import uuid

        self._catalogs: Dict[str, Connector] = {}
        # warm-path cache plane: identifies THIS registry in cache keys —
        # two runners in one process may mount same-named catalogs over
        # different connectors/schemas, and a cached plan resolved against
        # one registry must never serve the other (runtime/cachestore.py)
        self.cache_nonce = uuid.uuid4().hex[:8]

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def deregister(self, name: str) -> None:
        self._catalogs.pop(name, None)

    def get(self, name: str) -> Optional[Connector]:
        return self._catalogs.get(name)

    def names(self) -> List[str]:
        return sorted(self._catalogs)


@dataclass(frozen=True)
class ViewDefinition:
    """A stored view (ref: spi/connector/ConnectorViewDefinition.java +
    metadata/ViewDefinition.java): the original SQL text plus the defining
    session's catalog/schema so unqualified names inside the body resolve
    the same way at every use site."""

    sql: str
    catalog: Optional[str] = None
    schema: Optional[str] = None
    owner: str = "user"


class ViewStore:
    """Engine-side view registry keyed by (catalog, schema, name) — the
    analogue of view storage in connector metadata (MetadataManager
    createView/getView; the reference delegates to e.g. the hive metastore,
    here a process-local map serves every catalog)."""

    def __init__(self):
        self._views: Dict[Tuple[str, str, str], ViewDefinition] = {}

    def create(self, catalog: str, schema: str, name: str,
               view: ViewDefinition, replace: bool = False) -> None:
        key = (catalog, schema, name)
        if not replace and key in self._views:
            raise ValueError(f"view already exists: {catalog}.{schema}.{name}")
        self._views[key] = view

    def drop(self, catalog: str, schema: str, name: str) -> bool:
        return self._views.pop((catalog, schema, name), None) is not None

    def get(self, catalog: str, schema: str, name: str) -> Optional[ViewDefinition]:
        return self._views.get((catalog, schema, name))

    def list(self, catalog: str, schema: Optional[str] = None):
        return [
            (c, s, n, v)
            for (c, s, n), v in sorted(self._views.items())
            if c == catalog and (schema is None or s == schema)
        ]


@dataclass(frozen=True)
class SqlRoutine:
    """A stored expression-bodied SQL function (ref: metadata/
    LanguageFunctionManager + sql/routine/SqlRoutinePlanner — the reference
    compiles routines to bytecode; here the planner INLINES the body IR at
    every call site, the XLA-codegen equivalent)."""

    name: str
    parameters: Tuple[Tuple[str, object], ...]  # (name, Type)
    return_type: object
    body: object  # sql.tree Expression
    body_text: str = ""
    owner: str = "user"


class FunctionStore:
    """Engine-side routine registry keyed by (name, arity) — overload by
    argument count like GlobalFunctionCatalog's signature matching."""

    def __init__(self):
        self._functions: Dict[Tuple[str, int], SqlRoutine] = {}

    def create(self, routine: SqlRoutine, replace: bool = False) -> None:
        key = (routine.name, len(routine.parameters))
        if not replace and key in self._functions:
            raise ValueError(f"function already exists: {routine.name}")
        self._functions[key] = routine

    def drop(self, name: str) -> bool:
        keys = [k for k in self._functions if k[0] == name]
        for k in keys:
            del self._functions[k]
        return bool(keys)

    def get(self, name: str, nargs: int) -> Optional[SqlRoutine]:
        return self._functions.get((name, nargs))

    def list(self):
        return sorted(self._functions.values(), key=lambda r: r.name)


class Metadata:
    """ref: io.trino.metadata.MetadataManager (3,135 LoC) — the engine's single
    entry point for catalog operations."""

    def __init__(self, catalogs: CatalogManager):
        from .connectors.system import SystemContext

        self.catalogs = catalogs
        self.views = ViewStore()
        self.functions = FunctionStore()
        self._info_schemas: Dict[str, object] = {}
        # late-bound engine refs for the builtin `system` catalog (the
        # QueryManager / CoordinatorServer attach themselves here)
        self.system_context = SystemContext()
        self._system_connector = None

    def _info_schema(self, catalog: str):
        """Lazy per-catalog information_schema connector (ref: the
        InformationSchema* connector registered alongside every catalog)."""
        conn = self._info_schemas.get(catalog)
        if conn is None:
            from .connectors.information_schema import InformationSchemaConnector

            conn = InformationSchemaConnector(
                catalog, self.catalogs, self.views,
                resolver=self.connector_by_name,
            )
            self._info_schemas[catalog] = conn
        return conn

    def _system(self):
        """Lazy builtin ``system`` connector (ref: GlobalSystemConnector —
        always resolvable, like information_schema; an explicitly registered
        catalog of the same name wins)."""
        if self._system_connector is None:
            from .connectors.system import SystemConnector

            self._system_connector = SystemConnector(self.system_context)
        return self._system_connector

    def connector_by_name(self, catalog: str):
        """Registered connector, or the builtin system catalog."""
        conn = self.catalogs.get(catalog)
        if conn is None and catalog == "system":
            return self._system()
        return conn

    def resolve_name(
        self, session: Session, name: QualifiedName
    ) -> Tuple[str, str, str]:
        """Qualify a 1/2/3-part name against the session defaults."""
        parts = name.parts
        if len(parts) == 3:
            return parts[0], parts[1], parts[2]
        if len(parts) == 2:
            if session.catalog is None:
                raise ValueError(f"no default catalog set for table {name}")
            return session.catalog, parts[0], parts[1]
        if len(parts) == 1:
            if session.catalog is None or session.schema is None:
                raise ValueError(f"no default catalog/schema set for table {name}")
            return session.catalog, session.schema, parts[0]
        raise ValueError(f"invalid table name: {name}")

    def resolve_table(
        self, session: Session, name: QualifiedName
    ) -> Tuple[TableHandle, TableMetadata]:
        catalog, schema, table = self.resolve_name(session, name)
        connector = self.connector_by_name(catalog)
        if connector is None:
            raise ValueError(f"catalog not found: {catalog}")
        if schema == "information_schema":
            connector = self._info_schema(catalog)
        st = SchemaTableName(schema, table)
        meta = connector.metadata().get_table_metadata(st)
        if meta is None:
            raise ValueError(f"table not found: {catalog}.{st}")
        return TableHandle(catalog=catalog, schema_table=st), meta

    def _connector(self, handle: TableHandle) -> Connector:
        if handle.schema_table.schema == "information_schema":
            return self._info_schema(handle.catalog)
        return self.connector_by_name(handle.catalog)

    def get_table_metadata(self, handle: TableHandle) -> TableMetadata:
        meta = self._connector(handle).metadata().get_table_metadata(
            handle.schema_table
        )
        assert meta is not None
        return meta

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        return self._connector(handle).metadata().get_table_statistics(handle)

    def apply_filter(self, handle: TableHandle, domain: TupleDomain) -> Optional[TableHandle]:
        return self._connector(handle).metadata().apply_filter(handle, domain)

    def connector_for(self, handle: TableHandle) -> Connector:
        return self._connector(handle)
