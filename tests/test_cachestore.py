"""Warm-path cache plane (runtime/cachestore.py): result / fragment / plan
tiers keyed on structural fingerprints + catalog versions.

Covers the round-11 correctness gates: the mixed-snapshot regression
(concurrent INSERT + cached SELECT serves fully-old or fully-new, never a
blend), snapshot-bump invalidation, TTL fallback for unversioned catalogs,
nondeterministic-expression bypass, session-property keying, transaction
bypass, single-flight dedup under 16 concurrent identical queries, and the
``cache_poison`` chaos site (a crash mid-materialization must leave no
poisoned fragment entry).
"""

import threading
import time

import pytest

from trino_tpu.connectors.iceberg_lite import IcebergLiteConnector
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.fs import FileSystemManager, LocalFileSystem
from trino_tpu.runtime.cachestore import CACHES
from trino_tpu.runtime.local import ClientContext, LocalQueryRunner

SCALE = 0.001

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       avg(l_discount) AS avg_disc, count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""


@pytest.fixture(autouse=True)
def _clean_caches():
    CACHES.clear()
    yield
    CACHES.clear()


@pytest.fixture()
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture()
def berg_runner(tmp_path):
    fsm = FileSystemManager()
    fsm.register("local", lambda: LocalFileSystem(str(tmp_path)))
    berg = IcebergLiteConnector(fsm, "local://warehouse")
    r = LocalQueryRunner.tpch(scale=SCALE)
    r.register_catalog("berg", berg)
    CACHES.clear()  # register_catalog fires on_ddl; start tests at zero
    return r, berg


def _tier(kind):
    by = {r[0]: r for r in CACHES.stats_rows()}
    return by[kind]  # (tier, entries, bytes, hits, misses, evict, inval)


# ------------------------------------------------------- satellite regression


class TestMixedSnapshotRegression:
    """A result-cache entry recorded mid-DML must never serve a row set
    from a mixed snapshot (written FIRST, before the guard existed)."""

    def test_store_skipped_when_version_changes_mid_execution(
        self, berg_runner, monkeypatch
    ):
        r, berg = berg_runner
        r.execute(
            "CREATE TABLE berg.default.nat AS "
            "SELECT n_nationkey, n_name FROM nation WHERE n_nationkey < 5"
        )
        CACHES.clear()
        r.session.set("result_cache", True)

        # force the race deterministically: an INSERT lands between the
        # pre-execution version snapshot and the post-drain store point
        from trino_tpu.runtime.executor import PlanExecutor

        raced = {"done": False}
        orig = PlanExecutor.execute

        def execute_with_racing_insert(self_ex):
            out = orig(self_ex)
            if not raced["done"]:
                raced["done"] = True
                r2 = LocalQueryRunner.tpch(scale=SCALE)
                r2.register_catalog("berg", berg)
                r2.execute(
                    "INSERT INTO berg.default.nat "
                    "SELECT n_nationkey, n_name FROM nation "
                    "WHERE n_nationkey BETWEEN 5 AND 9"
                )
            return out

        monkeypatch.setattr(PlanExecutor, "execute", execute_with_racing_insert)
        old = r.execute("SELECT count(*) FROM berg.default.nat")
        monkeypatch.setattr(PlanExecutor, "execute", orig)
        assert old.rows == [(5,)]  # the raced run still answers correctly
        # ... but it must NOT have cached its pre-insert row set
        assert _tier("result")[1] == 0, "mixed-snapshot entry was stored"
        fresh = r.execute("SELECT count(*) FROM berg.default.nat")
        assert fresh.rows == [(10,)]

    def test_concurrent_insert_and_cached_select_full_snapshots_only(
        self, berg_runner
    ):
        r, berg = berg_runner
        r.execute(
            "CREATE TABLE berg.default.evens AS "
            "SELECT n_nationkey FROM nation WHERE n_nationkey < 5"
        )
        CACHES.clear()
        r.session.set("result_cache", True)
        writer = LocalQueryRunner.tpch(scale=SCALE)
        writer.register_catalog("berg", berg)
        errors = []

        def insert_batches():
            try:
                for _ in range(4):
                    writer.execute(
                        "INSERT INTO berg.default.evens "
                        "SELECT n_nationkey FROM nation WHERE n_nationkey < 5"
                    )
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=insert_batches)
        t.start()
        try:
            while t.is_alive():
                (n,), = r.execute(
                    "SELECT count(*) FROM berg.default.evens"
                ).rows
                # every commit appends a full 5-row batch: any count not a
                # multiple of 5 is a blend of two snapshots
                assert n % 5 == 0, f"mixed snapshot served: count={n}"
        finally:
            t.join()
        assert not errors
        (n,), = r.execute("SELECT count(*) FROM berg.default.evens").rows
        assert n == 25


# --------------------------------------------------------------- result tier


class TestResultCache:
    def test_hit_is_bit_identical_and_tagged(self, runner):
        runner.session.set("result_cache", True)
        cold = runner.execute(Q6)
        warm = runner.execute(Q6)
        assert warm.rows == cold.rows
        assert warm.query_stats["cacheHitTier"] == "result"
        assert "result cache HIT" in warm.query_stats["cacheProvenance"]
        assert cold.query_stats["cacheHitTier"] is None
        tier = _tier("result")
        assert tier[1] == 1 and tier[3] == 1  # one entry, one hit

    def test_oracle_corpus_cold_vs_warm(self, runner):
        """Every cached result must be bit-identical to the cold path."""
        colds = {}
        for name, sql in (("q1", Q1), ("q3", Q3), ("q6", Q6)):
            colds[name] = runner.execute(sql).rows
        runner.session.set("result_cache", True)
        runner.session.set("plan_cache_size", 64)
        for _ in range(2):  # store pass, then hit pass
            for name, sql in (("q1", Q1), ("q3", Q3), ("q6", Q6)):
                assert runner.execute(sql).rows == colds[name]
        assert _tier("result")[3] == 3  # all three hit on the second pass

    def test_snapshot_bump_invalidates(self, berg_runner):
        r, _ = berg_runner
        r.execute(
            "CREATE TABLE berg.default.nat AS "
            "SELECT n_nationkey FROM nation WHERE n_nationkey < 5"
        )
        r.session.set("result_cache", True)
        q = "SELECT count(*) FROM berg.default.nat"
        assert r.execute(q).rows == [(5,)]
        assert r.execute(q).rows == [(5,)]
        assert _tier("result")[3] == 1
        r.execute(
            "INSERT INTO berg.default.nat "
            "SELECT n_nationkey FROM nation WHERE n_nationkey BETWEEN 5 AND 9"
        )
        # exact invalidation: the INSERT dropped the entry (counter moved)
        assert _tier("result")[6] >= 1
        assert r.execute(q).rows == [(10,)]

    def test_ttl_fallback_for_unversioned_catalogs(self, runner):
        class UnversionedMemory(MemoryConnector):
            cache_table_version = None  # no version hook -> TTL-or-bypass

        runner.register_catalog("raw", UnversionedMemory())
        runner.execute("CREATE TABLE raw.default.t (x bigint)")
        runner.execute("INSERT INTO raw.default.t VALUES (1), (2)")
        CACHES.clear()
        runner.session.set("result_cache", True)
        q = "SELECT count(*) FROM raw.default.t"

        # ttl=0: unversioned plans bypass the tier entirely
        runner.session.set("result_cache_ttl", 0)
        runner.execute(q)
        runner.execute(q)
        assert _tier("result")[1] == 0 and _tier("result")[3] == 0

        # ttl>0: entries serve until expiry
        runner.session.set("result_cache_ttl", 300.0)
        assert runner.execute(q).rows == [(2,)]
        assert runner.execute(q).rows == [(2,)]
        assert _tier("result")[3] == 1
        # out-of-band mutation (no DML through the runner, no version hook):
        # an aged entry must EXPIRE at lookup instead of serving stale rows
        import numpy as np

        from trino_tpu.spi.connector import SchemaTableName
        from trino_tpu.spi.page import Column, Page
        from trino_tpu.spi.types import BIGINT

        conn = runner.catalogs.get("raw")
        page = Page(
            (Column(BIGINT, np.array([3]), np.array([True])),),
            np.array([True]),
        )
        conn.insert(SchemaTableName("default", "t"), page)
        with CACHES.result._lock:
            for e in CACHES.result._entries.values():
                e.created -= 301.0
        inval_before = _tier("result")[6]
        assert runner.execute(q).rows == [(3,)]
        assert _tier("result")[6] == inval_before + 1

    def test_nondeterministic_expression_bypasses(self, runner):
        runner.session.set("result_cache", True)
        q = "SELECT count(*) FROM lineitem WHERE l_quantity < 50 * random()"
        runner.execute(q)
        runner.execute(q)
        assert _tier("result")[1] == 0, "nondeterministic query was cached"

    def test_session_property_keying(self, runner):
        runner.session.set("result_cache", True)
        runner.session.set("hash_partition_count", 8)
        a = runner.execute(Q6)
        runner.session.set("hash_partition_count", 16)
        b = runner.execute(Q6)
        assert a.rows == b.rows
        # different session state -> different key -> no cross-property hit
        assert _tier("result")[3] == 0 and _tier("result")[1] == 2

    def test_transaction_bypass(self, runner):
        runner.session.set("result_cache", True)
        ctx = ClientContext()
        runner.execute("START TRANSACTION", client=ctx)
        runner.execute(Q6, client=ctx)
        assert _tier("result")[1] == 0, "cached inside an open transaction"
        runner.execute("COMMIT", client=ctx)
        runner.execute(Q6)
        assert _tier("result")[1] == 1

    def test_persistence_roundtrip(self, runner, tmp_path, monkeypatch):
        path = str(tmp_path / "results.json")
        monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", path)
        # the env path alone opts the process in (deployment default idiom)
        cold = runner.execute(Q6)
        CACHES.clear()  # drop memory; the file must reconstruct the entry
        warm = runner.execute(Q6)
        assert warm.rows == cold.rows
        assert warm.query_stats["cacheHitTier"] == "result"
        # explicit session False wins over the env default
        CACHES.clear()
        runner.session.set("result_cache", False)
        runner.execute(Q6)
        assert _tier("result")[1] == 0

    def test_lru_eviction_by_bytes(self, runner):
        runner.session.set("result_cache", True)
        for sql in (Q1, Q3, Q6):
            runner.execute(sql)
        with CACHES.result._lock:
            sizes = sorted(e.nbytes for e in CACHES.result._entries.values())
        # a bound that admits every entry individually but not all three
        bound = sizes[-1] + sizes[0]
        CACHES.clear()
        runner.session.set("result_cache_max_bytes", bound)
        for sql in (Q1, Q3, Q6):
            runner.execute(sql)
        tier = _tier("result")
        assert tier[5] >= 1, f"no eviction under a {bound}-byte bound"
        assert tier[2] <= bound


# ------------------------------------------------------------- fragment tier


class TestFragmentCache:
    def test_single_flight_16_concurrent_identical(self, runner, monkeypatch):
        runner.session.set("fragment_cache", True)
        runner.execute(Q6)  # warm compile so threads don't serialize on XLA
        CACHES.clear()
        from trino_tpu.runtime.executor import PlanExecutor

        agg_runs = []
        orig = PlanExecutor._exec_AggregationNode

        def counting(self_ex, node):
            agg_runs.append(threading.get_ident())
            return orig(self_ex, node)

        monkeypatch.setattr(PlanExecutor, "_exec_AggregationNode", counting)
        expected = None
        results = [None] * 16

        def go(i):
            results[i] = runner.execute(Q6).rows

        threads = [threading.Thread(target=go, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        expected = runner.execute(Q6).rows
        assert all(rows == expected for rows in results)
        # the shared scan->filter->agg prefix executed EXACTLY once; the
        # 15 losers blocked on the winner, the 17th run hit the entry
        assert len(agg_runs) == 1, f"prefix ran {len(agg_runs)}x"
        tier = _tier("fragment")
        assert tier[1] == 1 and tier[3] >= 15

    def test_shared_prefix_across_different_queries(self, runner):
        """Two DIFFERENT statements sharing a scan+filter+agg prefix: the
        second consumes the first's committed materialization."""
        runner.session.set("fragment_cache", True)
        qa = ("SELECT revenue FROM (SELECT sum(l_extendedprice * l_discount)"
              " AS revenue FROM lineitem WHERE l_quantity < 24)")
        qb = ("SELECT revenue + 1 FROM (SELECT sum(l_extendedprice *"
              " l_discount) AS revenue FROM lineitem WHERE l_quantity < 24)")
        a = runner.execute(qa)
        b = runner.execute(qb)
        assert b.rows[0][0] == pytest.approx(a.rows[0][0] + 1)
        tier = _tier("fragment")
        assert tier[1] == 1 and tier[3] == 1
        assert b.query_stats["cacheHitTier"] == "fragment"
        assert any(
            "fragment reused from query" in p
            for p in b.query_stats["cacheProvenance"]
        )

    def test_cache_poison_chaos_leaves_no_entry(self, runner):
        """A crash mid-materialization (the ``cache_poison`` site fires in
        the store path, aborting the exchange attempt before commit) must
        leave NO fragment entry — later queries re-execute and commit."""
        from trino_tpu.runtime.failure import ChaosInjector

        runner.session.set("fragment_cache", True)
        cold = runner.execute(Q6)
        CACHES.clear()
        with ChaosInjector() as chaos:
            chaos.arm("cache_poison", times=1)
            poisoned = runner.execute(Q6)
            assert chaos.fired.get("cache_poison") == 1
        assert poisoned.rows == cold.rows  # the winner still answers
        assert _tier("fragment")[1] == 0, "poisoned fragment entry committed"
        # a clean run repopulates and serves
        assert runner.execute(Q6).rows == cold.rows
        assert runner.execute(Q6).rows == cold.rows
        assert _tier("fragment")[1] == 1 and _tier("fragment")[3] >= 1

    def test_insert_invalidates_fragment_entries(self, berg_runner):
        r, _ = berg_runner
        r.execute(
            "CREATE TABLE berg.default.nat AS "
            "SELECT n_nationkey FROM nation WHERE n_nationkey < 5"
        )
        r.session.set("fragment_cache", True)
        q = "SELECT count(*) FROM berg.default.nat"
        assert r.execute(q).rows == [(5,)]
        assert _tier("fragment")[1] == 1
        r.execute(
            "INSERT INTO berg.default.nat "
            "SELECT n_nationkey FROM nation WHERE n_nationkey BETWEEN 5 AND 9"
        )
        assert _tier("fragment")[1] == 0 and _tier("fragment")[6] >= 1
        assert r.execute(q).rows == [(10,)]

    def test_nondeterministic_prefix_not_cached(self, runner):
        runner.session.set("fragment_cache", True)
        q = "SELECT count(*) FROM lineitem WHERE l_quantity < 50 * random()"
        runner.execute(q)
        runner.execute(q)
        assert _tier("fragment")[1] == 0


# ----------------------------------------------------------------- plan tier


class TestPlanCache:
    def test_skips_parse_and_planning(self, runner, monkeypatch):
        runner.session.set("plan_cache_size", 16)
        from trino_tpu.planner.logical_planner import LogicalPlanner

        calls = []
        orig = LogicalPlanner.plan

        def counting(self_p, stmt):
            calls.append(1)
            return orig(self_p, stmt)

        monkeypatch.setattr(LogicalPlanner, "plan", counting)
        a = runner.execute(Q6)
        n_after_first = len(calls)
        b = runner.execute(Q6)
        assert b.rows == a.rows
        assert len(calls) == n_after_first, "plan-cache hit still planned"
        assert b.query_stats["cacheHitTier"] == "plan"

    def test_ddl_invalidates_plans(self, runner):
        runner.session.set("plan_cache_size", 16)
        runner.register_catalog("mem", MemoryConnector())
        CACHES.clear()
        runner.execute("CREATE TABLE mem.default.t (x bigint)")
        runner.execute("INSERT INTO mem.default.t VALUES (1)")
        q = "SELECT count(*) FROM mem.default.t"
        runner.execute(q)
        assert _tier("plan")[1] == 1
        runner.execute("DROP TABLE mem.default.t")
        assert _tier("plan")[1] == 0, "DDL left stale plans behind"
        runner.execute("CREATE TABLE mem.default.t (x bigint)")
        assert runner.execute(q).rows == [(0,)]

    def test_nondeterministic_text_bypasses(self, runner):
        runner.session.set("plan_cache_size", 16)
        runner.execute("SELECT random() < 2 FROM nation LIMIT 1")
        assert _tier("plan")[1] == 0

    def test_nondeterminism_gate_is_word_bounded(self, runner):
        """Identifiers CONTAINING a nondeterministic token (i_brand has
        'rand', known has 'now') must still plan-cache — substring
        matching would silently disable the tier for them."""
        runner.session.set("plan_cache_size", 16)
        runner.execute(
            "SELECT n_name AS brand_known FROM nation ORDER BY brand_known"
            " LIMIT 1"
        )
        assert _tier("plan")[1] == 1

    def test_prepared_execute_not_keyed_on_execute_text(self, runner):
        """EXECUTE'd statements carry the EXECUTE text; the plan tier must
        not serve parameter-bound plans across different parameters."""
        runner.session.set("plan_cache_size", 16)
        ctx = ClientContext()
        runner.execute(
            "PREPARE p FROM SELECT count(*) FROM nation WHERE n_nationkey < ?",
            client=ctx,
        )
        a = runner.execute("EXECUTE p USING 5", client=ctx)
        b = runner.execute("EXECUTE p USING 10", client=ctx)
        assert a.rows == [(5,)] and b.rows == [(10,)]


# ------------------------------------------------------------- observability


class TestObservability:
    def test_system_runtime_caches_table(self, runner):
        runner.session.set("result_cache", True)
        runner.execute(Q6)
        runner.execute(Q6)
        res = runner.execute(
            "SELECT tier, entries, bytes, hits, misses, evictions, "
            "invalidations FROM system.runtime.caches ORDER BY tier"
        )
        by_tier = {r[0]: r for r in res.rows}
        assert set(by_tier) == {"plan", "result", "fragment"}
        assert by_tier["result"][1] == 1 and by_tier["result"][3] >= 1
        for row in res.rows:
            assert all(isinstance(v, int) for v in row[1:])

    def test_counters_registered_with_help(self, runner):
        from trino_tpu.runtime.metrics import REGISTRY

        runner.session.set("result_cache", True)
        runner.execute(Q6)
        runner.execute(Q6)
        by_name = {
            (m["name"], tuple(sorted(m.get("labels", {}).items()))): m
            for m in REGISTRY.collect()
        }
        hits = [
            m for (n, _), m in by_name.items()
            if n == "trino_tpu_cache_hits_total"
        ]
        assert hits and all(m["help"] for m in hits)

    def test_flight_events_paired_with_outcome(self, runner):
        from trino_tpu.runtime.observability import RECORDER

        runner.session.set("result_cache", True)
        runner.session.set("flight_recorder", True)
        RECORDER.clear()
        runner.execute(Q6)
        runner.execute(Q6)
        runner.session.set("flight_recorder", False)
        events = RECORDER.events()
        RECORDER.clear()
        lookups_b = [e for e in events
                     if e["name"] == "cache_lookup" and e["ph"] == "B"]
        lookups_e = [e for e in events
                     if e["name"] == "cache_lookup" and e["ph"] == "E"]
        assert lookups_b and len(lookups_b) == len(lookups_e)
        outcomes = {(e.get("args") or {}).get("outcome") for e in lookups_e}
        assert {"hit", "miss"} <= outcomes
        stores = [e for e in events
                  if e["name"] == "cache_store" and e["ph"] == "E"]
        assert any(
            (e.get("args") or {}).get("outcome") == "stored" for e in stores
        )

    def test_explain_renders_provenance(self, runner):
        runner.session.set("result_cache", True)
        runner.execute(Q6)
        text = "\n".join(
            r[0] for r in runner.execute("EXPLAIN " + Q6).rows
        )
        assert "result cache HIT" in text
        # cold plans (caches off) keep byte-identical EXPLAIN output
        runner.session.set("result_cache", False)
        text_off = "\n".join(
            r[0] for r in runner.execute("EXPLAIN " + Q6).rows
        )
        assert "result cache" not in text_off

    def test_explain_analyze_renders_fragment_reuse(self, runner):
        runner.session.set("fragment_cache", True)
        runner.execute(Q6)
        text = "\n".join(
            r[0] for r in runner.execute("EXPLAIN ANALYZE " + Q6).rows
        )
        assert "fragment reused from query" in text

    def test_query_stats_fields_carry_tier(self, runner):
        from trino_tpu.runtime.observability import query_stats_fields

        runner.session.set("result_cache", True)
        runner.execute(Q6)
        warm = runner.execute(Q6)
        fields = query_stats_fields(warm.query_stats)
        assert fields["cacheHitTier"] == "result"


# ------------------------------------------------- version token identity


class TestVersionTokenIdentity:
    """Equal version tokens must imply equal DATA — across connector
    instances and processes (the persisted cache outlives both)."""

    def test_two_memory_connectors_never_alias(self):
        ra = LocalQueryRunner.tpch(scale=SCALE)
        ra.register_catalog("mem", MemoryConnector())
        rb = LocalQueryRunner.tpch(scale=SCALE)
        rb.register_catalog("mem", MemoryConnector())
        for r, vals in ((ra, "(1), (2)"), (rb, "(10), (14)")):
            r.execute("CREATE TABLE mem.default.t (x bigint)")
            r.execute(f"INSERT INTO mem.default.t VALUES {vals}")
            r.session.set("result_cache", True)
        CACHES.clear()
        # same SQL, same table name, same mutation count — different data:
        # the per-instance nonce keeps the second runner off the first's entry
        assert ra.execute("SELECT sum(x) FROM mem.default.t").rows == [(3,)]
        assert rb.execute("SELECT sum(x) FROM mem.default.t").rows == [(24,)]

    def test_tpch_default_scale_rides_the_token(self):
        """A non-canonical schema name resolves scale from the connector
        default — two defaults must not alias under one schema name."""
        r1 = LocalQueryRunner.tpch(scale=0.001, schema="mydata")
        r2 = LocalQueryRunner.tpch(scale=0.002, schema="mydata")
        r1.session.set("result_cache", True)
        r2.session.set("result_cache", True)
        CACHES.clear()
        c1 = r1.execute("SELECT count(*) FROM lineitem").rows
        c2 = r2.execute("SELECT count(*) FROM lineitem").rows
        assert c1 != c2

    def test_plan_cache_scoped_to_catalog_registry(self):
        """Two runners mounting same-named catalogs (possibly different
        table schemas): a plan resolved against one registry must never
        serve the other — the registry nonce rides every plan-cache key,
        so the second runner MISSES and plans for itself."""
        ra = LocalQueryRunner.tpch(scale=SCALE)
        ra.register_catalog("mem", MemoryConnector())
        rb = LocalQueryRunner.tpch(scale=SCALE)
        rb.register_catalog("mem", MemoryConnector())
        for r, vals in ((ra, "(1), (2)"), (rb, "(10), (14)")):
            r.execute("CREATE TABLE mem.default.kv (x bigint)")
            r.execute(f"INSERT INTO mem.default.kv VALUES {vals}")
            r.session.set("plan_cache_size", 16)
        CACHES.clear()
        q = "SELECT sum(x) FROM mem.default.kv"
        assert ra.execute(q).rows == [(3,)]
        hits_before = _tier("plan")[3]
        assert rb.execute(q).rows == [(24,)]
        # rb planned against ITS registry: no cross-registry plan hit,
        # two separate entries
        assert _tier("plan")[3] == hits_before
        assert _tier("plan")[1] == 2
        # and each runner's own repeat DOES hit its own entry
        assert rb.execute(q).rows == [(24,)]
        assert _tier("plan")[3] == hits_before + 1

    def test_system_tables_never_result_cached(self, runner):
        """Volatile engine snapshots (system.runtime.*) bypass the result
        tier — a monitoring poll must see NOW, not a TTL-old replay."""
        runner.session.set("result_cache", True)
        q = "SELECT count(*) FROM system.runtime.queries"
        a = runner.execute(q)
        b = runner.execute(q)
        assert b.query_stats["cacheHitTier"] is None
        assert _tier("result")[1] == 0
        del a
        # information_schema likewise: "metadata is never stale" — the
        # backing catalog's (static!) version token must not apply
        q2 = "SELECT count(*) FROM tpch.information_schema.tables"
        runner.execute(q2)
        c = runner.execute(q2)
        assert c.query_stats["cacheHitTier"] is None
        assert _tier("result")[1] == 0

    def test_iceberg_token_qualified_by_warehouse(self, tmp_path):
        """Snapshot ids are sequential per table; two warehouses at the
        same snapshot count must never serve each other's rows."""
        runners = []
        for tag in ("wh_a", "wh_b"):
            fsm = FileSystemManager()
            root = str(tmp_path / tag)
            fsm.register("local", lambda root=root: LocalFileSystem(root))
            r = LocalQueryRunner.tpch(scale=SCALE)
            r.register_catalog(
                "berg", IcebergLiteConnector(fsm, "local://" + tag)
            )
            runners.append(r)
        ia, ib = runners
        ia.execute("CREATE TABLE berg.default.t AS "
                   "SELECT n_nationkey FROM nation WHERE n_nationkey < 5")
        ib.execute("CREATE TABLE berg.default.t AS "
                   "SELECT n_nationkey FROM nation WHERE n_nationkey < 10")
        ia.session.set("result_cache", True)
        ib.session.set("result_cache", True)
        CACHES.clear()
        assert ia.execute("SELECT count(*) FROM berg.default.t").rows == [(5,)]
        assert ib.execute("SELECT count(*) FROM berg.default.t").rows == [(10,)]


# -------------------------------------------------------------- distributed


class TestDistributedFragmentCache:
    def test_staged_runner_shares_fragments_across_queries(self):
        from trino_tpu.parallel.runner import DistributedQueryRunner

        r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=2)
        r.session.set("fragment_cache", True)
        r.session.set("use_ici_exchange", False)
        CACHES.clear()
        q = ("SELECT l_returnflag, count(*) FROM lineitem "
             "WHERE l_quantity < 24 GROUP BY l_returnflag "
             "ORDER BY l_returnflag")
        a = r.execute(q)
        hits_before = _tier("fragment")[3]
        b = r.execute(q)
        assert b.rows == a.rows
        assert _tier("fragment")[3] > hits_before
