"""Resource groups, transactions, and access control.

Model: the reference's TestResourceGroups (InternalResourceGroup state
machine), TestInMemoryTransactionManager, and file-based access-control
plugin tests (TestFileBasedAccessControl).
"""

import threading
import time

import pytest


# --------------------------------------------------------------------------- #
# resource groups (unit level — the state machine itself)
# --------------------------------------------------------------------------- #


class TestResourceGroups:
    def make(self, limit=1, max_queued=2):
        from trino_tpu.runtime.resource_groups import (
            ResourceGroupManager,
            ResourceGroupSpec,
            SelectorSpec,
        )

        spec = ResourceGroupSpec(
            name="global", hard_concurrency_limit=limit, max_queued=max_queued
        )
        return ResourceGroupManager([spec], [SelectorSpec(group=("global",))])

    def test_admit_then_queue(self):
        m = self.make(limit=1)
        t1 = m.submit("alice")
        assert t1.admitted
        t2 = m.submit("bob")
        assert not t2.admitted
        m.finish(t1)
        assert t2.event.wait(1) and t2.admitted
        m.finish(t2)

    def test_queue_full_rejects(self):
        from trino_tpu.runtime.resource_groups import QueryQueueFullError

        m = self.make(limit=1, max_queued=1)
        t1 = m.submit("a")
        m.submit("b")  # queued
        with pytest.raises(QueryQueueFullError):
            m.submit("c")
        m.finish(t1)

    def test_per_user_subgroups(self):
        from trino_tpu.runtime.resource_groups import (
            ResourceGroupManager,
            ResourceGroupSpec,
            SelectorSpec,
        )

        spec = ResourceGroupSpec(
            name="global",
            hard_concurrency_limit=2,
            max_queued=10,
            sub_groups=(
                ResourceGroupSpec(
                    name="${USER}", hard_concurrency_limit=1, max_queued=10
                ),
            ),
        )
        m = ResourceGroupManager(
            [spec], [SelectorSpec(group=("global", "${USER}"))]
        )
        a1 = m.submit("alice")
        a2 = m.submit("alice")  # alice at her per-user limit -> queues
        b1 = m.submit("bob")  # bob has his own subgroup -> admitted
        assert a1.admitted and b1.admitted and not a2.admitted
        m.finish(a1)
        assert a2.event.wait(1) and a2.admitted
        m.finish(a2)
        m.finish(b1)

    def test_weighted_fair_prefers_lighter_group(self):
        from trino_tpu.runtime.resource_groups import (
            ResourceGroupManager,
            ResourceGroupSpec,
            SelectorSpec,
        )

        spec = ResourceGroupSpec(
            name="root",
            hard_concurrency_limit=1,
            max_queued=10,
            sub_groups=(
                ResourceGroupSpec(name="heavy", scheduling_weight=1, hard_concurrency_limit=5, max_queued=10),
                ResourceGroupSpec(name="light", scheduling_weight=10, hard_concurrency_limit=5, max_queued=10),
            ),
        )
        m = ResourceGroupManager(
            [spec],
            [
                SelectorSpec(group=("root", "heavy"), user_pattern="h.*"),
                SelectorSpec(group=("root", "light"), user_pattern="l.*"),
            ],
        )
        t0 = m.submit("h0")
        th = m.submit("h1")  # queued in heavy (enqueued first)
        tl = m.submit("l1")  # queued in light
        m.finish(t0)
        # both children idle (running 0 each): weighted fair ties at 0 —
        # earliest waiter (heavy) wins; then light is next
        assert th.event.wait(1)
        m.finish(th)
        assert tl.event.wait(1)
        m.finish(tl)

    def test_info_tree(self):
        m = self.make()
        t = m.submit("a")
        info = m.info()
        assert info["subGroups"][0]["running"] == 1
        m.finish(t)

    def test_config_round_trip(self):
        from trino_tpu.runtime.resource_groups import ResourceGroupManager

        m = ResourceGroupManager.from_config(
            {
                "rootGroups": [
                    {
                        "name": "global",
                        "hardConcurrencyLimit": 3,
                        "maxQueued": 7,
                        "subGroups": [
                            {"name": "adhoc", "hardConcurrencyLimit": 2}
                        ],
                    }
                ],
                "selectors": [{"group": "global.adhoc"}],
            }
        )
        t = m.submit("x")
        assert t.group.path == "global.adhoc"
        m.finish(t)


# --------------------------------------------------------------------------- #
# resource groups through the QueryManager
# --------------------------------------------------------------------------- #


class TestQueryManagerAdmission:
    def test_concurrency_one_serializes(self):
        from trino_tpu.runtime.query_manager import QueryManager, QueryState

        running = []
        peak = []
        lock = threading.Lock()

        def slow_exec(sql):
            with lock:
                running.append(1)
                peak.append(len(running))
            time.sleep(0.1)
            with lock:
                running.pop()

            class R:
                column_names = ["x"]
                rows = [(1,)]

            return R()

        qm = QueryManager(slow_exec, max_workers=4, max_concurrent=1)
        qs = [qm.submit(f"q{i}") for i in range(3)]
        for q in qs:
            assert q.wait_done(10)
            assert q.state == QueryState.FINISHED
        assert max(peak) == 1

    def test_queue_full_fails_query(self):
        from trino_tpu.runtime.query_manager import QueryManager, QueryState
        from trino_tpu.runtime.resource_groups import ResourceGroupManager

        ev = threading.Event()

        def blocking_exec(sql):
            ev.wait(5)

            class R:
                column_names = ["x"]
                rows = []

            return R()

        rgm = ResourceGroupManager.default(1, max_queued=1)
        qm = QueryManager(blocking_exec, max_workers=4, resource_groups=rgm)
        q1 = qm.submit("a")
        time.sleep(0.2)  # let q1 admit
        q2 = qm.submit("b")
        time.sleep(0.2)  # q2 queues
        q3 = qm.submit("c")
        assert q3.wait_done(5)
        assert q3.state == QueryState.FAILED and "queued" in q3.error.lower()
        ev.set()
        assert q1.wait_done(5) and q2.wait_done(5)


# --------------------------------------------------------------------------- #
# transactions
# --------------------------------------------------------------------------- #


@pytest.fixture()
def runner():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime import LocalQueryRunner
    from trino_tpu.metadata import Session

    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", MemoryConnector())
    r.execute("CREATE TABLE t AS SELECT 1 AS id, 10 AS v UNION ALL SELECT 2, 20")
    return r


class TestTransactions:
    def test_rollback_restores_update(self, runner):
        runner.execute("START TRANSACTION")
        runner.execute("UPDATE t SET v = 99 WHERE id = 1")
        assert runner.execute("SELECT v FROM t WHERE id = 1").rows == [(99,)]
        runner.execute("ROLLBACK")
        assert runner.execute("SELECT v FROM t WHERE id = 1").rows == [(10,)]

    def test_commit_keeps_changes(self, runner):
        runner.execute("START TRANSACTION")
        runner.execute("DELETE FROM t WHERE id = 2")
        runner.execute("COMMIT")
        assert runner.execute("SELECT count(*) FROM t").rows == [(1,)]

    def test_rollback_drops_created_table(self, runner):
        runner.execute("START TRANSACTION")
        runner.execute("CREATE TABLE t2 AS SELECT 5 AS x")
        runner.execute("ROLLBACK")
        with pytest.raises(Exception):
            runner.execute("SELECT * FROM t2")

    def test_rollback_restores_dropped_table(self, runner):
        runner.execute("START TRANSACTION")
        runner.execute("DROP TABLE t")
        runner.execute("ROLLBACK")
        assert runner.execute("SELECT count(*) FROM t").rows == [(2,)]

    def test_read_only_blocks_writes(self, runner):
        runner.execute("START TRANSACTION READ ONLY")
        with pytest.raises(Exception, match="READ ONLY"):
            runner.execute("UPDATE t SET v = 0")
        runner.execute("ROLLBACK")

    def test_nested_begin_rejected(self, runner):
        runner.execute("START TRANSACTION")
        with pytest.raises(Exception, match="already in progress"):
            runner.execute("START TRANSACTION")
        runner.execute("ROLLBACK")

    def test_commit_without_txn_rejected(self, runner):
        with pytest.raises(Exception, match="no transaction"):
            runner.execute("COMMIT")

    def test_multi_table_rollback(self, runner):
        runner.execute("CREATE TABLE u AS SELECT 7 AS a")
        runner.execute("START TRANSACTION")
        runner.execute("INSERT INTO t VALUES (3, 30)")
        runner.execute("UPDATE u SET a = 8")
        runner.execute("ROLLBACK")
        assert runner.execute("SELECT count(*) FROM t").rows == [(2,)]
        assert runner.execute("SELECT a FROM u").rows == [(7,)]


# --------------------------------------------------------------------------- #
# access control
# --------------------------------------------------------------------------- #


class TestAccessControl:
    def make_runner(self, rules):
        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.metadata import Session
        from trino_tpu.spi.security import RuleBasedAccessControl

        ac = RuleBasedAccessControl.from_config({"tables": rules})
        r = LocalQueryRunner(
            Session(catalog="memory", schema="default", user="alice"),
            access_control=ac,
        )
        r.register_catalog("memory", MemoryConnector())
        return r

    def test_select_denied_without_rule(self):
        r = self.make_runner([
            {"user": "bob", "privileges": ["SELECT"]},
        ])
        # alice can't even create (OWNERSHIP missing) — use a bob-owned setup
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("CREATE TABLE t AS SELECT 1 AS x")

    def test_select_allowed_with_rule(self):
        r = self.make_runner([
            {"user": "alice", "privileges": ["OWNERSHIP", "SELECT", "INSERT"]},
        ])
        r.execute("CREATE TABLE t AS SELECT 1 AS x")
        assert r.execute("SELECT x FROM t").rows == [(1,)]

    def test_insert_denied(self):
        from trino_tpu.spi.security import RuleBasedAccessControl

        r = self.make_runner([
            {"user": "alice", "privileges": ["OWNERSHIP", "SELECT", "INSERT"]},
        ])
        r.execute("CREATE TABLE t AS SELECT 1 AS x")
        r.access_control = RuleBasedAccessControl.from_config(
            {"tables": [{"user": "alice", "privileges": ["SELECT"]}]}
        )
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("INSERT INTO t VALUES (2)")

    def test_delete_requires_privilege(self):
        r = self.make_runner([
            {"user": "alice", "privileges": ["OWNERSHIP", "SELECT"]},
        ])
        r.execute("CREATE TABLE t AS SELECT 1 AS x")
        # OWNERSHIP implies everything in this model — narrow to a table rule
        r2_rules = [{"user": "alice", "table": "t", "privileges": ["SELECT"]}]
        from trino_tpu.spi.security import RuleBasedAccessControl

        r.access_control = RuleBasedAccessControl.from_config({"tables": r2_rules})
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("DELETE FROM t")

    def test_password_authenticator(self):
        from trino_tpu.spi.security import (
            AuthenticationError,
            PasswordAuthenticator,
        )

        auth = PasswordAuthenticator()
        auth.add_user("alice", "secret")
        auth.authenticate("alice", "secret")
        with pytest.raises(AuthenticationError):
            auth.authenticate("alice", "wrong")
        with pytest.raises(AuthenticationError):
            auth.authenticate("mallory", "secret")


class TestReviewRegressions:
    """Review findings: MERGE source reads, endpoint auth, isolation parse,
    user propagation."""

    def test_merge_source_select_checked(self):
        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.metadata import Session
        from trino_tpu.spi.security import RuleBasedAccessControl

        r = LocalQueryRunner(Session(catalog="memory", schema="default", user="alice"))
        r.register_catalog("memory", MemoryConnector())
        r.execute("CREATE TABLE tgt AS SELECT 1 AS id, 'x' AS data")
        r.execute("CREATE TABLE secret AS SELECT 1 AS id, 'classified' AS data")
        r.access_control = RuleBasedAccessControl.from_config(
            {"tables": [{"user": "alice", "table": "tgt",
                         "privileges": ["SELECT", "INSERT", "UPDATE", "DELETE"]}]}
        )
        with pytest.raises(Exception, match="Access Denied"):
            r.execute(
                "MERGE INTO tgt a USING secret d ON a.id = d.id "
                "WHEN MATCHED THEN UPDATE SET data = d.data"
            )

    def test_isolation_levels_parse(self):
        from trino_tpu.sql import parse_statement

        for text, expect in [
            ("START TRANSACTION ISOLATION LEVEL READ COMMITTED", "READ COMMITTED"),
            ("START TRANSACTION ISOLATION LEVEL READ UNCOMMITTED", "READ UNCOMMITTED"),
            ("START TRANSACTION ISOLATION LEVEL REPEATABLE READ", "REPEATABLE READ"),
            ("START TRANSACTION ISOLATION LEVEL SERIALIZABLE, READ ONLY", "SERIALIZABLE"),
        ]:
            stmt = parse_statement(text)
            assert stmt.isolation == expect
        stmt = parse_statement("START TRANSACTION ISOLATION LEVEL SERIALIZABLE, READ ONLY")
        assert stmt.read_only

    def test_user_propagates_through_query_manager(self):
        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.runtime.query_manager import QueryManager, QueryState
        from trino_tpu.metadata import Session
        from trino_tpu.spi.security import RuleBasedAccessControl

        r = LocalQueryRunner(Session(catalog="memory", schema="default", user="admin"))
        r.register_catalog("memory", MemoryConnector())
        r.execute("CREATE TABLE t AS SELECT 1 AS x")
        r.access_control = RuleBasedAccessControl.from_config(
            {"tables": [{"user": "admin", "privileges": ["OWNERSHIP"]},
                        {"user": "bob", "privileges": []}]}
        )
        qm = QueryManager(r.execute)
        ok = qm.submit("SELECT x FROM t", user="admin")
        denied = qm.submit("SELECT x FROM t", user="bob")
        assert ok.wait_done(10) and ok.state == QueryState.FINISHED
        assert denied.wait_done(10) and denied.state == QueryState.FAILED
        assert "Access Denied" in denied.error


class TestSecondReviewRegressions:
    """Round-2 review findings: EXPLAIN ANALYZE access, txn schema restore,
    idle-expiry undo, metadata filtering."""

    def test_explain_analyze_checks_access(self):
        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.metadata import Session
        from trino_tpu.spi.security import RuleBasedAccessControl

        r = LocalQueryRunner(Session(catalog="memory", schema="default", user="alice"))
        r.register_catalog("memory", MemoryConnector())
        r.execute("CREATE TABLE secret AS SELECT 1 AS x")
        r.access_control = RuleBasedAccessControl.from_config({"tables": []})
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("EXPLAIN ANALYZE SELECT * FROM secret")

    def test_rollback_restores_schema_after_drop_recreate(self, runner):
        runner.execute("START TRANSACTION")
        runner.execute("DROP TABLE t")
        runner.execute("CREATE TABLE t AS SELECT 'other' AS different_col")
        runner.execute("ROLLBACK")
        got = runner.execute("SELECT id, v FROM t ORDER BY id").rows
        assert got == [(1, 10), (2, 20)]

    def test_idle_expiry_rolls_back(self, runner):
        runner.transactions._idle_timeout = 0.05
        runner.execute("START TRANSACTION")
        runner.execute("UPDATE t SET v = 999 WHERE id = 1")
        time.sleep(0.1)
        # next begin() expires the idle txn and must restore pre-images
        runner.transactions.begin()
        assert runner.execute("SELECT v FROM t WHERE id = 1").rows == [(10,)]

    def test_show_catalogs_and_tables_filtered(self):
        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.metadata import Session
        from trino_tpu.spi.security import RuleBasedAccessControl

        r = LocalQueryRunner(Session(catalog="memory", schema="default", user="alice"))
        r.register_catalog("memory", MemoryConnector())
        r.execute("CREATE TABLE visible AS SELECT 1 AS x")
        r.execute("CREATE TABLE hidden AS SELECT 1 AS x")
        r.access_control = RuleBasedAccessControl.from_config(
            {"tables": [{"user": "alice", "table": "visible", "privileges": ["SELECT"]}]}
        )
        assert r.execute("SHOW TABLES").rows == [("visible",)]
        assert r.execute("SHOW CATALOGS").rows == [("memory",)]


class TestThirdReviewRegressions:
    def test_expired_txn_write_rejected_and_session_recovers(self, runner):
        runner.transactions._idle_timeout = 0.05
        runner.execute("START TRANSACTION")
        runner.execute("UPDATE t SET v = 999 WHERE id = 1")
        time.sleep(0.1)
        runner.transactions.begin()  # expires + rolls back the idle txn
        with pytest.raises(Exception, match="idle-expired"):
            runner.execute("UPDATE t SET v = 777 WHERE id = 1")
        # the write did NOT apply and the session is out of txn mode
        assert runner.execute("SELECT v FROM t WHERE id = 1").rows == [(10,)]
        runner.execute("START TRANSACTION")  # recovers
        runner.execute("ROLLBACK")

    def test_failed_commit_leaves_txn_mode(self, runner):
        runner.transactions._idle_timeout = 0.05
        runner.execute("START TRANSACTION")
        time.sleep(0.1)
        runner.transactions.begin()
        with pytest.raises(Exception):
            runner.execute("COMMIT")
        runner.execute("START TRANSACTION")  # must not raise
        runner.execute("ROLLBACK")

    def test_show_columns_denied_table(self):
        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.metadata import Session
        from trino_tpu.spi.security import RuleBasedAccessControl

        r = LocalQueryRunner(Session(catalog="memory", schema="default", user="alice"))
        r.register_catalog("memory", MemoryConnector())
        r.execute("CREATE TABLE hidden AS SELECT 1 AS secret_col")
        r.access_control = RuleBasedAccessControl.from_config({"tables": []})
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("SHOW COLUMNS FROM hidden")


class TestJwtAuthentication:
    """HS256 JWT bearer auth (ref: server/security/jwt/JwtAuthenticator.java +
    the authenticator-chain ordering of AuthenticationFilter)."""

    def _auth(self, **kw):
        from trino_tpu.spi.security import JwtAuthenticator

        return JwtAuthenticator(secret=b"test-secret-key", **kw)

    def test_round_trip(self):
        auth = self._auth()
        token = auth.issue("alice")
        assert auth.authenticate_token(token) == "alice"

    def test_bad_signature_rejected(self):
        from trino_tpu.spi.security import AuthenticationError, JwtAuthenticator

        token = self._auth().issue("alice")
        other = JwtAuthenticator(secret=b"different-secret")
        with pytest.raises(AuthenticationError, match="signature"):
            other.authenticate_token(token)

    def test_alg_none_rejected(self):
        import json

        from trino_tpu.spi.security import AuthenticationError

        auth = self._auth()
        h = auth._b64url_encode(json.dumps({"alg": "none"}).encode())
        p = auth._b64url_encode(json.dumps({"sub": "mallory"}).encode())
        with pytest.raises(AuthenticationError, match="alg"):
            auth.authenticate_token(f"{h}.{p}.")

    def test_expiry_and_nbf(self):
        import time

        from trino_tpu.spi.security import AuthenticationError

        auth = self._auth()
        expired = auth.issue("alice", ttl_secs=-3600)
        with pytest.raises(AuthenticationError, match="expired"):
            auth.authenticate_token(expired)
        future = auth.issue("alice", nbf=int(time.time()) + 3600)
        with pytest.raises(AuthenticationError, match="not yet valid"):
            auth.authenticate_token(future)

    def test_issuer_audience(self):
        from trino_tpu.spi.security import AuthenticationError

        auth = self._auth(issuer="idp", audience="trino")
        token = auth.issue("alice")
        assert auth.authenticate_token(token) == "alice"
        stranger = self._auth(issuer="other-idp", audience="trino")
        with pytest.raises(AuthenticationError, match="issuer"):
            auth.authenticate_token(stranger.issue("alice"))

    def test_coordinator_bearer_flow(self, tpch_tiny):
        from trino_tpu.client import ClientError, StatementClient
        from trino_tpu.server import CoordinatorServer
        from trino_tpu.spi.security import JwtAuthenticator

        auth = JwtAuthenticator(secret=b"cluster-secret")
        srv = CoordinatorServer(tpch_tiny, jwt_authenticator=auth).start()
        try:
            token = auth.issue("alice")
            client = StatementClient(f"http://{srv.address}", token=token)
            res = client.execute("SELECT count(*) FROM nation")
            assert res.rows == [[25]] or res.rows == [(25,)]
            bad = StatementClient(f"http://{srv.address}", token="not.a.jwt")
            with pytest.raises(Exception):
                bad.execute("SELECT 1")
            anon = StatementClient(f"http://{srv.address}")
            with pytest.raises(Exception):
                anon.execute("SELECT 1")
        finally:
            srv.stop()


class TestGrantRevoke:
    """GRANT/REVOKE DCL (ref: execution/GrantTask.java + RevokeTask.java,
    ownership-gated like checkCanGrantTablePrivilege)."""

    def _runner(self):
        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.metadata import Session
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.spi.security import RuleBasedAccessControl

        r = LocalQueryRunner(Session(catalog="memory", schema="default",
                                     user="admin"))
        r.register_catalog("memory", MemoryConnector())
        r.access_control = RuleBasedAccessControl.from_config(
            {"tables": [{"user": "admin", "privileges":
                         ["OWNERSHIP", "SELECT", "INSERT", "UPDATE", "DELETE"]}]}
        )
        r.execute("CREATE TABLE memory.default.tt AS SELECT 1 AS x")
        return r

    def test_grant_enables_select(self, runner_unused=None):
        r = self._runner()
        r.session.user = "bob"
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("SELECT * FROM memory.default.tt")
        r.session.user = "admin"
        r.execute("GRANT SELECT ON memory.default.tt TO bob")
        r.session.user = "bob"
        assert r.execute("SELECT * FROM memory.default.tt").rows == [(1,)]
        # SELECT alone does not confer INSERT
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("INSERT INTO memory.default.tt VALUES (2)")

    def test_revoke_removes(self):
        r = self._runner()
        r.execute("GRANT ALL PRIVILEGES ON TABLE memory.default.tt TO bob")
        r.session.user = "bob"
        r.execute("INSERT INTO memory.default.tt VALUES (2)")
        r.session.user = "admin"
        r.execute("REVOKE INSERT ON memory.default.tt FROM bob")
        r.session.user = "bob"
        with pytest.raises(Exception, match="Access Denied"):
            r.execute("INSERT INTO memory.default.tt VALUES (3)")
        assert len(r.execute("SELECT * FROM memory.default.tt").rows) == 2

    def test_non_owner_cannot_grant(self):
        r = self._runner()
        r.session.user = "mallory"
        with pytest.raises(Exception, match="Cannot grant"):
            r.execute("GRANT SELECT ON memory.default.tt TO mallory")
