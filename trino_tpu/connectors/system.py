"""``system`` catalog: SQL-queryable live engine state + procedures.

Reference blueprint: core/trino-main/src/main/java/io/trino/connector/system/
(SystemConnector, GlobalSystemConnector — ``system.runtime.queries`` /
``tasks`` / ``nodes`` backed by QueryManager/TaskManager/NodeManager
snapshots, ``system.metrics`` over JMX beans, and the
``system.runtime.kill_query`` procedure; SURVEY.md §5.5). The engine
dogfoods its own query language over its own runtime: every table is a
zero-copy-ish snapshot assembled at scan time, flowing through the same
compiled pipeline as any data scan.

Consistency caveats (documented in ARCHITECTURE.md "System catalog"):
snapshots are eventually consistent — a scan sees each source's state at
the moment its rows are built, with no cross-source barrier; the tasks
read is lock-free against running workers (one registry lock per manager,
never blocking task execution).

Wiring: the connector reads a :class:`SystemContext` owned by the Metadata
facade. ``QueryManager`` self-registers into the runner's context at
construction; ``CoordinatorServer`` adds its node manager and optional
persistent history store; worker ``TaskManager`` instances register into a
process-wide set (``server.worker.all_task_managers``).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
)
from ..spi.page import Page
from ..spi.types import BIGINT, BOOLEAN, DOUBLE, VarcharType
from .synthetic import synthetic_page

VARCHAR = VarcharType()

CATALOG_NAME = "system"


@dataclass
class SystemContext:
    """Late-bound engine references the system tables snapshot.

    Every field is optional: an embedded LocalQueryRunner without a
    QueryManager still serves ``nodes``/``metrics``/``flight_events``;
    query-backed tables are empty until a manager attaches (QueryManager
    auto-wires itself when built over a runner's ``execute``).
    """

    query_manager: Optional[object] = None
    node_manager: Optional[object] = None
    history_store: Optional[object] = None
    # memory arbitration plane (runtime/memory.py): the QueryManager
    # registers its pool + ClusterMemoryManager here at construction
    memory_pool: Optional[object] = None
    cluster_memory: Optional[object] = None
    # cluster observability plane (runtime/clusterobs.py): the coordinator
    # attaches its federated-metrics fold here; None = empty cluster tables
    cluster_metrics: Optional[object] = None
    # extra task snapshot providers beyond the process-wide worker registry
    task_sources: List[object] = field(default_factory=list)


# table name -> ordered column metadata, per schema (a slice of the
# reference's SystemTable registry)
TABLES: Dict[str, Dict[str, Tuple[ColumnMetadata, ...]]] = {
    "runtime": {
        "queries": (
            ColumnMetadata("query_id", VARCHAR),
            ColumnMetadata("state", VARCHAR),
            ColumnMetadata("user", VARCHAR),
            ColumnMetadata("source", VARCHAR),
            ColumnMetadata("query", VARCHAR),
            ColumnMetadata("resource_group", VARCHAR),
            ColumnMetadata("error_type", VARCHAR),
            ColumnMetadata("created", DOUBLE),       # epoch seconds
            ColumnMetadata("ended", DOUBLE),         # NULL while running
            ColumnMetadata("elapsed_ms", BIGINT),
            ColumnMetadata("cpu_ms", BIGINT),
            ColumnMetadata("rows", BIGINT),
            ColumnMetadata("device_busy_ms", BIGINT),
            ColumnMetadata("host_wait_ms", BIGINT),
            ColumnMetadata("compile_ms", BIGINT),
        ),
        "query_history": (
            ColumnMetadata("query_id", VARCHAR),
            ColumnMetadata("state", VARCHAR),
            ColumnMetadata("user", VARCHAR),
            ColumnMetadata("query", VARCHAR),
            ColumnMetadata("created", DOUBLE),
            ColumnMetadata("ended", DOUBLE),
            ColumnMetadata("elapsed_ms", BIGINT),
            ColumnMetadata("cpu_ms", BIGINT),
            ColumnMetadata("rows", BIGINT),
            ColumnMetadata("error_type", VARCHAR),
        ),
        "tasks": (
            ColumnMetadata("node_id", VARCHAR),
            ColumnMetadata("task_id", VARCHAR),
            ColumnMetadata("query_id", VARCHAR),
            ColumnMetadata("state", VARCHAR),
            ColumnMetadata("error", VARCHAR),
            ColumnMetadata("queued_ms", BIGINT),
            ColumnMetadata("run_ms", BIGINT),
            ColumnMetadata("buffered_pages", BIGINT),
        ),
        "nodes": (
            ColumnMetadata("node_id", VARCHAR),
            ColumnMetadata("http_uri", VARCHAR),
            ColumnMetadata("node_version", VARCHAR),
            ColumnMetadata("coordinator", BOOLEAN),
            ColumnMetadata("state", VARCHAR),
            ColumnMetadata("device", VARCHAR),
            ColumnMetadata("last_seen_age_ms", BIGINT),
        ),
        "task_attempts": (
            ColumnMetadata("query_id", VARCHAR),
            ColumnMetadata("fragment_id", BIGINT),
            ColumnMetadata("partition_id", BIGINT),
            ColumnMetadata("attempt", BIGINT),
            ColumnMetadata("worker", VARCHAR),
            ColumnMetadata("outcome", VARCHAR),   # ok|failed|timeout|stale
            ColumnMetadata("error_category", VARCHAR),
            ColumnMetadata("speculative", BOOLEAN),
            ColumnMetadata("elapsed_ms", BIGINT),
        ),
        "flight_events": (
            ColumnMetadata("kind", VARCHAR),
            ColumnMetadata("cat", VARCHAR),
            ColumnMetadata("phase", VARCHAR),
            ColumnMetadata("ts", BIGINT),   # microseconds (monotonic clock)
            ColumnMetadata("dur", BIGINT),  # microseconds; 0 for non-X events
            ColumnMetadata("tid", BIGINT),
            ColumnMetadata("args", VARCHAR),
        ),
        "resource_groups": (
            ColumnMetadata("id", VARCHAR),
            ColumnMetadata("parent", VARCHAR),
            ColumnMetadata("hard_concurrency_limit", BIGINT),
            ColumnMetadata("max_queued", BIGINT),
            ColumnMetadata("scheduling_weight", BIGINT),
            ColumnMetadata("soft_memory_limit_bytes", BIGINT),  # NULL = none
            ColumnMetadata("memory_usage_bytes", BIGINT),
            ColumnMetadata("running", BIGINT),
            ColumnMetadata("queued", BIGINT),
        ),
        "memory_pool": (
            ColumnMetadata("node_id", VARCHAR),
            ColumnMetadata("pool", VARCHAR),
            ColumnMetadata("max_bytes", BIGINT),        # 0 = unbounded
            ColumnMetadata("reserved_bytes", BIGINT),
            ColumnMetadata("revocable_bytes", BIGINT),
            ColumnMetadata("peak_bytes", BIGINT),
            ColumnMetadata("blocked_queries", BIGINT),
            ColumnMetadata("low_memory_kills", BIGINT),  # NULL on workers
        ),
        # warm-path cache plane snapshot (runtime/cachestore.py): one row
        # per tier (plan / result / fragment)
        "caches": (
            ColumnMetadata("tier", VARCHAR),
            ColumnMetadata("entries", BIGINT),
            ColumnMetadata("bytes", BIGINT),
            ColumnMetadata("hits", BIGINT),
            ColumnMetadata("misses", BIGINT),
            ColumnMetadata("evictions", BIGINT),
            ColumnMetadata("invalidations", BIGINT),
        ),
        # persisted query-profile bundles (cluster observability plane;
        # $TRINO_TPU_QUERY_PROFILE_DIR — empty when unset)
        "query_profiles": (
            ColumnMetadata("query_id", VARCHAR),
            ColumnMetadata("state", VARCHAR),
            ColumnMetadata("user", VARCHAR),
            ColumnMetadata("query", VARCHAR),
            ColumnMetadata("wall_ms", BIGINT),
            ColumnMetadata("stages", BIGINT),
            ColumnMetadata("diagnosis", VARCHAR),
            ColumnMetadata("created", DOUBLE),
            ColumnMetadata("path", VARCHAR),
        ),
        # ANN serving tier: measured recall@k of centroid-pruned vector
        # top-k against the periodic exact oracle (ops/tensor.py ring;
        # empty until ann_recall_sample_rate draws a sample)
        "ann_recall": (
            ColumnMetadata("table_name", VARCHAR),
            ColumnMetadata("k", BIGINT),
            ColumnMetadata("nprobe", BIGINT),
            ColumnMetadata("recall", DOUBLE),
            ColumnMetadata("probed_splits", BIGINT),
            ColumnMetadata("total_splits", BIGINT),
        ),
        # per-plan-node cardinality actuals of recent queries (the
        # statistics feedback plane's bounded ring; runtime/statstore.py)
        # kernel cost plane (runtime/kernelcost.py): per-program XLA
        # cost-model attribution of recent kernel_cost-enabled queries;
        # the node column is "" for local rows and the announcing worker's
        # id for rows folded from the federated plane
        "kernel_costs": (
            ColumnMetadata("node", VARCHAR),
            ColumnMetadata("query_id", VARCHAR),
            ColumnMetadata("plan_node", VARCHAR),
            ColumnMetadata("label", VARCHAR),
            ColumnMetadata("program_key", VARCHAR),
            ColumnMetadata("platform", VARCHAR),
            ColumnMetadata("flops", DOUBLE),            # NULL = unavailable
            ColumnMetadata("bytes_accessed", DOUBLE),   # NULL = unavailable
            ColumnMetadata("peak_hbm_bytes", BIGINT),
            ColumnMetadata("arithmetic_intensity", DOUBLE),
            ColumnMetadata("classification", VARCHAR),  # memory-/compute-bound
            ColumnMetadata("status", VARCHAR),  # ok | cost_unavailable
            ColumnMetadata("ts", DOUBLE),       # epoch seconds
        ),
        # host-path observability plane (runtime/hostprof.py): collapsed
        # wall-clock sampling-profiler stacks per named engine thread,
        # heaviest-first; empty until the sampler has run (host_profile
        # session property or $TRINO_TPU_HOSTPROF)
        "host_profile": (
            ColumnMetadata("thread", VARCHAR),
            ColumnMetadata("stack", VARCHAR),     # root;...;leaf collapsed
            ColumnMetadata("samples", BIGINT),
            ColumnMetadata("share", DOUBLE),      # fraction of all samples
        ),
        "operator_stats": (
            ColumnMetadata("query_id", VARCHAR),
            ColumnMetadata("fragment", BIGINT),       # NULL on local runs
            ColumnMetadata("node_id", BIGINT),        # preorder position
            ColumnMetadata("plan_node", VARCHAR),
            ColumnMetadata("estimated_rows", DOUBLE),  # NULL = no estimate
            ColumnMetadata("actual_rows", BIGINT),
            ColumnMetadata("input_rows", BIGINT),
            ColumnMetadata("output_bytes", BIGINT),
            ColumnMetadata("null_fraction", DOUBLE),
            ColumnMetadata("build_rows", BIGINT),      # joins only
            ColumnMetadata("dynamic_filter_selectivity", DOUBLE),
            ColumnMetadata("q_error", DOUBLE),
            ColumnMetadata("ts", DOUBLE),              # epoch seconds
        ),
    },
    "optimizer": {
        # the history-based stats store: estimate-vs-actual per recorded
        # plan-shape key (structural subtree fingerprint or canonical leaf)
        "stats_history": (
            ColumnMetadata("key", VARCHAR),
            ColumnMetadata("plan_fingerprint", VARCHAR),
            ColumnMetadata("plan_node", VARCHAR),
            ColumnMetadata("table_name", VARCHAR),     # scans only
            ColumnMetadata("estimated_rows", DOUBLE),
            ColumnMetadata("actual_rows", DOUBLE),
            ColumnMetadata("q_error", DOUBLE),
            ColumnMetadata("runs", BIGINT),
            ColumnMetadata("updated_at", DOUBLE),
        ),
    },
    "metrics": {
        "counters": (
            ColumnMetadata("name", VARCHAR),
            ColumnMetadata("labels", VARCHAR),
            ColumnMetadata("kind", VARCHAR),  # counter | gauge
            ColumnMetadata("value", DOUBLE),
            ColumnMetadata("help", VARCHAR),
        ),
        "histograms": (
            ColumnMetadata("name", VARCHAR),
            ColumnMetadata("labels", VARCHAR),
            ColumnMetadata("le", DOUBLE),  # +Inf bucket -> inf
            ColumnMetadata("cumulative_count", BIGINT),
            ColumnMetadata("sum", DOUBLE),
            ColumnMetadata("count", BIGINT),
            # estimated quantiles by exponential-bucket interpolation
            # (metrics.histogram_quantile); NULL while the series is empty
            ColumnMetadata("p50", DOUBLE),
            ColumnMetadata("p95", DOUBLE),
            ColumnMetadata("p99", DOUBLE),
            ColumnMetadata("help", VARCHAR),
        ),
        # federated per-node series folded from announcement snapshots
        # (cluster observability plane; empty without a coordinator fold)
        "cluster_counters": (
            ColumnMetadata("name", VARCHAR),
            ColumnMetadata("labels", VARCHAR),
            ColumnMetadata("node", VARCHAR),
            ColumnMetadata("kind", VARCHAR),  # counter | gauge
            ColumnMetadata("value", DOUBLE),
            ColumnMetadata("help", VARCHAR),
        ),
        "cluster_histograms": (
            ColumnMetadata("name", VARCHAR),
            ColumnMetadata("labels", VARCHAR),
            ColumnMetadata("node", VARCHAR),
            ColumnMetadata("le", DOUBLE),  # +Inf bucket -> inf
            ColumnMetadata("cumulative_count", BIGINT),
            ColumnMetadata("sum", DOUBLE),
            ColumnMetadata("count", BIGINT),
            ColumnMetadata("help", VARCHAR),
        ),
    },
}


def device_kind() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — table degrades, never fails
        return "unknown"


def _ms(secs: Optional[float]) -> Optional[int]:
    return None if secs is None else int(secs * 1000)


class SystemConnector(Connector):
    """One per Metadata facade; every table reads live engine state."""

    name = CATALOG_NAME
    # warm-path cache plane: live engine snapshots must NEVER serve stale
    # (a monitoring dashboard polling system.runtime.* wants NOW, not a
    # TTL-old replay) — runtime/cachestore.py bypasses on this attr
    cache_bypass = True

    def __init__(self, context: Optional[SystemContext] = None):
        self.context = context or SystemContext()
        self._meta = _SystemMetadata()
        self._splits = _SystemSplits()
        self._pages = _SystemPageSource(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    # ------------------------------------------------------------- snapshots

    def _rows(self, schema: str, table: str) -> List[tuple]:
        fn = getattr(self, f"_rows_{schema}_{table}", None)
        if fn is None:
            raise ValueError(f"unknown system table: {schema}.{table}")
        return fn()

    def _rows_runtime_queries(self) -> List[tuple]:
        mgr = self.context.query_manager
        if mgr is None:
            return []
        rows = []
        for q in mgr.list_queries():
            times = (q.query_stats or {}).get("times", {})
            rows.append((
                q.query_id,
                q.state.value,
                q.user,
                q.source or None,
                q.sql,
                q.resource_group or None,
                q.error_type,
                q.stats.create_time,
                q.stats.end_time,
                _ms(q.stats.elapsed),
                _ms(q.stats.cpu_time),
                q.stats.rows,
                _ms(times.get("device_busy_secs", 0.0)),
                _ms(times.get("host_wait_secs", 0.0)),
                _ms(times.get("compile_secs", 0.0)),
            ))
        rows.sort(key=lambda r: (r[7], r[0]))
        return rows

    def _rows_runtime_query_history(self) -> List[tuple]:
        store = self.context.history_store
        if store is None:
            return []
        rows = []
        for ev in store.records():
            rows.append((
                ev.get("queryId"),
                ev.get("state"),
                ev.get("user"),
                ev.get("query"),
                ev.get("createTime"),
                ev.get("endTime"),
                _ms(ev.get("elapsedSeconds")),
                _ms(ev.get("cpuSeconds")),
                ev.get("outputRows"),
                ev.get("errorType"),
            ))
        return rows

    def _rows_runtime_tasks(self) -> List[tuple]:
        from ..server.worker import all_task_managers

        sources = list(all_task_managers()) + list(self.context.task_sources)
        rows = []
        for tm in sources:
            try:
                snaps = tm.snapshot()
            except Exception:  # noqa: BLE001 — one bad source can't kill the scan
                continue
            for s in snaps:
                rows.append((
                    s.get("nodeId"),
                    s.get("taskId"),
                    s.get("queryId"),
                    s.get("state"),
                    s.get("error"),
                    _ms(s.get("queuedSecs")),
                    _ms(s.get("runSecs")),
                    s.get("bufferedPages"),
                ))
        rows.sort(key=lambda r: (r[0] or "", r[1] or ""))
        return rows

    def _rows_runtime_nodes(self) -> List[tuple]:
        mgr = self.context.node_manager
        now = time.time()
        if mgr is None:
            # embedded single-process runner: this process IS the cluster
            from .. import __version__

            return [(
                "local", None, __version__, True, "ACTIVE", device_kind(), 0,
            )]
        return [
            (
                n.node_id,
                n.uri or None,
                n.version or None,
                bool(n.coordinator),
                n.state.value,
                n.device or None,
                max(int((now - n.last_heartbeat) * 1000), 0),
            )
            for n in mgr.all_nodes()
        ]

    def _rows_runtime_task_attempts(self) -> List[tuple]:
        """FTE scheduler attempt history (bounded process-wide ring — the
        task-attempt analogue of query_history; ref: the scheduler's task
        lifecycle events surfaced through EXPLAIN/ system.runtime)."""
        from ..runtime.fte_scheduler import attempt_log

        return [
            (
                r.get("query_id"),
                r.get("fragment"),
                r.get("partition"),
                r.get("attempt"),
                r.get("worker"),
                r.get("outcome"),
                r.get("category") or None,
                bool(r.get("speculative")),
                r.get("elapsed_ms"),
            )
            for r in attempt_log()
        ]

    def _rows_runtime_resource_groups(self) -> List[tuple]:
        """Live admission state per materialized group (ref: the reference's
        ResourceGroupInfo rows behind /v1/resourceGroupState)."""
        mgr = self.context.query_manager
        groups = getattr(mgr, "resource_groups", None) if mgr else None
        flat = getattr(groups, "flat_info", None)
        if flat is None:
            return []
        return [
            (
                row.get("id"),
                row.get("parent"),
                row.get("hardConcurrencyLimit"),
                row.get("maxQueued"),
                row.get("schedulingWeight"),
                row.get("softMemoryLimitBytes"),
                row.get("memoryUsageBytes", 0),
                row.get("running", 0),
                row.get("queued", 0),
            )
            for row in flat()
        ]

    def _rows_runtime_memory_pool(self) -> List[tuple]:
        """Pool standing per node: the local (coordinator) pool first, then
        every announced worker's heartbeat-reported memory."""
        rows: List[tuple] = []
        pool = self.context.memory_pool
        if pool is None:
            mgr = self.context.query_manager
            pool = getattr(mgr, "memory_pool", None) if mgr else None
        cluster = self.context.cluster_memory
        if pool is not None:
            s = pool.snapshot()
            rows.append((
                "local",
                s.get("pool"),
                s.get("maxBytes", 0),
                s.get("reservedBytes", 0),
                s.get("revocableBytes", 0),
                s.get("peakBytes", 0),
                s.get("blockedQueries", 0),
                getattr(cluster, "kills_total", 0) if cluster else 0,
            ))
        nmgr = self.context.node_manager
        if nmgr is not None:
            for n in nmgr.all_nodes():
                if getattr(n, "coordinator", False):
                    continue  # the coordinator's pool is the "local" row
                rows.append((
                    n.node_id,
                    "general",
                    getattr(n, "pool_max_bytes", 0),
                    getattr(n, "reserved_bytes", 0),
                    getattr(n, "revocable_bytes", 0),
                    getattr(n, "peak_bytes", 0),
                    getattr(n, "blocked_queries", 0),
                    None,
                ))
        return rows

    def _rows_runtime_caches(self) -> List[tuple]:
        from ..runtime.cachestore import CACHES

        return CACHES.stats_rows()

    def _rows_runtime_ann_recall(self) -> List[tuple]:
        from ..ops import tensor as T

        return list(T.ann_recall_rows())

    def _rows_runtime_flight_events(self) -> List[tuple]:
        from ..runtime.observability import RECORDER

        rows = []
        for ev in RECORDER.events():
            args = ev.get("args")
            rows.append((
                ev.get("name"),
                ev.get("cat"),
                ev.get("ph"),
                ev.get("ts"),
                int(ev.get("dur", 0)),
                ev.get("tid"),
                json.dumps(args) if args else None,
            ))
        return rows

    def _rows_metrics_counters(self) -> List[tuple]:
        from ..runtime.metrics import REGISTRY

        rows = []
        for entry in REGISTRY.collect():
            if entry["type"] == "histogram":
                continue
            rows.append((
                entry["name"],
                json.dumps(entry["labels"]) if entry["labels"] else None,
                entry["type"],
                float(entry["value"]),
                entry["help"] or None,
            ))
        return rows

    def _rows_metrics_histograms(self) -> List[tuple]:
        from ..runtime.metrics import REGISTRY, histogram_quantile

        rows = []
        for entry in REGISTRY.collect():
            if entry["type"] != "histogram":
                continue
            labels = json.dumps(entry["labels"]) if entry["labels"] else None
            qs = [
                histogram_quantile(entry["buckets"], entry["count"], q)
                for q in (0.50, 0.95, 0.99)
            ]
            for bound, cum in entry["buckets"]:
                rows.append((
                    entry["name"], labels, bound, cum,
                    entry["sum"], entry["count"],
                    qs[0], qs[1], qs[2],
                    entry["help"] or None,
                ))
        return rows

    def _rows_runtime_query_profiles(self) -> List[tuple]:
        """Persisted query-profile bundles (cluster observability plane);
        empty rows until $TRINO_TPU_QUERY_PROFILE_DIR is configured."""
        from ..runtime.clusterobs import profile_store

        store = profile_store()
        if store is None:
            return []
        rows = []
        for p in store.list():
            rows.append((
                p.get("queryId"),
                p.get("state"),
                p.get("user") or None,
                p.get("query"),
                _ms(p.get("wallSecs")),
                len(p.get("stages") or {}),
                p.get("diagnosis"),
                p.get("createdAt"),
                p.get("_path"),
            ))
        rows.sort(key=lambda r: (r[7] or 0.0, r[0] or ""))
        return rows

    def _rows_metrics_cluster_counters(self) -> List[tuple]:
        cm = self.context.cluster_metrics
        if cm is None:
            return []
        from ..runtime.metrics import REGISTRY

        return cm.counters_rows(local_registry=REGISTRY)

    def _rows_metrics_cluster_histograms(self) -> List[tuple]:
        cm = self.context.cluster_metrics
        if cm is None:
            return []
        from ..runtime.metrics import REGISTRY

        return cm.histograms_rows(local_registry=REGISTRY)

    def _rows_runtime_kernel_costs(self) -> List[tuple]:
        """XLA cost-model attributions: this process's ledger plus rows
        folded from worker announcements (federated plane, TTL-pruned)."""
        from ..runtime import kernelcost

        def to_row(node: str, r: dict) -> tuple:
            peak = r.get("peak_hbm_bytes")
            return (
                node,
                r.get("query_id") or None,
                r.get("plan_node") or None,
                r.get("label"),
                r.get("key"),
                r.get("platform"),
                r.get("flops"),
                r.get("bytes_accessed"),
                int(peak) if peak is not None else None,
                r.get("arithmetic_intensity"),
                r.get("classification"),
                r.get("status"),
                r.get("ts"),
            )

        rows = [to_row("", r) for r in kernelcost.ledger_rows()]
        rows.extend(to_row(nid, r) for nid, r in kernelcost.federated_rows())
        rows.sort(key=lambda r: (r[12] or 0.0, r[0] or "", r[4] or ""))
        return rows

    def _rows_runtime_host_profile(self) -> List[tuple]:
        """Host-path sampling-profiler snapshot: collapsed stacks per named
        engine thread from the bounded sample ring (runtime/hostprof.py)."""
        from ..runtime.hostprof import PROFILER

        return list(PROFILER.profile_rows())

    def _rows_runtime_operator_stats(self) -> List[tuple]:
        """Recent per-plan-node cardinality actuals (the statistics feedback
        plane's bounded process ring; runtime/statstore.py)."""
        from ..runtime.statstore import operator_stats_log

        return [
            (
                r.get("query_id") or None,
                r.get("fragment"),
                r.get("node_id"),
                r.get("kind"),
                r.get("estimate"),
                r.get("actual"),
                r.get("input_rows"),
                r.get("bytes"),
                r.get("null_frac"),
                r.get("build_rows"),
                r.get("dyn_filter_sel"),
                r.get("qerror"),
                r.get("ts"),
            )
            for r in operator_stats_log()
        ]

    def _rows_optimizer_stats_history(self) -> List[tuple]:
        """The history-based stats store, live (file- or memory-backed)."""
        from ..runtime.statstore import load_history

        rows = []
        for key, ent in sorted(load_history().items()):
            rows.append((
                key,
                ent.get("plan") or None,
                ent.get("kind"),
                ent.get("table"),
                ent.get("estimate"),
                ent.get("actual"),
                ent.get("qerror"),
                int(ent.get("runs", 1)),
                ent.get("updated_at"),
            ))
        return rows


class _SystemMetadata(ConnectorMetadata):
    def list_schemas(self) -> List[str]:
        return sorted(TABLES)

    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        schemas = [schema] if schema else sorted(TABLES)
        return [
            SchemaTableName(s, t)
            for s in schemas
            if s in TABLES
            for t in sorted(TABLES[s])
        ]

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        cols = TABLES.get(name.schema, {}).get(name.table)
        if cols is None:
            return None
        return TableMetadata(name, tuple(cols))


class _SystemSplits(ConnectorSplitManager):
    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        st = handle.schema_table
        return [
            Split(
                table=handle, split_id=0, total_splits=1,
                info=(st.schema, st.table),
            )
        ]


class _SystemPageSource(ConnectorPageSourceProvider):
    def __init__(self, conn: SystemConnector):
        self.conn = conn

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        schema, table = split.info
        all_cols = TABLES[schema][table]
        rows = self.conn._rows(schema, table)
        return synthetic_page(all_cols, rows, column_indexes)


# --------------------------------------------------------------------------- #
# procedures (ref: io.trino.connector.system.KillQueryProcedure)
# --------------------------------------------------------------------------- #


def call_procedure(runner, parts: Tuple[str, ...], args: List[object]):
    """Dispatch CALL catalog.schema.proc(args) -> (column_names, rows).

    The only registry today is the system catalog's; connector-defined
    procedures would hook in here (spi Procedure analogue).
    """
    if len(parts) != 3 or parts[0] != CATALOG_NAME:
        raise ValueError(
            f"procedure not found: {'.'.join(parts)} "
            f"(procedures live in the system catalog, e.g. "
            f"system.runtime.kill_query)"
        )
    key = (parts[1], parts[2])
    if key == ("runtime", "kill_query"):
        if not 1 <= len(args) <= 2:
            raise ValueError("kill_query(query_id, message) takes 1-2 arguments")
        message = str(args[1]) if len(args) == 2 and args[1] is not None else ""
        return _kill_query(runner, str(args[0]), message)
    raise ValueError(f"procedure not found: {'.'.join(parts)}")


def _kill_query(runner, query_id: str, message: str):
    from ..runtime.query_manager import CancelResult, QueryNotFound

    ctx = runner.metadata.system_context
    mgr = ctx.query_manager
    if mgr is None:
        raise ValueError(
            "kill_query requires a query manager (submit through a "
            "QueryManager or the coordinator)"
        )
    target = mgr.get(query_id)
    if target is None:
        raise QueryNotFound(query_id)
    # authorization (ref: KillQueryProcedure -> checkCanKillQueryOwnedBy):
    # killing your own query is always allowed; killing another user's
    # query consults the access-control hook when the installed
    # implementation provides one
    user = runner._current_user()
    if target.user != user:
        hook = getattr(
            runner.access_control, "check_can_kill_query_owned_by", None
        )
        if hook is not None:
            hook(user, target.user)
    result = mgr.kill(query_id, message)  # raises QueryNotFound when unknown
    if result is CancelResult.TERMINAL:
        raise ValueError(f"query is not running: {query_id}")
    return ["result"], [(True,)]
