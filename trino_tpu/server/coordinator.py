"""Coordinator HTTP server: the client protocol + status APIs.

Reference blueprint: the REST surface of SURVEY.md §3.1/§2.6 —
QueuedStatementResource (`POST /v1/statement`, dispatcher/QueuedStatementResource.
java:172), ExecutingStatementResource (`GET /v1/statement/executing/{id}/{slug}/
{token}` with nextUri paging), QueryResource (`/v1/query`), plus /v1/info and
/v1/status. Wire shape follows docs/src/main/sphinx/develop/client-protocol.md:
each response carries columns, data, stats, and a nextUri until the query drains.

Implementation: stdlib ThreadingHTTPServer — the control plane is cold-path
Python by design (SURVEY.md §7: "Python for frontend/planner/coordinator");
pages per response are bounded like Trino's targetResultSize.
"""

from __future__ import annotations

import json
import threading
import datetime
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import urlparse

from .. import __version__
from .. import knobs
from ..runtime.query_manager import QueryManager, QueryState

PAGE_ROWS = 4096  # rows per protocol page (targetResultSize analogue)


class BadSessionHeader(ValueError):
    """A session-state request header failed to parse (-> HTTP 400)."""


def _json_value(v: Any, type_=None) -> Any:
    """Row value -> wire JSON, matching the reference client's decode rules
    (client/trino-client JsonDecodingUtils): dates/timestamps as their SQL
    text forms, decimals as exact-scale strings."""
    if isinstance(v, datetime.datetime):
        return v.isoformat(sep=" ")
    if isinstance(v, datetime.time):
        return v.isoformat()
    if isinstance(v, datetime.date):
        return v.isoformat()
    if v is not None and type_ is not None and getattr(type_, "name", "") == "decimal":
        return f"{v:.{type_.scale}f}"
    if isinstance(v, list):
        el_t = getattr(type_, "element", None)
        return [_json_value(x, el_t) for x in v]
    if isinstance(v, dict):
        kt, vt = getattr(type_, "key", None), getattr(type_, "value", None)
        return {_json_value(k, kt): _json_value(x, vt) for k, x in v.items()}
    if isinstance(v, tuple):
        fts = [ft for _, ft in getattr(type_, "fields", [])] or [None] * len(v)
        return [_json_value(x, ft) for x, ft in zip(v, fts)]
    return v


def _type_signature(type_) -> Dict:
    """Our Type -> Trino wire type + ClientTypeSignature
    (ref: client/trino-client ClientTypeSignature / TypeSignature text forms,
    StatementClientV1.java:75 consumers decode by these)."""
    if type_ is None:
        return {
            "type": "varchar",
            "typeSignature": {"rawType": "varchar", "arguments": [
                {"kind": "LONG", "value": 2147483647}
            ]},
        }
    name = type_.name
    args = []
    if name == "array":
        args = [{"kind": "TYPE", "value": _type_signature(type_.element)["typeSignature"]}]
    elif name == "map":
        args = [
            {"kind": "TYPE", "value": _type_signature(type_.key)["typeSignature"]},
            {"kind": "TYPE", "value": _type_signature(type_.value)["typeSignature"]},
        ]
    elif name == "row":
        args = [
            {
                "kind": "NAMED_TYPE",
                "value": {
                    "fieldName": ({"name": n} if n else None),
                    "typeSignature": _type_signature(ft)["typeSignature"],
                },
            }
            for n, ft in type_.fields
        ]
    if name == "decimal":
        args = [
            {"kind": "LONG", "value": type_.precision},
            {"kind": "LONG", "value": type_.scale},
        ]
    elif name == "varchar":
        length = getattr(type_, "length", None)
        args = [{"kind": "LONG", "value": 2147483647 if length is None else length}]
    elif name == "char":
        args = [{"kind": "LONG", "value": type_.length}]
    elif name in ("timestamp", "time", "timestamp with time zone"):
        args = [{"kind": "LONG", "value": type_.precision}]
    display = type_.display()
    if name == "varchar" and getattr(type_, "length", None) is None:
        display = "varchar"
    return {"type": display, "typeSignature": {"rawType": name, "arguments": args}}


class CoordinatorServer:
    """Embeds a query runner behind the REST protocol."""

    def __init__(self, runner, host: str = "127.0.0.1", port: int = 0,
                 resource_groups=None, authenticator=None,
                 jwt_authenticator=None, oauth2_authenticator=None,
                 history_path: Optional[str] = None, ha_lease=None,
                 fleet=None, node_id: Optional[str] = None,
                 front_port: Optional[int] = None):
        import os

        from ..runtime.nodes import InternalNodeManager

        from ..runtime.spool import FileSystemSpoolingManager

        from ..runtime.clusterobs import ClockSync, ClusterMetrics

        self.runner = runner
        self.manager = QueryManager(runner.execute, resource_groups=resource_groups)
        self.nodes = InternalNodeManager()
        # cluster observability plane: per-node clock offsets (heartbeat
        # RTT midpoints) + the federated metric fold. Always constructed
        # (cheap, empty); only announcement riders feed them.
        self.clock_sync = ClockSync()
        self.cluster_metrics = ClusterMetrics()
        # memory arbitration: the ClusterMemoryManager (built by the
        # QueryManager when a pool is configured) reads per-worker pool state
        # off THIS node manager's announcements
        if self.manager.cluster_memory is not None:
            self.manager.cluster_memory.node_manager = self.nodes
        # system catalog wiring: the QueryManager registered itself into the
        # runner's SystemContext at construction; nodes + persistent query
        # history attach here (system.runtime.nodes / query_history)
        self.history = None
        sys_ctx = getattr(runner.metadata, "system_context", None)
        if sys_ctx is not None:
            sys_ctx.node_manager = self.nodes
            sys_ctx.cluster_metrics = self.cluster_metrics
        history_path = history_path or knobs.env_path(
            "TRINO_TPU_QUERY_HISTORY_PATH"
        )
        if history_path:
            from ..runtime.events import QueryHistoryStore

            self.history = QueryHistoryStore(history_path)
            self.manager.add_listener(self.history)
            if sys_ctx is not None:
                sys_ctx.history_store = self.history
        self.authenticator = authenticator  # PasswordAuthenticator or None
        self.jwt_authenticator = jwt_authenticator  # JwtAuthenticator or None
        self.oauth2 = oauth2_authenticator  # OAuth2Authenticator or None
        self.spooling = FileSystemSpoolingManager()
        self._spooled: Dict[str, list] = {}  # query_id -> segment descriptors
        self._spool_lock = threading.Lock()
        self.host = host
        coordinator = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            # ---------------------------------------------------------- utils

            def _send(self, code: int, payload: Dict, extra_headers=None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _client_context(self):
                """Rebuild the client session from protocol headers — the
                client re-sends its prepared statements and transaction id on
                every request (client-protocol.md: X-Trino-Prepared-Statement
                name=url-encoded-sql, X-Trino-Transaction-Id), so transaction
                and prepared state never depend on which server thread runs
                the statement."""
                from urllib.parse import unquote

                from ..runtime.local import ClientContext
                from ..sql import parse_statement

                ctx = ClientContext()
                header = self.headers.get("X-Trino-Prepared-Statement", "")
                for part in header.split(","):
                    part = part.strip()
                    if not part or "=" not in part:
                        continue
                    name, encoded = part.split("=", 1)
                    try:
                        ctx.prepared[unquote(name)] = parse_statement(
                            unquote(encoded)
                        )
                    except Exception as e:  # noqa: BLE001
                        # a corrupt entry must fail THIS request loudly, not
                        # resurface later as "prepared statement not found"
                        raise BadSessionHeader(
                            f"invalid X-Trino-Prepared-Statement entry "
                            f"{unquote(name)!r}: {e}"
                        ) from None
                txn_id = self.headers.get("X-Trino-Transaction-Id", "")
                if txn_id and txn_id.upper() != "NONE":
                    try:
                        ctx.txn = coordinator.runner.transactions.get(txn_id)
                    except Exception:  # noqa: BLE001 — expired/unknown txn
                        ctx.txn = None
                return ctx

            def _base_uri(self) -> str:
                host = self.headers.get("Host", coordinator.address)
                front = coordinator._front_server
                if front is not None and host.rsplit(":", 1)[-1] == str(
                    front.server_port
                ):
                    # the request came in on the shared SO_REUSEPORT front
                    # port: a nextUri/infoUri echoing that port would let
                    # the kernel hand the follow-up to a SIBLING process
                    # that has never heard of the query — stateful
                    # conversation URIs must pin to THIS process's unique
                    # address
                    return f"http://{coordinator.address}"
                return f"http://{host}"

            def _authenticate(self):
                """Bearer (JWT) then Basic auth, like the reference's
                authenticator chain (server/security/AuthenticationFilter
                tries each configured authenticator in order); returns the
                authenticated user or None after sending a 401. With no
                authenticator configured, trusts X-Trino-User."""
                user_header = self.headers.get("X-Trino-User", "user")
                if (
                    coordinator.authenticator is None
                    and coordinator.jwt_authenticator is None
                    and coordinator.oauth2 is None
                ):
                    return user_header
                import base64

                auth = self.headers.get("Authorization", "")
                if auth.startswith("Bearer ") and coordinator.oauth2:
                    try:
                        return coordinator.oauth2.authenticate_token(auth[7:].strip())
                    except Exception:
                        pass
                if auth.startswith("Bearer ") and coordinator.jwt_authenticator:
                    try:
                        return coordinator.jwt_authenticator.authenticate_token(
                            auth[7:].strip()
                        )
                    except Exception:
                        pass
                if auth.startswith("Basic ") and coordinator.authenticator:
                    try:
                        decoded = base64.b64decode(auth[6:]).decode()
                        user, _, password = decoded.partition(":")
                        coordinator.authenticator.authenticate(user, password)
                        return user
                    except Exception:
                        pass
                self.send_response(401)
                challenge = (
                    'Basic realm="trino-tpu"'
                    if coordinator.authenticator
                    else 'Bearer realm="trino-tpu"'
                )
                self.send_header("WWW-Authenticate", challenge)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return None

            # ---------------------------------------------------------- routes

            def do_PUT(self):
                # worker announcements (node/Announcer.java -> /v1/announcement)
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                if len(parts) == 3 and parts[0] == "v1" and parts[1] == "announcement":
                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("announcement body must be an object")
                    except (ValueError, json.JSONDecodeError) as e:
                        self._send(400, {"error": f"bad announcement body: {e}"})
                        return
                    memory = body.get("memory")
                    coordinator.nodes.announce(
                        parts[2],
                        body.get("uri", ""),
                        coordinator=bool(body.get("coordinator")),
                        location=str(body.get("location", "")),
                        version=str(body.get("version", "")),
                        device=str(body.get("device", "")),
                        memory=memory if isinstance(memory, dict) else None,
                    )
                    # cluster observability riders (payload-driven: only
                    # flag-on workers attach them; the response is the
                    # same either way)
                    clock = body.get("clock")
                    if isinstance(clock, dict):
                        coordinator.clock_sync.observe_announcement(
                            parts[2], clock
                        )
                    metrics = body.get("metrics")
                    if isinstance(metrics, list):
                        coordinator.cluster_metrics.ingest(parts[2], metrics)
                    kc_rows = body.get("kernel_costs")
                    if isinstance(kc_rows, list):
                        from ..runtime import kernelcost

                        kernelcost.ingest_federated(parts[2], kc_rows)
                    self._send(202, {"announced": parts[2]})
                    return
                # admin kill (QueryResource.killQuery / KillQueryProcedure
                # over HTTP): PUT /v1/query/{id}/killed, body = message
                if (
                    len(parts) == 4
                    and parts[0] == "v1"
                    and parts[1] == "query"
                    and parts[3] == "killed"
                ):
                    from ..runtime.query_manager import (
                        CancelResult,
                        QueryNotFound,
                    )

                    if self._authenticate() is None:
                        return
                    length = int(self.headers.get("Content-Length", 0))
                    message = (self.rfile.read(length) or b"").decode()
                    try:
                        result = coordinator.manager.kill(parts[2], message)
                    except QueryNotFound:
                        self._send(404, {"error": "unknown query"})
                        return
                    if result is CancelResult.TERMINAL:
                        self._send(409, {"error": "query already finished"})
                        return
                    self._send(202, {"killed": parts[2]})
                    return
                self._send(404, {"error": "not found"})

            def do_POST(self):
                path = urlparse(self.path).path
                if path == "/v1/statement":
                    # host-path plane: every protocol phase of statement
                    # intake gets a paired flight span (proto_accept wraps
                    # the whole request; auth/parse nest inside) so a slow
                    # submission attributes to a phase, not a guess
                    from ..runtime.hostprof import phase_span
                    from ..runtime.observability import RECORDER

                    with phase_span(
                        RECORDER, "accept", path="/v1/statement"
                    ) as accept_end:
                        with phase_span(RECORDER, "auth"):
                            user = self._authenticate()
                        if user is None:
                            return
                        length = int(self.headers.get("Content-Length", 0))
                        sql = self.rfile.read(length).decode()
                        # coordinator fleet (runtime/fleet.py): partitioned
                        # admission — a non-owner either 307-redirects the
                        # client to the owner or proxies the intake there,
                        # under proto_route/proto_proxy spans; follower-
                        # servable reads short-circuit to local execution
                        if coordinator.fleet is not None:
                            if coordinator._fleet_route(self, sql, user):
                                return
                        try:
                            with phase_span(RECORDER, "parse"):
                                client_ctx = self._client_context()
                        except BadSessionHeader as e:
                            self._send(400, {"error": str(e)})
                            return
                        encodings = [
                            e.strip()
                            for e in self.headers.get(
                                "X-Trino-Query-Data-Encoding", ""
                            ).split(",")
                            if e.strip()
                        ]
                        q = coordinator.manager.submit(
                            sql,
                            user=user,
                            source=self.headers.get("X-Trino-Source", ""),
                            data_encoding=coordinator._pick_encoding(encodings),
                            client_ctx=client_ctx,
                            warm_result=getattr(
                                self, "_fleet_warm_hit", None
                            ),
                        )
                        accept_end["query_id"] = q.query_id
                        wait = coordinator._first_response_wait()
                        if wait > 0:
                            # first-response long-poll (the protocol's
                            # maxWait idea applied to the POST): a query
                            # that finishes within the window — a warm
                            # cache hit above all — drains in ONE round
                            # trip; a slower query falls through to the
                            # usual nextUri sequence when the wait lapses
                            q.wait_done(wait)
                        with phase_span(
                            RECORDER, "result_stream", query_id=q.query_id
                        ):
                            self._send(
                                200,
                                coordinator._results_payload(
                                    q, 0, self._base_uri()
                                ),
                                extra_headers=coordinator._session_headers(q),
                            )
                    return
                self._send(404, {"error": f"not found: {path}"})

            def do_GET(self):
                path_q = urlparse(self.path)
                if coordinator.oauth2 is not None and path_q.path == "/oauth2/authorize":
                    # start of the code flow (OAuth2WebUiAuthenticationFilter):
                    # bounce the browser to the IdP with an HMAC'd state
                    import uuid as _uuid

                    state = coordinator.oauth2.sign_state(_uuid.uuid4().hex)
                    redirect = f"{self._base_uri()}/oauth2/callback"
                    url = coordinator.oauth2.authorization_url(redirect, state)
                    self.send_response(302)
                    self.send_header("Location", url)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if coordinator.oauth2 is not None and path_q.path == "/oauth2/callback":
                    from urllib.parse import parse_qs

                    params = parse_qs(path_q.query)
                    state = (params.get("state") or [""])[0]
                    code = (params.get("code") or [""])[0]
                    if not coordinator.oauth2.check_state(state):
                        self._send(401, {"error": "bad oauth2 state"})
                        return
                    try:
                        token = coordinator.oauth2.exchange_code(
                            code, f"{self._base_uri()}/oauth2/callback"
                        )
                    except Exception as e:  # noqa: BLE001 — auth failures -> 401
                        self._send(401, {"error": f"oauth2 exchange failed: {e}"})
                        return
                    self._send(200, {"token": token, "token_type": "Bearer"})
                    return
                if self._authenticate() is None:
                    return
                path = urlparse(self.path).path
                parts = [p for p in path.split("/") if p]
                if path in ("/", "/ui", "/ui/"):
                    # minimal cluster/query overview (core/trino-web-ui's role;
                    # a real SPA is a later round — this reads the same APIs)
                    body = coordinator._ui_html().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/v1/info":
                    self._send(
                        200,
                        {
                            "nodeVersion": {"version": __version__},
                            "environment": "trino-tpu",
                            "coordinator": True,
                            "starting": False,
                            "uptime": "up",
                        },
                    )
                    return
                if path == "/v1/resourceGroupState":
                    groups = coordinator.manager.resource_groups
                    self._send(200, groups.info() if groups else {})
                    return
                if path == "/v1/memory":
                    # cluster memory pool view (ref: MemoryResource /
                    # ClusterMemoryManager): local pool + per-node heartbeat-
                    # reported reservations
                    cm = coordinator.manager.cluster_memory
                    if cm is not None:
                        self._send(200, cm.cluster_info())
                    else:
                        pool = coordinator.manager.memory_pool
                        self._send(200, pool.snapshot() if pool else {})
                    return
                if path == "/v1/flightrecorder":
                    # the pipeline flight recorder's ring buffer as
                    # Chrome/Perfetto trace-event JSON (load the payload in
                    # ui.perfetto.dev); ?enable=1 / ?disable=1 toggle it,
                    # ?query_id= filters to one query's attribution windows
                    # (the cluster trace assembly's coordinator segment)
                    from urllib.parse import parse_qs

                    from ..runtime.observability import RECORDER

                    params = parse_qs(path_q.query)

                    def flag(name):
                        v = params.get(name, ["0"])[0].lower()
                        return v not in ("", "0", "false", "no")

                    if flag("enable"):
                        RECORDER.enable()
                    if flag("disable"):
                        RECORDER.disable()
                    if flag("clear"):
                        RECORDER.clear()
                    qid = params.get("query_id", [""])[0]
                    if qid:
                        from ..runtime.clusterobs import (
                            local_segment,
                            server_enabled,
                        )

                        # filtering is part of the cluster plane: with the
                        # flag off the param is ignored (unknown params
                        # always were) and the response stays byte-identical
                        if server_enabled():
                            self._send(200, local_segment([qid]))
                            return
                    self._send(200, RECORDER.chrome_trace())
                    return
                if path == "/v1/metrics/cluster":
                    # fleet-wide Prometheus exposition: local registry +
                    # every announced worker's piggybacked snapshot, per-
                    # node labels, HELP preserved, histogram buckets merged
                    from ..runtime.clusterobs import server_enabled

                    if not server_enabled():
                        self._send(404, {"error": "cluster_obs disabled"})
                        return
                    from ..runtime.metrics import REGISTRY

                    body = coordinator.cluster_metrics.render(
                        local_registry=REGISTRY
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/v1/statshistory":
                    # the statistics feedback plane's history store (the
                    # estimate-vs-actual records HistoryBasedStatsEstimator
                    # overlays; SQL twin: system.optimizer.stats_history)
                    from ..runtime.statstore import history_path, load_history

                    self._send(
                        200,
                        {
                            "path": history_path(),
                            "entries": load_history(),
                        },
                    )
                    return
                if path == "/v1/metrics":
                    from ..runtime.metrics import REGISTRY

                    body = REGISTRY.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if (
                    len(parts) == 4
                    and parts[0] == "v1"
                    and parts[1] == "query"
                    and parts[3] == "trace"
                ):
                    from urllib.parse import parse_qs

                    from ..runtime.clusterobs import server_enabled
                    from ..runtime.tracing import TRACER

                    params = parse_qs(path_q.query)
                    want_cluster = params.get("cluster", ["0"])[0].lower() \
                        not in ("", "0", "false", "no")
                    if want_cluster and server_enabled():
                        # cross-node trace assembly: pull every node's
                        # segment, skew-align by announced clock offsets,
                        # merge into one Perfetto timeline
                        q = coordinator.manager.get(parts[2])
                        if q is None:
                            self._send(404, {"error": "unknown query"})
                            return
                        self._send(200, coordinator.cluster_trace(q))
                        return
                    q = coordinator.manager.get(parts[2])
                    if q is None or q.trace_id is None:
                        self._send(404, {"error": "no trace for query"})
                        return
                    self._send(
                        200,
                        {"traceId": q.trace_id, "spans": TRACER.trace(q.trace_id)},
                    )
                    return
                if (
                    len(parts) == 4
                    and parts[0] == "v1"
                    and parts[1] == "query"
                    and parts[3] == "profile"
                ):
                    # persisted query profile bundle (cluster obs plane)
                    from ..runtime.clusterobs import (
                        profile_store,
                        server_enabled,
                    )

                    if not server_enabled():
                        self._send(404, {"error": "cluster_obs disabled"})
                        return
                    store = profile_store()
                    profile = store.read(parts[2]) if store else None
                    if profile is None:
                        self._send(404, {"error": "no profile for query"})
                        return
                    self._send(200, profile)
                    return
                if len(parts) == 3 and parts[0] == "v1" and parts[1] == "spooled":
                    data = coordinator.spooling.get_segment(parts[2])
                    if data is None:
                        self._send(404, {"error": "unknown segment"})
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if path == "/v1/ha":
                    # serving fabric plane: leader lease state (standby
                    # coordinators and operators read the same snapshot)
                    lease = coordinator.ha_lease
                    self._send(
                        200,
                        lease.snapshot() if lease is not None
                        else {"enabled": False},
                    )
                    return
                if path == "/v1/status":
                    queries = coordinator.manager.list_queries()
                    self._send(
                        200,
                        {
                            "nodeCount": 1,
                            "runningQueries": sum(
                                1 for q in queries if not q.state.is_done
                            ),
                            "totalQueries": len(queries),
                        },
                    )
                    return
                if path == "/ui/api/stats":
                    # ClusterStatsResource.java analogue: the numbers the
                    # React UI's landing page renders
                    queries = coordinator.manager.list_queries()
                    by_state: Dict[str, int] = {}
                    for q in queries:
                        by_state[q.state.name] = by_state.get(q.state.name, 0) + 1
                    nodes = coordinator.nodes.all_nodes()
                    self._send(
                        200,
                        {
                            "runningQueries": sum(
                                1 for q in queries if not q.state.is_done
                            ),
                            "queuedQueries": by_state.get("QUEUED", 0),
                            "finishedQueries": by_state.get("FINISHED", 0),
                            "failedQueries": by_state.get("FAILED", 0),
                            "totalQueries": len(queries),
                            "queriesByState": by_state,
                            "activeWorkers": sum(
                                1 for n in nodes if not n.coordinator
                            ),
                            "totalNodes": max(len(nodes), 1),
                        },
                    )
                    return
                if path == "/v1/node":
                    self._send(
                        200,
                        [
                            {
                                "nodeId": n.node_id,
                                "uri": n.uri,
                                "state": n.state.value,
                                "coordinator": n.coordinator,
                                "lastHeartbeat": n.last_heartbeat,
                            }
                            for n in coordinator.nodes.all_nodes()
                        ],
                    )
                    return
                if len(parts) == 2 and parts[:1] == ["v1"] and parts[1] == "query":
                    payload = [
                        coordinator._query_info(q)
                        for q in coordinator.manager.list_queries()
                    ]
                    self._send(200, payload)
                    return
                if len(parts) == 3 and parts[0] == "v1" and parts[1] == "query":
                    q = coordinator.manager.get(parts[2])
                    if q is None:
                        # fleet follower read: any member answers a status
                        # poll for a query it does not own from the board
                        # the owner publishes on lifecycle transitions
                        board = coordinator._fleet_board_status(parts[2])
                        if board is not None:
                            self._send(200, board)
                            return
                        self._send(404, {"error": "unknown query"})
                        return
                    self._send(200, coordinator._query_info_detail(q))
                    return
                if (
                    len(parts) == 5
                    and parts[0] == "v1"
                    and parts[1] == "statement"
                    and parts[2] == "executing"
                ):
                    query_id, token = parts[3], int(parts[4])
                    q = coordinator.manager.get(query_id)
                    if q is None:
                        self._send(404, {"error": "unknown query"})
                        return
                    from ..runtime.hostprof import phase_span
                    from ..runtime.observability import RECORDER

                    # long-poll-ish: wait briefly for progress (the reference's
                    # ExecutingStatementResource does the same with maxWait)
                    if not q.state.is_done:
                        q.wait_done(timeout=1.0)
                    with phase_span(
                        RECORDER, "result_stream", query_id=query_id,
                        token=token,
                    ):
                        self._send(
                            200,
                            coordinator._results_payload(
                                q, token, self._base_uri()
                            ),
                            extra_headers=coordinator._session_headers(q),
                        )
                    return
                self._send(404, {"error": f"not found: {path}"})

            def do_DELETE(self):
                if self._authenticate() is None:
                    return
                path = urlparse(self.path).path
                parts = [p for p in path.split("/") if p]
                if len(parts) == 3 and parts[0] == "v1" and parts[1] == "spooled":
                    # segment acknowledgement (SpoolingManager.delete)
                    coordinator.spooling.delete_segment(parts[2])
                    self._send(204, {})
                    return
                from ..runtime.query_manager import CancelResult, QueryNotFound

                if len(parts) >= 4 and parts[1] == "statement":
                    # protocol cancel: an already-finished OR history-evicted
                    # query is a client-side race, not an error — a client
                    # closing its statement handle after the bounded ring
                    # dropped the id must still get the no-op 204
                    try:
                        coordinator.manager.cancel(parts[3])
                    except QueryNotFound:
                        pass
                    self._send(204, {})
                    return
                if len(parts) == 3 and parts[0] == "v1" and parts[1] == "query":
                    # admin cancel (QueryResource.cancelQuery): the right
                    # status per outcome — 404 unknown, 409 already terminal
                    try:
                        result = coordinator.manager.cancel(parts[2])
                    except QueryNotFound:
                        self._send(404, {"error": "unknown query"})
                        return
                    if result is CancelResult.TERMINAL:
                        self._send(409, {"error": "query already finished"})
                        return
                    self._send(204, {})
                    return
                self._send(404, {"error": "not found"})

        # stdlib default accept backlog is 5: a concurrent-session storm
        # overflows it and every dropped SYN costs the client a ~1s
        # retransmit. Sizing the listen queue is part of the fleet front
        # plane (runtime/fleet.py main defaults it to 128 per process);
        # the default deployment keeps the shipped listen(5) behavior.
        backlog = knobs.env_int("TRINO_TPU_HTTP_BACKLOG", 0)
        if backlog > 0:
            class _CoordinatorHTTPServer(ThreadingHTTPServer):
                request_queue_size = backlog
        else:
            _CoordinatorHTTPServer = ThreadingHTTPServer

        self._http_server_cls = _CoordinatorHTTPServer
        self._server = _CoordinatorHTTPServer((host, port), Handler)
        self.port = self._server.server_port
        self._thread: Optional[threading.Thread] = None
        # coordinator fleet plane (runtime/fleet.py): membership on the fs
        # substrate when deployed ($TRINO_TPU_FLEET_DIR or an explicit
        # member); plus the optional SO_REUSEPORT front listener so N
        # forked coordinator processes share one client-facing port while
        # membership advertises each process's unique port for routing
        self.fleet = fleet
        if self.fleet is None:
            from ..runtime.fleet import member_from_env

            self.fleet = member_from_env(
                f"http://{host}:{self.port}", node_id=node_id,
                cluster_metrics=self.cluster_metrics,
            )
        self._front_server = None
        self._front_thread: Optional[threading.Thread] = None
        if front_port is None:
            front_port = knobs.env_int("TRINO_TPU_FLEET_FRONT_PORT", 0)
        if front_port:
            import socket

            class _ReusePortServer(self._http_server_cls):
                allow_reuse_address = True

                def server_bind(inner):
                    if hasattr(socket, "SO_REUSEPORT"):
                        inner.socket.setsockopt(
                            socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                        )
                    ThreadingHTTPServer.server_bind(inner)

            self._front_server = _ReusePortServer((host, front_port), Handler)
        if self.fleet is not None:
            from ..runtime.fleet import FleetStatusListener
            from ..runtime.metrics import REGISTRY

            depth = REGISTRY.gauge(
                "trino_tpu_protocol_queue_depth",
                help="queries waiting on a resource-group concurrency slot",
            )
            self.fleet.queue_depth_fn = lambda: int(depth.value)
            self.manager.add_listener(FleetStatusListener(self.fleet))
        # serving fabric plane (runtime/ha.py): a leader lease on the shared
        # substrate when HA is deployed ($TRINO_TPU_HA_DIR or an explicit
        # lease); the runner's FTE journal appends fence on the same epoch
        self.ha_lease = ha_lease
        if self.ha_lease is None:
            ha_dir = knobs.env_path("TRINO_TPU_HA_DIR")
            if ha_dir:
                from ..runtime.ha import LeaderLease

                self.ha_lease = LeaderLease(
                    ha_dir, node_id=f"coordinator-{os.getpid()}-{self.port}"
                )
        if self.ha_lease is not None and hasattr(runner, "ha_lease"):
            runner.ha_lease = self.ha_lease
        self._ha_stop: Optional[threading.Event] = None
        self._ha_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ api

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "CoordinatorServer":
        # named: the hostprof sampler and the deterministic-tid Perfetto
        # contract both group on thread names
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"coordinator-http-{self.port}",
        )
        self._thread.start()
        if self._front_server is not None:
            # the shared SO_REUSEPORT client-facing listener: the kernel
            # load-balances accepts across the forked sibling processes
            self._front_thread = threading.Thread(
                target=self._front_server.serve_forever, daemon=True,
                name=f"coordinator-front-{self._front_server.server_port}",
            )
            self._front_thread.start()
        if self.fleet is not None:
            self.fleet.start()
        # host-path plane: $TRINO_TPU_HOSTPROF runs the sampling profiler +
        # GIL-contention probe for the process lifetime (no-op when off)
        from ..runtime.hostprof import start_server_profiling

        start_server_profiling()
        # the coordinator is a node too (system.runtime.nodes shows the whole
        # cluster, like the reference's CoordinatorNodeManager)
        from ..connectors.system import device_kind

        pool = self.manager.memory_pool
        self.nodes.announce(
            "coordinator", f"http://{self.address}", coordinator=True,
            version=__version__, device=device_kind(),
            memory=pool.memory_announcement() if pool is not None else None,
        )
        if self.ha_lease is not None:
            # primary grabs the lease; either way the maintenance loop
            # below keeps it honest — the holder renews at ttl/3, a
            # standby keeps watching and takes over when the lease lapses
            self.ha_lease.acquire()
            self._ha_stop = threading.Event()
            self._ha_thread = threading.Thread(
                target=self._ha_loop, daemon=True, name="ha-lease"
            )
            self._ha_thread.start()
        return self

    def _ha_loop(self) -> None:
        """Lease maintenance: renew while leading, re-attempt acquisition
        while standing by. Dies with the process — a crashed coordinator
        stops renewing, which is exactly what lets the standby take over."""
        lease = self.ha_lease
        while not self._ha_stop.wait(max(0.05, lease.ttl / 3.0)):
            try:
                if lease.epoch > 0:
                    lease.renew()
                else:
                    lease.acquire()
            except Exception:  # noqa: BLE001 — maintenance must never die
                pass

    def stop(self, crash: bool = False) -> None:
        """``crash=True`` models a dead process for the fleet plane: the
        membership record is NOT deregistered — it stays until its TTL
        lapses, which is what drives hash-range reassignment."""
        if self._ha_stop is not None:
            self._ha_stop.set()
        if self.fleet is not None:
            self.fleet.stop(deregister=not crash)
        if self._front_server is not None:
            self._front_server.shutdown()
            self._front_server.server_close()
        self._server.shutdown()
        self._server.server_close()
        self.spooling.close()

    # --------------------------------------------------------- fleet routing

    def _fleet_route(self, handler, sql: str, user: str) -> bool:
        """Partitioned-admission routing for one POST /v1/statement under
        the fleet plane. Returns True when a response has been sent (the
        statement was redirected or proxied to its owner); False means
        this coordinator serves it locally — because it owns the key, or
        because the statement is follower-servable (system.*-only, or a
        warm result-cache hit via the PURE ``peek_cached_result`` probe
        against the shared tier)."""
        from ..runtime.fleet import (
            FOLLOWER_READS_HELP,
            ROUTED_HELP,
            _counter,
            is_system_read,
            partition_key,
        )
        from ..runtime.hostprof import phase_span
        from ..runtime.observability import RECORDER

        fleet = self.fleet
        with phase_span(RECORDER, "route") as sp:
            source = handler.headers.get("X-Trino-Source", "")
            if knobs.env_flag("TRINO_TPU_FLEET_FOLLOWER_READS", True):
                if is_system_read(sql):
                    sp["outcome"] = "follower_read"
                    _counter(
                        "trino_tpu_fleet_follower_reads_total",
                        FOLLOWER_READS_HELP,
                    ).inc()
                    return False
                peek = getattr(self.runner, "peek_cached_result", None)
                hit = None
                if peek is not None:
                    try:
                        hit = peek(sql, user=user)
                    except Exception:  # noqa: BLE001 — probe must stay pure
                        hit = None
                if hit is not None:
                    # the owner never sees a warm hit: the local submit
                    # path serves it from the shared tier before the gate.
                    # Hand the peeked result to admission so the serving
                    # path does not repeat the plan/key/lookup work.
                    handler._fleet_warm_hit = hit
                    sp["outcome"] = "warm_hit"
                    _counter(
                        "trino_tpu_fleet_follower_reads_total",
                        FOLLOWER_READS_HELP,
                    ).inc()
                    return False
            group = ""
            if knobs.env_str(
                "TRINO_TPU_FLEET_PARTITION_BY", "session"
            ) == "group" and self.manager.resource_groups is not None:
                try:
                    group = self.manager.resource_groups.group_path(
                        user, source
                    )
                except Exception:  # noqa: BLE001 — no selector match
                    group = ""
            key = partition_key(user, source, group)
            owner = fleet.owner_of(key)
            sp["owner"] = owner.get("node_id", "")
            if owner.get("node_id") == fleet.node_id:
                sp["outcome"] = "self"
                return False
            mode = knobs.env_str("TRINO_TPU_FLEET_ROUTE", "redirect")
            if mode != "proxy":
                sp["outcome"] = "redirect"
                _counter(
                    "trino_tpu_fleet_routed_total", ROUTED_HELP
                ).inc()
                handler._send(
                    307,
                    {"redirect": owner["url"]},
                    extra_headers={
                        "Location": f"{owner['url']}/v1/statement",
                        "X-Trino-Fleet-Owner": owner.get("node_id", ""),
                    },
                )
                return True
            sp["outcome"] = "proxy"
        self._fleet_proxy(handler, sql, owner)
        return True

    def _fleet_proxy(self, handler, sql: str, owner: dict) -> None:
        """Forward the statement intake to the owner and relay its
        response verbatim. Only the intake is proxied: the owner's
        nextUri points at the owner's own address, so result paging goes
        direct (one extra hop per statement, zero per page)."""
        import urllib.error
        import urllib.request

        from ..runtime.fleet import PROXIED_HELP, _counter
        from ..runtime.hostprof import phase_span
        from ..runtime.observability import RECORDER

        with phase_span(
            RECORDER, "proxy", owner=owner.get("node_id", "")
        ):
            fwd_headers = {
                k: v for k, v in handler.headers.items()
                if k.lower().startswith("x-trino")
                or k.lower() == "authorization"
            }
            req = urllib.request.Request(
                f"{owner['url']}/v1/statement", data=sql.encode(),
                method="POST", headers=fwd_headers,
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    status, body = resp.status, resp.read()
                    relay = {
                        k: v for k, v in resp.headers.items()
                        if k.lower().startswith("x-trino")
                    }
            except urllib.error.HTTPError as e:
                status, body, relay = e.code, e.read(), {}
            except (urllib.error.URLError, OSError) as e:
                handler._send(
                    503, {"error": f"fleet owner unreachable: {e}"}
                )
                return
            _counter("trino_tpu_fleet_proxied_total", PROXIED_HELP).inc()
            handler.send_response(status)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            for k, v in relay.items():
                handler.send_header(k, v)
            handler.end_headers()
            handler.wfile.write(body)

    def _fleet_board_status(self, query_id: str) -> Optional[Dict]:
        """Follower status read: the owner-published board record for a
        query this coordinator does not hold (None = not fleet-deployed,
        follower reads off, or no record)."""
        if self.fleet is None or not knobs.env_flag(
            "TRINO_TPU_FLEET_FOLLOWER_READS", True
        ):
            return None
        board = self.fleet.read_status(query_id)
        if board is not None:
            from ..runtime.fleet import FOLLOWER_READS_HELP, _counter

            _counter(
                "trino_tpu_fleet_follower_reads_total", FOLLOWER_READS_HELP
            ).inc()
        return board

    # --------------------------------------------------- cluster observability

    def cluster_trace(self, q) -> Dict:
        """Cross-node trace assembly for one query: the coordinator's own
        flight-recorder segment plus every announced worker's
        ``/v1/flightrecorder?query_id=`` segment, skew-aligned by the clock
        offsets estimated from announcement RTT midpoints and merged into
        one Perfetto timeline (one process lane per node). When the query
        ran under the HA plane, its dispatch-journal records ride along as
        instant markers, stitching both leader epochs of a failover."""
        import os
        import urllib.request

        from ..runtime import clusterobs
        from .worker import SIGNATURE_HEADER, sign

        qids = {q.query_id}
        fte_id = getattr(q, "fte_query_id", None)
        if fte_id:
            qids.add(fte_id)
        segments = {"coordinator": clusterobs.local_segment(qids)}
        # the runner's explicit secret= wins over the env var — workers
        # deployed with a constructor secret would 401 an env-only lookup
        secret = (
            getattr(self.runner, "secret", None)
            or knobs.env_str("TRINO_TPU_INTERNAL_SECRET")
        )
        for n in self.nodes.all_nodes():
            if n.coordinator or not n.uri:
                continue
            rel = "/v1/flightrecorder"
            url = f"{n.uri.rstrip('/')}{rel}?query_id={fte_id or q.query_id}"
            req = urllib.request.Request(url, method="GET")
            sig = sign(secret, "GET", rel)
            if sig:
                req.add_header(SIGNATURE_HEADER, sig)
            try:
                with urllib.request.urlopen(req, timeout=5.0) as resp:
                    payload = json.loads(resp.read())
            except (OSError, ValueError):
                continue  # a dead node costs its lane, never the merge
            trace = payload.get("trace") if isinstance(payload, dict) else None
            if isinstance(trace, dict):
                segments[n.node_id] = trace
        # the journal copy attached to the query's stats bundle survives
        # exchange-directory cleanup; a live (uncleaned) journal file is
        # the fallback for queries still in flight
        journal_records = (getattr(q, "query_stats", None) or {}).get("journal")
        if not journal_records and fte_id:
            mgr = getattr(self.runner, "_fte_manager", None)
            base = getattr(mgr, "base_dir", None)
            if base:
                from ..runtime.ha import DispatchJournal

                path = DispatchJournal.path_for(base, fte_id)
                if os.path.isfile(path):
                    journal_records, _ = DispatchJournal.read(path)
        return clusterobs.assemble_cluster_trace(
            segments,
            offsets=self.clock_sync.offsets(),
            journal_records=journal_records,
        )

    # ------------------------------------------------------------------- ui

    def _ui_html(self) -> str:
        import html as html_mod

        all_queries = self.manager.list_queries()
        running = sum(1 for q in all_queries if not q.state.is_done)
        queries = sorted(
            all_queries, key=lambda q: q.stats.create_time, reverse=True
        )[:50]
        nodes = self.nodes.all_nodes()
        rows = "\n".join(
            f"<tr><td><a href='/v1/query/{q.query_id}'>{q.query_id}</a></td>"
            f"<td>{q.state.value}</td><td>{q.stats.elapsed:.2f}s</td>"
            f"<td>{q.stats.rows}</td>"
            f"<td><code>{html_mod.escape(q.sql[:120])}</code></td></tr>"
            for q in queries
        )
        # node_id/uri arrive from announcements — escape like everything else
        node_rows = "\n".join(
            f"<tr><td>{html_mod.escape(n.node_id)}</td><td>{n.state.value}</td>"
            f"<td>{html_mod.escape(n.uri)}</td></tr>"
            for n in nodes
        )
        return f"""<!doctype html><html><head><title>trino-tpu</title>
<style>body{{font-family:sans-serif;margin:2em}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:4px 8px;text-align:left}}</style></head>
<body><h1>trino-tpu coordinator</h1>
<p>version {__version__} &middot; {running} running &middot; {len(queries)} recent queries
&middot; {len(nodes)} announced workers</p>
<h2>Queries</h2>
<table><tr><th>id</th><th>state</th><th>elapsed</th><th>rows</th><th>query</th></tr>
{rows}</table>
<h2>Workers</h2>
<table><tr><th>node</th><th>state</th><th>uri</th></tr>{node_rows}</table>
</body></html>"""

    # ------------------------------------------------------------- payloads

    def _query_info(self, q) -> Dict:
        return {
            "queryId": q.query_id,
            "state": q.state.value,
            "query": q.sql,
            "elapsedTime": round(q.stats.elapsed, 4),
            "cpuTime": round(q.stats.cpu_time, 4),
            "rows": q.stats.rows,
            "error": q.error,
        }

    def _query_info_detail(self, q) -> Dict:
        """The full query JSON (ref: server/QueryResource.java:59 — the
        reference returns QueryInfo with the stage/task/operator tree; here
        the operator tree comes from the tracing spans the executor already
        records, nested by parent span)."""
        from ..runtime.tracing import TRACER

        info = self._query_info(q)
        info["queryStats"] = {
            "elapsedTime": round(q.stats.elapsed, 4),
            "cpuTime": round(q.stats.cpu_time, 4),
            "rows": q.stats.rows,
            "state": q.state.value,
            # warm-path cache plane: which tier served this query
            # ("result" / "fragment" / "plan"), null on a fully cold run —
            # overwritten from the stats snapshot when one exists
            "cacheHitTier": None,
        }
        # observability plane: Trino-parity attribution fields
        # (QueryStats.java naming — device/host/compile time, spill and
        # exchange byte counts) when the runner produced a stats snapshot
        plane = getattr(q, "query_stats", None)
        if plane is not None:
            from ..runtime.observability import query_stats_fields

            info["queryStats"].update(query_stats_fields(plane))
        spans = TRACER.trace(q.trace_id) if q.trace_id else []
        by_id = {}
        roots = []
        for sp in spans:
            entry = {
                "name": sp["name"],
                "durationMs": sp.get("durationMs"),
                "attributes": sp.get("attributes", {}),
                "children": [],
            }
            by_id[sp["spanId"]] = entry
            parent = sp.get("parentSpanId")
            if parent and parent in by_id:
                by_id[parent]["children"].append(entry)
            else:
                roots.append(entry)
        info["operatorTree"] = roots
        return info

    def _session_headers(self, q) -> Dict[str, str]:
        """Session-state response headers mirroring what the statement changed
        (client-protocol.md: the client accumulates these and re-sends the
        state on subsequent requests): X-Trino-Added-Prepare /
        X-Trino-Deallocated-Prepare / X-Trino-Started-Transaction-Id /
        X-Trino-Clear-Transaction-Id."""
        from urllib.parse import quote

        ctx = getattr(q, "client_ctx", None)
        if ctx is None or not q.state.is_done or not ctx.updates:
            return {}
        headers: Dict[str, str] = {}
        added = ctx.updates.get("added_prepare")
        if added is not None:
            name, sql_text = added
            headers["X-Trino-Added-Prepare"] = (
                f"{quote(name)}={quote(sql_text)}"
            )
        if "deallocated_prepare" in ctx.updates:
            headers["X-Trino-Deallocated-Prepare"] = quote(
                ctx.updates["deallocated_prepare"]
            )
        if "started_txn" in ctx.updates:
            headers["X-Trino-Started-Transaction-Id"] = ctx.updates["started_txn"]
        if ctx.updates.get("clear_txn"):
            headers["X-Trino-Clear-Transaction-Id"] = "true"
        if "set_catalog" in ctx.updates:
            headers["X-Trino-Set-Catalog"] = ctx.updates["set_catalog"]
        if "set_schema" in ctx.updates:
            headers["X-Trino-Set-Schema"] = ctx.updates["set_schema"]
        if "set_session" in ctx.updates:
            name, value = ctx.updates["set_session"]
            headers["X-Trino-Set-Session"] = f"{quote(name)}={quote(value)}"
        if "clear_session" in ctx.updates:
            headers["X-Trino-Clear-Session"] = quote(ctx.updates["clear_session"])
        return headers

    def _pick_encoding(self, requested) -> Optional[str]:
        """First supported spooled encoding, or None for inline results
        (protocol/spooling negotiation)."""
        from ..native import native_available

        for enc in requested:
            if enc == "json":
                return enc
            if enc == "json+lz4" and native_available():
                return enc
        return None

    def _spool_results(self, q, base_uri: str) -> list:
        """Write a finished query's rows into spool segments (idempotent).
        Serialization happens OUTSIDE the lock so one huge result can't block
        other clients' first responses; a losing racer deletes its segments."""
        with self._spool_lock:
            segs = self._spooled.get(q.query_id)
            if segs is not None:
                return segs
        types = q.column_types or [None] * len(q.column_names or [])
        rows = q.rows or []
        built = []
        seg_rows = max(PAGE_ROWS * 8, 1)
        for start in range(0, len(rows), seg_rows):
            chunk = rows[start : start + seg_rows]
            data = json.dumps(
                [
                    [_json_value(v, t) for v, t in zip(row, types)]
                    for row in chunk
                ]
            ).encode()
            raw_len = len(data)
            if q.data_encoding == "json+lz4":
                from ..native import lz4_compress

                data = lz4_compress(data)
            handle = self.spooling.create_segment(data, len(chunk))
            built.append(
                {
                    "uri": f"{base_uri}/v1/spooled/{handle.segment_id}",
                    "segmentId": handle.segment_id,
                    "rowCount": handle.rows,
                    "byteSize": handle.size_bytes,
                    "uncompressedSize": raw_len,
                }
            )
        with self._spool_lock:
            segs = self._spooled.get(q.query_id)
            if segs is not None:  # lost the race: free our duplicates
                for s in built:
                    self.spooling.delete_segment(s["segmentId"])
                return segs
            # prune descriptors of queries the tracker has since expired
            for qid in list(self._spooled):
                if self.manager.get(qid) is None:
                    for s in self._spooled.pop(qid):
                        self.spooling.delete_segment(s["segmentId"])
            self._spooled[q.query_id] = built
            return built

    def _first_response_wait(self) -> float:
        """Seconds the initial POST response may block on query completion
        (session prop ``protocol_first_response_wait``, default 0 = the
        classic immediate-nextUri sequence)."""
        session = getattr(self.runner, "session", None)
        if session is None:
            return 0.0
        try:
            return float(session.get("protocol_first_response_wait") or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def _results_payload(self, q, token: int, base_uri: str) -> Dict:
        payload: Dict = {
            "id": q.query_id,
            "infoUri": f"{base_uri}/v1/query/{q.query_id}",
            "stats": {
                "state": q.state.value,
                "elapsedTimeMillis": int(q.stats.elapsed * 1000),
                "processedRows": q.stats.rows,
            },
        }
        if q.state == QueryState.FAILED:
            payload["error"] = {
                "message": q.error,
                "errorName": q.error_type or "GENERIC_ERROR",
            }
            return payload
        if not q.state.is_done:
            payload["nextUri"] = (
                f"{base_uri}/v1/statement/executing/{q.query_id}/{token}"
            )
            return payload
        if q.data_encoding is not None and token == 0:
            # spooled protocol: all segments described at once; the client
            # fetches them out-of-band and acks with DELETE
            types = q.column_types or [None] * len(q.column_names or [])
            payload["columns"] = [
                {"name": name, **_type_signature(t)}
                for name, t in zip(q.column_names or [], types)
            ]
            payload["dataEncoding"] = q.data_encoding
            payload["segments"] = self._spool_results(q, base_uri)
            return payload
        # finished: page out rows
        start = token * PAGE_ROWS
        rows = q.rows or []
        chunk = rows[start : start + PAGE_ROWS]
        if q.column_names is not None and token == 0 or chunk:
            types = q.column_types or [None] * len(q.column_names or [])
            payload["columns"] = [
                {"name": name, **_type_signature(t)}
                for name, t in zip(q.column_names or [], types)
            ]
        if chunk:
            types = q.column_types or [None] * (len(chunk[0]) if chunk else 0)
            payload["data"] = [
                [_json_value(v, t) for v, t in zip(row, types)] for row in chunk
            ]
        if start + PAGE_ROWS < len(rows):
            payload["nextUri"] = (
                f"{base_uri}/v1/statement/executing/{q.query_id}/{token + 1}"
            )
        return payload
