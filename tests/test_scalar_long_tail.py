"""Round-3 scalar function batch.

Coverage model: the reference's operator/scalar tests — MathFunctions,
BitwiseFunctions, DateTimeFunctions (ISO week semantics), StringFunctions.
"""

import datetime
import math

import pytest

from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


def one(runner, expr):
    return runner.execute(f"SELECT {expr}").rows[0][0]


class TestMath:
    def test_constants(self, runner):
        assert abs(one(runner, "pi()") - math.pi) < 1e-15
        assert abs(one(runner, "e()") - math.e) < 1e-15
        assert math.isnan(one(runner, "nan()"))
        assert math.isinf(one(runner, "infinity()"))

    def test_angle_and_hyperbolic(self, runner):
        assert abs(one(runner, "degrees(pi())") - 180.0) < 1e-12
        assert abs(one(runner, "radians(180.0)") - math.pi) < 1e-12
        assert abs(one(runner, "cosh(1.0)") - math.cosh(1)) < 1e-12
        assert abs(one(runner, "tanh(0.5)") - math.tanh(0.5)) < 1e-12

    def test_truncate(self, runner):
        assert one(runner, "truncate(3.789)") == 3.0
        assert abs(one(runner, "truncate(3.789, 2)") - 3.78) < 1e-12
        assert one(runner, "truncate(-3.789)") == -3.0

    def test_predicates(self, runner):
        assert one(runner, "is_nan(nan())") is True
        assert one(runner, "is_finite(1.0)") is True
        assert one(runner, "is_infinite(1.0 / 0.0)") in (True, None)

    def test_width_bucket(self, runner):
        assert one(runner, "width_bucket(5.0, 0.0, 10.0, 4)") == 3
        assert one(runner, "width_bucket(-1.0, 0.0, 10.0, 4)") == 0
        assert one(runner, "width_bucket(11.0, 0.0, 10.0, 4)") == 5

    def test_random_bounds(self, runner):
        rows = runner.execute(
            "SELECT min(r) >= 0.0, max(r) < 1.0 FROM "
            "(SELECT random() AS r FROM lineitem)"
        ).rows
        assert rows == [(True, True)]
        (distinct,) = runner.execute(
            "SELECT count(DISTINCT r) FROM (SELECT random() AS r FROM lineitem)"
        ).rows[0]
        assert distinct > 100


class TestBitwise:
    def test_basics(self, runner):
        assert one(runner, "bitwise_and(12, 10)") == 8
        assert one(runner, "bitwise_or(12, 10)") == 14
        assert one(runner, "bitwise_xor(12, 10)") == 6
        assert one(runner, "bitwise_not(0)") == -1
        assert one(runner, "bitwise_not(-1)") == 0

    def test_shifts(self, runner):
        assert one(runner, "bitwise_left_shift(1, 10)") == 1024
        assert one(runner, "bitwise_right_shift(1024, 3)") == 128
        # logical right shift of a negative (the reference's semantics)
        assert one(runner, "bitwise_right_shift(-1, 62)") == 3

    def test_bit_count(self, runner):
        assert one(runner, "bit_count(255)") == 8
        assert one(runner, "bit_count(0)") == 0
        assert one(runner, "bit_count(-1, 64)") == 64
        assert one(runner, "bit_count(-1, 8)") == 8


class TestDatetimeLongTail:
    def test_iso_week_edges(self, runner):
        # 2026-01-01 is a Thursday: week 1 of 2026
        assert one(runner, "week(DATE '2026-01-01')") == 1
        assert one(runner, "year_of_week(DATE '2026-01-01')") == 2026
        # 2021-01-01 is a Friday: ISO week 53 of 2020
        assert one(runner, "week(DATE '2021-01-01')") == 53
        assert one(runner, "yow(DATE '2021-01-01')") == 2020
        # 2024-12-30 is a Monday: week 1 of 2025
        assert one(runner, "week(DATE '2024-12-30')") == 1
        assert one(runner, "year_of_week(DATE '2024-12-30')") == 2025

    def test_week_against_python(self, runner):
        rows = runner.execute(
            "SELECT o_orderdate, week(o_orderdate), year_of_week(o_orderdate) "
            "FROM orders LIMIT 200"
        ).rows
        for d, w, wy in rows:
            iso = d.isocalendar()
            assert (wy, w) == (iso[0], iso[1]), d

    def test_last_day_of_month(self, runner):
        assert one(runner, "last_day_of_month(DATE '2024-02-10')") == datetime.date(2024, 2, 29)
        assert one(runner, "last_day_of_month(DATE '2023-02-10')") == datetime.date(2023, 2, 28)
        assert one(runner, "last_day_of_month(DATE '2026-12-31')") == datetime.date(2026, 12, 31)

    def test_aliases(self, runner):
        assert one(runner, "day_of_month(DATE '2026-07-30')") == 30
        assert one(runner, "dow(DATE '2026-07-30')") == 4  # Thursday
        assert one(runner, "doy(DATE '2026-02-01')") == 32


class TestStringLongTail:
    def test_split_part(self, runner):
        assert one(runner, "split_part('a,b,c', ',', 2)") == "b"
        assert one(runner, "split_part('a,b,c', ',', 9)") is None

    def test_translate(self, runner):
        assert one(runner, "translate('hello', 'el', 'ip')") == "hippo"
        # unmapped from-characters are deleted
        assert one(runner, "translate('abcd', 'bd', 'x')") == "axc"

    def test_codepoint(self, runner):
        assert one(runner, "codepoint('A')") == 65

    def test_distances_over_column(self, runner):
        rows = runner.execute(
            "SELECT n_name, levenshtein_distance(n_name, 'CHINA') FROM nation "
            "WHERE n_name IN ('CHINA', 'INDIA') ORDER BY n_name"
        ).rows
        assert rows == [("CHINA", 0), ("INDIA", 4)]
        assert one(runner, "hamming_distance('abc', 'abd')") == 1
        assert one(runner, "hamming_distance('abc', 'abcd')") is None


class TestRound4ScalarBatch:
    """Math CDFs, hash/encoding family (hex-string deviation noted in
    compiler), regexp counts, Wilson intervals, timezone extracts.
    ref: scalar/MathFunctions.java (normalCdf/inverseNormalCdf/betaCdf),
    WilsonInterval.java, VarbinaryFunctions.java, JoniRegexpFunctions."""

    def test_math_cdfs(self, runner):
        row = runner.execute(
            "SELECT log(2.0, 8.0), normal_cdf(0.0, 1.0, 1.96), "
            "inverse_normal_cdf(0.0, 1.0, 0.975), beta_cdf(2.0, 3.0, 0.5)"
        ).rows[0]
        for got, exp in zip(row, (3.0, 0.97500, 1.95996, 0.6875)):
            assert abs(got - exp) < 1e-4, (got, exp)

    def test_wilson_interval(self, runner):
        row = runner.execute(
            "SELECT wilson_interval_lower(10, 100, 1.96), "
            "wilson_interval_upper(10, 100, 1.96)"
        ).rows[0]
        for got, exp in zip(row, (0.05522, 0.17437)):
            assert abs(got - exp) < 1e-4, (got, exp)

    def test_hash_and_encoding(self, runner):
        rows = runner.execute(
            "SELECT md5('abc'), sha256(''), crc32('abc'), "
            "to_base64('hello'), from_base64('aGVsbG8='), "
            "to_hex('AB'), from_hex('4142')"
        ).rows
        assert rows == [(
            "900150983cd24fb0d6963f7d28e17f72",
            "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855",
            891568578, "aGVsbG8=", "hello", "4142", "AB",
        )]

    def test_regexp_count_position(self, runner):
        rows = runner.execute(
            "SELECT regexp_count('a1b2c3', '[0-9]'), "
            "regexp_position('xxy7', '[0-9]'), regexp_position('xxy', '[0-9]')"
        ).rows
        assert rows == [(3, 4, -1)]

    def test_luhn_and_iso_date(self, runner):
        import datetime

        rows = runner.execute(
            "SELECT luhn_check('79927398713'), luhn_check('79927398714'), "
            "from_iso8601_date('2001-08-22')"
        ).rows
        assert rows == [(True, False, datetime.date(2001, 8, 22))]

    def test_timezone_extracts(self, runner):
        rows = runner.execute(
            "SELECT timezone_hour(TIMESTAMP '2001-08-22 03:04:05.321 +07:09'), "
            "timezone_minute(TIMESTAMP '2001-08-22 03:04:05.321 +07:09')"
        ).rows
        assert rows == [(7, 9)]

    def test_normalize(self, runner):
        rows = runner.execute("SELECT normalize('café')").rows
        assert rows == [("café",)]
