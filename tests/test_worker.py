"""Worker task API: fragments dispatched over HTTP to worker servers
(TaskResource/HttpRemoteTask analogue, SURVEY.md §3.2)."""

import urllib.error
import urllib.request

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.metadata import CatalogManager, Session
from trino_tpu.parallel.runner import DistributedQueryRunner
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.server.worker import WorkerServer

SCALE = 0.0005


def _worker_catalogs():
    c = CatalogManager()
    c.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    return c


@pytest.fixture(scope="module")
def workers():
    w1 = WorkerServer(_worker_catalogs()).start()
    w2 = WorkerServer(_worker_catalogs()).start()
    yield [w1, w2]
    w1.stop()
    w2.stop()


@pytest.fixture(scope="module")
def remote_dist(workers):
    dist = DistributedQueryRunner(
        Session(catalog="tpch", schema="sf0_0005"),
        n_workers=4,
        worker_urls=[f"http://{w.address}" for w in workers],
    )
    dist.catalogs.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    return dist


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner.tpch(scale=SCALE)


class TestRemoteWorkers:
    QUERIES = [
        "SELECT count(*), sum(l_quantity) FROM lineitem",
        "SELECT l_returnflag, count(*) c, avg(l_quantity) a FROM lineitem GROUP BY 1 ORDER BY 1",
        "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey WHERE l_quantity < 10",
        "SELECT c_mktsegment, count(*) FROM customer JOIN nation ON c_nationkey = n_nationkey GROUP BY 1 ORDER BY 1",
    ]

    @pytest.mark.parametrize("sql", QUERIES)
    def test_parity_with_local(self, remote_dist, local, sql):
        a = remote_dist.execute(sql).rows
        b = local.execute(sql).rows
        assert len(a) == len(b)
        for ra, rb in zip(a, b):
            for va, vb in zip(ra, rb):
                if isinstance(va, float):
                    assert abs(va - vb) <= 1e-9 * max(1.0, abs(vb))
                else:
                    assert va == vb

    def test_task_error_propagates(self, workers):
        # garbage task body -> HTTP 500 with the error text
        req = urllib.request.Request(
            f"http://{workers[0].address}/v1/task/bogus",
            data=b"not a pickle",
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 500

    def test_unknown_route(self, workers):
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{workers[0].address}/v1/bogus", data=b"", method="POST"
                )
            )
        assert e.value.code == 404
