"""Array/Map/Row types: constructors, accessors, functions, UNNEST, array_agg.

Model: the reference's TestArrayOperators / TestMapOperators /
TestRowOperator + AbstractTestQueries UNNEST coverage (operator/scalar tests,
operator/unnest/UnnestOperator). The TPU layout under test is the pad-and-mask
[cap, W] lane design (spi.types.ArrayType docstring).
"""

import pytest


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime import LocalQueryRunner

    r = LocalQueryRunner.tpch(scale=0.0005)
    r.register_catalog("memory", MemoryConnector())
    return r


def one(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


class TestArrayScalars:
    def test_constructor_and_subscript(self, runner):
        assert one(runner, "SELECT ARRAY[1, 2, 3]") == ([1, 2, 3],)
        assert one(runner, "SELECT ARRAY[1, 2, 3][2]") == (2,)
        assert one(runner, "SELECT ARRAY['x','y'][1]") == ("x",)

    def test_cardinality(self, runner):
        assert one(runner, "SELECT cardinality(ARRAY[1,2,3]), cardinality(ARRAY[])") == (3, 0)
        assert one(runner, "SELECT cardinality(CAST(NULL AS array(bigint)))") == (None,)

    def test_contains_and_position(self, runner):
        assert one(runner, "SELECT contains(ARRAY[1,2,3], 2), contains(ARRAY[1,2,3], 9)") == (True, False)
        assert one(runner, "SELECT array_position(ARRAY['a','b','c'], 'b')") == (2,)
        assert one(runner, "SELECT array_position(ARRAY[1,2], 9)") == (0,)

    def test_contains_null_semantics(self, runner):
        # no match + null element present -> NULL (3VL)
        assert one(runner, "SELECT contains(ARRAY[1, NULL], 9)") == (None,)
        assert one(runner, "SELECT contains(ARRAY[1, NULL], 1)") == (True,)

    def test_element_at_out_of_bounds_is_null(self, runner):
        assert one(runner, "SELECT element_at(ARRAY[10,20], 5)") == (None,)
        assert one(runner, "SELECT element_at(ARRAY[10,20], 2)") == (20,)

    def test_min_max_sort_distinct(self, runner):
        assert one(runner, "SELECT array_min(ARRAY[3,1,2]), array_max(ARRAY[3,1,2])") == (1, 3)
        assert one(runner, "SELECT array_sort(ARRAY[3,1,2])") == ([1, 2, 3],)
        assert one(runner, "SELECT array_distinct(ARRAY[1,2,1,3,2])") == ([1, 2, 3],)
        # null element poisons min/max (reference semantics)
        assert one(runner, "SELECT array_min(ARRAY[1, NULL])") == (None,)

    def test_concat_and_slice(self, runner):
        assert one(runner, "SELECT ARRAY[1,2] || ARRAY[3]") == ([1, 2, 3],)
        assert one(runner, "SELECT concat(ARRAY[1], ARRAY[2], ARRAY[3])") == ([1, 2, 3],)
        assert one(runner, "SELECT slice(ARRAY[1,2,3,4], 2, 2)") == ([2, 3],)
        assert one(runner, "SELECT slice(ARRAY[1,2,3,4], -2, 2)") == ([3, 4],)

    def test_string_arrays_merge_dictionaries(self, runner):
        assert one(runner, "SELECT ARRAY['b','a'] || ARRAY['c']") == (["b", "a", "c"],)
        assert one(runner, "SELECT array_sort(ARRAY['b','c','a'])") == (["a", "b", "c"],)


class TestMapRow:
    def test_map_constructor_subscript(self, runner):
        assert one(runner, "SELECT map(ARRAY['a','b'], ARRAY[1,2])['b']") == (2,)
        assert one(runner, "SELECT element_at(map(ARRAY['a'], ARRAY[1]), 'z')") == (None,)

    def test_map_keys_values_cardinality(self, runner):
        assert one(
            runner,
            "SELECT map_keys(map(ARRAY['a','b'], ARRAY[1,2])), "
            "map_values(map(ARRAY['a','b'], ARRAY[1,2])), "
            "cardinality(map(ARRAY['a','b'], ARRAY[1,2]))",
        ) == (["a", "b"], [1, 2], 2)

    def test_row_constructor_and_subscript(self, runner):
        assert one(runner, "SELECT ROW(1, 'x')[1]") == (1,)
        assert one(runner, "SELECT ROW(1, 'x')[2]") == ("x",)

    def test_map_decode(self, runner):
        assert one(runner, "SELECT map(ARRAY['x','y'], ARRAY[1,2])") == ({"x": 1, "y": 2},)


class TestUnnest:
    def test_bare_unnest(self, runner):
        rows = runner.execute("SELECT t.x FROM UNNEST(ARRAY[1,2,3]) AS t(x)").rows
        assert rows == [(1,), (2,), (3,)]

    def test_with_ordinality(self, runner):
        rows = runner.execute(
            "SELECT x, o FROM UNNEST(ARRAY[10,20]) WITH ORDINALITY AS t(x, o)"
        ).rows
        assert rows == [(10, 1), (20, 2)]

    def test_zip_pads_shorter_with_null(self, runner):
        rows = runner.execute(
            "SELECT a, b FROM UNNEST(ARRAY[1,2,3], ARRAY['p','q']) AS u(a, b)"
        ).rows
        assert rows == [(1, "p"), (2, "q"), (3, None)]

    def test_map_unnest(self, runner):
        rows = runner.execute(
            "SELECT k, v FROM UNNEST(map(ARRAY['x','y'], ARRAY[1,2])) AS u(k, v) ORDER BY k"
        ).rows
        assert rows == [("x", 1), ("y", 2)]

    def test_null_array_produces_no_rows(self, runner):
        rows = runner.execute(
            "SELECT e FROM UNNEST(CAST(NULL AS array(bigint))) AS u(e)"
        ).rows
        assert rows == []

    def test_correlated_cross_join_unnest(self, runner):
        runner.execute(
            "CREATE TABLE memory.default.nt AS "
            "SELECT 1 AS id, ARRAY[10,20] AS a UNION ALL SELECT 2, ARRAY[30]"
        )
        rows = runner.execute(
            "SELECT id, e FROM memory.default.nt CROSS JOIN UNNEST(a) AS u(e) "
            "ORDER BY id, e"
        ).rows
        assert rows == [(1, 10), (1, 20), (2, 30)]
        rows = runner.execute(
            "SELECT id, sum(e) FROM memory.default.nt CROSS JOIN UNNEST(a) AS u(e) "
            "GROUP BY id ORDER BY id"
        ).rows
        assert rows == [(1, 30), (2, 30)]


class TestReviewRegressions:
    def test_inner_join_unnest_applies_on_condition(self, runner):
        assert one(
            runner,
            "SELECT count(*) FROM orders INNER JOIN UNNEST(ARRAY[1]) AS t(x) "
            "ON o_orderkey = 999999999",
        ) == (0,)
        n_orders = one(runner, "SELECT count(*) FROM orders")[0]
        assert one(
            runner,
            "SELECT count(*) FROM orders INNER JOIN UNNEST(ARRAY[1,2]) AS t(x) ON x = 2",
        ) == (n_orders,)

    def test_null_string_element(self, runner):
        assert one(runner, "SELECT ARRAY['a', NULL]") == (["a", None],)

    def test_dictionary_flows_through_accessors(self, runner):
        assert one(
            runner,
            "SELECT ROW('x', 1)[1] = 'x', "
            "element_at(map(ARRAY[1,2], ARRAY['a','b']), 2), "
            "upper(ROW('x',1)[1]), map_values(map(ARRAY[1], ARRAY['z']))",
        ) == (True, "b", "X", ["z"])

    def test_array_distinct_keeps_first_occurrence_order(self, runner):
        assert one(runner, "SELECT array_distinct(ARRAY[3, 1, 3, NULL, 1, NULL])") == (
            [3, 1, None],
        )


class TestArrayAgg:
    def test_grouped(self, runner):
        rows = runner.execute(
            "SELECT l_returnflag, array_agg(l_linenumber) FROM lineitem "
            "WHERE l_orderkey < 10 GROUP BY l_returnflag ORDER BY l_returnflag"
        ).rows
        from tests.oracle import tpch_df

        li = tpch_df("lineitem", 0.0005)
        m = li[li.l_orderkey < 10]
        want = m.groupby("l_returnflag").l_linenumber.apply(list).sort_index()
        assert [r[0] for r in rows] == list(want.index)
        for (_, got), (_, w) in zip(rows, want.items()):
            assert sorted(got) == sorted(w)

    def test_global_and_roundtrip(self, runner):
        assert one(runner, "SELECT cardinality(array_agg(l_orderkey)) FROM lineitem")[0] > 0
        assert one(runner, "SELECT array_sort(array_agg(DISTINCT l_linestatus)) FROM lineitem") == (["F", "O"],)

    def test_array_agg_then_unnest_roundtrip(self, runner):
        rows = runner.execute(
            "SELECT e FROM (SELECT array_agg(l_linestatus) AS a FROM lineitem "
            "WHERE l_orderkey < 3) CROSS JOIN UNNEST(a) AS u(e)"
        ).rows
        assert len(rows) == 8
