"""Query lifecycle management: state machine, tracking, async execution.

Reference blueprint: io.trino.execution.QueryStateMachine (QueryStateMachine.java:131
over StateMachine.java:43; states QUEUED...FINISHED), QueryTracker.java:51 (expiry),
DispatchManager.createQuery (DispatchManager.java:176). SURVEY.md §2.6.
"""

from __future__ import annotations

import threading
import time
import traceback
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, List, Optional


class QueryState(Enum):
    QUEUED = "QUEUED"
    PLANNING = "PLANNING"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELED = "CANCELED"

    @property
    def is_done(self) -> bool:
        return self in (QueryState.FINISHED, QueryState.FAILED, QueryState.CANCELED)


@dataclass
class QueryStats:
    create_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    cpu_time: float = 0.0
    rows: int = 0

    @property
    def elapsed(self) -> float:
        end = self.end_time or time.time()
        return end - self.create_time


@dataclass
class QueryExecution:
    """One tracked query (SqlQueryExecution + QueryInfo analogue)."""

    query_id: str
    sql: str
    user: str = "user"
    source: str = ""
    resource_group: str = ""
    # client-requested spooled result encoding ("json" / "json+lz4"); None =
    # inline protocol data (ref: protocol/spooling QueryDataEncoding)
    data_encoding: Optional[str] = None
    # protocol-level client session (ClientContext): carries prepared
    # statements + open transaction across pool threads; session-state
    # changes land in client_ctx.updates for the protocol layer
    client_ctx: Optional[Any] = None
    trace_id: Optional[str] = None
    # observability plane: QueryStatsCollector.snapshot() from the runner
    # (device/host/compile attribution + counters; /v1/query surfaces it)
    query_stats: Optional[dict] = None
    state: QueryState = QueryState.QUEUED
    stats: QueryStats = field(default_factory=QueryStats)
    column_names: Optional[List[str]] = None
    column_types: Optional[List[object]] = None
    rows: Optional[List[tuple]] = None
    error: Optional[str] = None
    error_type: Optional[str] = None
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    _done: threading.Event = field(default_factory=threading.Event, repr=False)
    _state_listeners: List[Callable] = field(default_factory=list, repr=False)

    def transition(self, new_state: QueryState) -> None:
        with self._lock:
            if self.state.is_done:
                return
            self.state = new_state
            if new_state.is_done:
                self.stats.end_time = time.time()
                self._done.set()
        for listener in list(self._state_listeners):
            listener(self)

    def wait_done(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class QueryManager:
    """Tracks queries and runs them on a worker pool behind hierarchical
    resource-group admission (DispatchManager + QueryTracker +
    InternalResourceGroup: queries QUEUE at the group's hard concurrency
    limit, are rejected when the queue is full, and dequeue weighted-fair)."""

    def __init__(self, executor_fn: Callable[[str], Any], max_workers: int = 4,
                 max_history: int = 100, max_concurrent: Optional[int] = None,
                 resource_groups=None):
        from .resource_groups import ResourceGroupManager

        import inspect

        self._executor_fn = executor_fn
        try:
            params = inspect.signature(executor_fn).parameters
            self._fn_accepts_user = "user" in params
            self._fn_accepts_client = "client" in params
        except (TypeError, ValueError):
            self._fn_accepts_user = False
            self._fn_accepts_client = False
        self._pool = ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="query")
        self._queries: Dict[str, QueryExecution] = {}
        self._lock = threading.Lock()
        self._max_history = max_history
        self._listeners: List[Callable] = []
        if resource_groups is not None:
            self._groups = resource_groups
        elif max_concurrent:
            self._groups = ResourceGroupManager.default(max_concurrent)
        else:
            self._groups = None

    @property
    def resource_groups(self):
        return self._groups

    def add_listener(self, listener: Callable) -> None:
        """EventListener SPI hook (spi/eventlistener/, dispatched on completion)."""
        self._listeners.append(listener)

    def submit(self, sql: str, user: str = "user", source: str = "",
               data_encoding: Optional[str] = None,
               client_ctx=None) -> QueryExecution:
        from .metrics import REGISTRY

        query_id = f"q_{uuid.uuid4().hex[:16]}"
        q = QueryExecution(
            query_id=query_id, sql=sql, user=user, source=source,
            data_encoding=data_encoding, client_ctx=client_ctx,
        )
        with self._lock:
            self._queries[query_id] = q
            self._expire_old()
        REGISTRY.counter(
            "trino_tpu_queries_submitted_total", help="queries submitted"
        ).inc()
        self._pool.submit(self._run, q)
        return q

    def get(self, query_id: str) -> Optional[QueryExecution]:
        with self._lock:
            return self._queries.get(query_id)

    def list_queries(self) -> List[QueryExecution]:
        with self._lock:
            return list(self._queries.values())

    def cancel(self, query_id: str) -> bool:
        q = self.get(query_id)
        if q is None:
            return False
        q.transition(QueryState.CANCELED)
        return True

    def _run(self, q: QueryExecution) -> None:
        if q.state.is_done:
            return
        if self._groups is None:
            self._run_admitted(q)
            return
        from .resource_groups import QueryQueueFullError

        try:
            ticket = self._groups.submit(q.user, q.source)
        except QueryQueueFullError as e:
            q.error = str(e)
            q.error_type = "QueryQueueFullError"
            q.transition(QueryState.FAILED)
            for listener in self._listeners:
                try:
                    listener(q)
                except Exception:
                    traceback.print_exc()
            return
        q.resource_group = ticket.group.path
        try:
            # stays QUEUED until the group grants a concurrency slot
            while not ticket.event.wait(timeout=0.5):
                if q.state.is_done:  # canceled while queued
                    self._groups.cancel(ticket)
                    return
            if ticket.canceled:
                return
            self._run_admitted(q)
        finally:
            self._groups.finish(ticket)

    def _run_admitted(self, q: QueryExecution) -> None:
        from .metrics import REGISTRY

        if q.state.is_done:
            return
        q.transition(QueryState.PLANNING)
        running = REGISTRY.gauge(
            "trino_tpu_queries_running", help="queries currently executing"
        )
        running.inc()
        t0 = time.time()
        try:
            q.transition(QueryState.RUNNING)
            # propagate the authenticated principal so access control checks
            # run against the submitting user, not the shared session default
            kwargs = {}
            if self._fn_accepts_user:
                kwargs["user"] = q.user
            if self._fn_accepts_client and q.client_ctx is not None:
                kwargs["client"] = q.client_ctx
            result = self._executor_fn(q.sql, **kwargs)
            q.column_names = result.column_names
            q.column_types = getattr(result, "column_types", None)
            q.trace_id = getattr(result, "trace_id", None)
            q.query_stats = getattr(result, "query_stats", None)
            q.rows = result.rows
            q.stats.rows = len(result.rows)
            q.stats.cpu_time = time.time() - t0
            q.transition(QueryState.FINISHED)
            REGISTRY.counter(
                "trino_tpu_queries_finished_total", help="queries finished"
            ).inc()
            REGISTRY.counter(
                "trino_tpu_rows_produced_total", help="result rows produced"
            ).inc(len(result.rows))
        except Exception as e:  # noqa: BLE001 — error surface is the protocol
            q.error = str(e)
            q.error_type = type(e).__name__
            q.stats.cpu_time = time.time() - t0
            q.transition(QueryState.FAILED)
            REGISTRY.counter(
                "trino_tpu_queries_failed_total", help="queries failed"
            ).inc()
        finally:
            running.dec()
            REGISTRY.histogram(
                "trino_tpu_query_duration_secs",
                help="end-to-end query wall time",
            ).observe(time.time() - t0)
        for listener in self._listeners:
            try:
                listener(q)
            except Exception:
                traceback.print_exc()

    def _expire_old(self) -> None:
        # QueryTracker-style history cap
        if len(self._queries) <= self._max_history:
            return
        done = [q for q in self._queries.values() if q.state.is_done]
        done.sort(key=lambda q: q.stats.end_time or 0)
        for q in done[: len(self._queries) - self._max_history]:
            self._queries.pop(q.query_id, None)
