"""Real TPC-H queries vs the pandas oracle (the BASELINE.md workload ladder:
Q6 scan+filter+sum, Q1 multi-key group-by, Q3/Q14 joins, Q13 left join,
Q18 having+in-subquery+joins, Q5 six-way join)."""

import datetime

import numpy as np
import pandas as pd
import pytest

from tests.oracle import tpch_df, assert_rows_equal

SCALE = 0.0005
EPOCH = datetime.date(1970, 1, 1)


def days(iso: str) -> int:
    return (datetime.date.fromisoformat(iso) - EPOCH).days


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


def test_q6(runner):
    res = runner.execute(
        """
        SELECT sum(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
          AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
          AND l_quantity < 24
        """
    )
    li = tpch_df("lineitem", SCALE)
    m = li[
        (li.l_shipdate >= days("1994-01-01"))
        & (li.l_shipdate < days("1995-01-01"))
        & (li.l_discount >= 0.05)
        & (li.l_discount <= 0.07)
        & (li.l_quantity < 24)
    ]
    expected = (m.l_extendedprice * m.l_discount).sum()
    assert_rows_equal(res.rows, [(expected,)], float_tol=1e-9)


def test_q1(runner):
    res = runner.execute(
        """
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               avg(l_quantity) AS avg_qty,
               avg(l_extendedprice) AS avg_price,
               avg(l_discount) AS avg_disc,
               count(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
        """
    )
    li = tpch_df("lineitem", SCALE)
    m = li[li.l_shipdate <= days("1998-12-01") - 90].copy()
    m["disc_price"] = m.l_extendedprice * (1 - m.l_discount)
    m["charge"] = m.disc_price * (1 + m.l_tax)
    g = (
        m.groupby(["l_returnflag", "l_linestatus"])
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_orderkey", "count"),
        )
        .reset_index()
        .sort_values(["l_returnflag", "l_linestatus"])
    )
    # decimal avg columns round to the column scale (Trino semantics)
    g["avg_qty"] = g.avg_qty.round(2)
    g["avg_price"] = g.avg_price.round(2)
    g["avg_disc"] = g.avg_disc.round(2)
    assert_rows_equal(
        res.rows, [tuple(r) for r in g.itertuples(index=False)], float_tol=1e-9
    )


def test_q3(runner):
    res = runner.execute(
        """
        SELECT l_orderkey,
               sum(l_extendedprice * (1 - l_discount)) AS revenue,
               o_orderdate, o_shippriority
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING'
          AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND o_orderdate < DATE '1995-03-15'
          AND l_shipdate > DATE '1995-03-15'
        GROUP BY l_orderkey, o_orderdate, o_shippriority
        ORDER BY revenue DESC, o_orderdate, l_orderkey
        LIMIT 10
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    m = (
        c[c.c_mktsegment == "BUILDING"]
        .merge(o[o.o_orderdate < days("1995-03-15")], left_on="c_custkey", right_on="o_custkey")
        .merge(li[li.l_shipdate > days("1995-03-15")], left_on="o_orderkey", right_on="l_orderkey")
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"]
        .sum()
        .reset_index()
        .sort_values(["revenue", "o_orderdate", "l_orderkey"], ascending=[False, True, True])
        .head(10)
    )
    assert_rows_equal(
        res.rows,
        [
            (int(r.l_orderkey), round(r.revenue, 4), int(r.o_orderdate), int(r.o_shippriority))
            for r in g.itertuples()
        ],
        float_tol=1e-9,
    )


def test_q5(runner):
    res = runner.execute(
        """
        SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM customer, orders, lineitem, supplier, nation, region
        WHERE c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND l_suppkey = s_suppkey
          AND c_nationkey = s_nationkey
          AND s_nationkey = n_nationkey
          AND n_regionkey = r_regionkey
          AND r_name = 'ASIA'
          AND o_orderdate >= DATE '1994-01-01'
          AND o_orderdate < DATE '1995-01-01'
        GROUP BY n_name
        ORDER BY revenue DESC
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    s = tpch_df("supplier", SCALE)
    n = tpch_df("nation", SCALE)
    r = tpch_df("region", SCALE)
    m = (
        c.merge(o[(o.o_orderdate >= days("1994-01-01")) & (o.o_orderdate < days("1995-01-01"))],
                left_on="c_custkey", right_on="o_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
    )
    m = m[m.c_nationkey == m.s_nationkey]
    m = m.merge(n, left_on="s_nationkey", right_on="n_nationkey").merge(
        r[r.r_name == "ASIA"], left_on="n_regionkey", right_on="r_regionkey"
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = m.groupby("n_name")["revenue"].sum().reset_index().sort_values("revenue", ascending=False)
    assert_rows_equal(
        res.rows,
        [(r_.n_name, round(r_.revenue, 4)) for r_ in g.itertuples()],
        float_tol=1e-9,
    )


def test_q13(runner):
    res = runner.execute(
        """
        SELECT c_count, count(*) AS custdist
        FROM (
          SELECT c_custkey, count(o_orderkey) AS c_count
          FROM customer LEFT JOIN orders ON c_custkey = o_custkey
            AND o_comment NOT LIKE '%special%requests%'
          GROUP BY c_custkey
        ) AS c_orders
        GROUP BY c_count
        ORDER BY custdist DESC, c_count DESC
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    of = o[~o.o_comment.str.contains("special.*requests", regex=True)]
    m = c.merge(of, left_on="c_custkey", right_on="o_custkey", how="left")
    cc = m.groupby("c_custkey")["o_orderkey"].count().reset_index(name="c_count")
    cd = (
        cc.groupby("c_count").size().reset_index(name="custdist")
        .sort_values(["custdist", "c_count"], ascending=[False, False])
    )
    assert_rows_equal(
        res.rows, [(int(r.c_count), int(r.custdist)) for r in cd.itertuples()]
    )


def test_q14(runner):
    res = runner.execute(
        """
        SELECT 100.00 * sum(CASE WHEN p_type LIKE 'PROMO%'
                                 THEN l_extendedprice * (1 - l_discount)
                                 ELSE 0 END)
               / sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
        FROM lineitem, part
        WHERE l_partkey = p_partkey
          AND l_shipdate >= DATE '1995-09-01'
          AND l_shipdate < DATE '1995-10-01'
        """
    )
    li = tpch_df("lineitem", SCALE)
    p = tpch_df("part", SCALE)
    m = li[(li.l_shipdate >= days("1995-09-01")) & (li.l_shipdate < days("1995-10-01"))].merge(
        p, left_on="l_partkey", right_on="p_partkey"
    )
    disc = m.l_extendedprice * (1 - m.l_discount)
    promo = disc.where(m.p_type.str.startswith("PROMO"), 0.0)
    expected = 100.0 * promo.sum() / disc.sum()
    assert_rows_equal(res.rows, [(expected,)], float_tol=1e-9)


def test_q18(runner):
    res = runner.execute(
        """
        SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity)
        FROM customer, orders, lineitem
        WHERE o_orderkey IN (
            SELECT l_orderkey FROM lineitem
            GROUP BY l_orderkey HAVING sum(l_quantity) > 150
          )
          AND c_custkey = o_custkey
          AND o_orderkey = l_orderkey
        GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        ORDER BY o_totalprice DESC, o_orderdate, o_orderkey
        LIMIT 100
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    big = li.groupby("l_orderkey")["l_quantity"].sum()
    big = set(big[big > 150].index)
    m = (
        c.merge(o[o.o_orderkey.isin(big)], left_on="c_custkey", right_on="o_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
    )
    g = (
        m.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"])["l_quantity"]
        .sum()
        .reset_index()
        .sort_values(["o_totalprice", "o_orderdate", "o_orderkey"], ascending=[False, True, True])
        .head(100)
    )
    assert_rows_equal(
        res.rows,
        [
            (r.c_name, int(r.c_custkey), int(r.o_orderkey), int(r.o_orderdate),
             r.o_totalprice, r.l_quantity)
            for r in g.itertuples()
        ],
        float_tol=1e-9,
    )


def test_q12(runner):
    res = runner.execute(
        """
        SELECT l_shipmode,
               sum(CASE WHEN o_orderpriority = '1-URGENT' OR o_orderpriority = '2-HIGH'
                        THEN 1 ELSE 0 END) AS high_line_count,
               sum(CASE WHEN o_orderpriority <> '1-URGENT' AND o_orderpriority <> '2-HIGH'
                        THEN 1 ELSE 0 END) AS low_line_count
        FROM orders, lineitem
        WHERE o_orderkey = l_orderkey
          AND l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate
          AND l_shipdate < l_commitdate
          AND l_receiptdate >= DATE '1994-01-01'
          AND l_receiptdate < DATE '1995-01-01'
        GROUP BY l_shipmode
        ORDER BY l_shipmode
        """
    )
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    m = li[
        li.l_shipmode.isin(["MAIL", "SHIP"])
        & (li.l_commitdate < li.l_receiptdate)
        & (li.l_shipdate < li.l_commitdate)
        & (li.l_receiptdate >= days("1994-01-01"))
        & (li.l_receiptdate < days("1995-01-01"))
    ].merge(o, left_on="l_orderkey", right_on="o_orderkey")
    high = m.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    g = (
        m.assign(h=high.astype(int), l=(~high).astype(int))
        .groupby("l_shipmode")
        .agg(h=("h", "sum"), l=("l", "sum"))
        .reset_index()
        .sort_values("l_shipmode")
    )
    assert_rows_equal(
        res.rows, [(r.l_shipmode, int(r.h), int(r.l)) for r in g.itertuples()]
    )


def test_q19_simplified(runner):
    # Q19's OR-of-ANDs over two tables (quantity windows x brand x container)
    res = runner.execute(
        """
        SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem, part
        WHERE p_partkey = l_partkey
          AND ((p_brand = 'Brand#12' AND l_quantity BETWEEN 1 AND 11)
            OR (p_brand = 'Brand#23' AND l_quantity BETWEEN 10 AND 20))
        """
    )
    li = tpch_df("lineitem", SCALE)
    p = tpch_df("part", SCALE)
    m = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    cond = ((m.p_brand == "Brand#12") & m.l_quantity.between(1, 11)) | (
        (m.p_brand == "Brand#23") & m.l_quantity.between(10, 20)
    )
    expected = (m[cond].l_extendedprice * (1 - m[cond].l_discount)).sum()
    assert_rows_equal(res.rows, [(round(expected, 4),)], float_tol=1e-9)


def test_q7(runner):
    res = runner.execute(
        """
        SELECT supp_nation, cust_nation, l_year, sum(volume) AS revenue FROM (
          SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
                 EXTRACT(YEAR FROM l_shipdate) AS l_year,
                 l_extendedprice * (1 - l_discount) AS volume
          FROM supplier, lineitem, orders, customer, nation n1, nation n2
          WHERE s_suppkey = l_suppkey AND o_orderkey = l_orderkey AND c_custkey = o_custkey
            AND s_nationkey = n1.n_nationkey AND c_nationkey = n2.n_nationkey
            AND ((n1.n_name = 'FRANCE' AND n2.n_name = 'GERMANY')
              OR (n1.n_name = 'GERMANY' AND n2.n_name = 'FRANCE'))
            AND l_shipdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31') AS shipping
        GROUP BY supp_nation, cust_nation, l_year
        ORDER BY supp_nation, cust_nation, l_year
        """
    )
    s = tpch_df("supplier", SCALE)
    li = tpch_df("lineitem", SCALE)
    o = tpch_df("orders", SCALE)
    c = tpch_df("customer", SCALE)
    n = tpch_df("nation", SCALE)
    m = (
        li[(li.l_shipdate >= days("1995-01-01")) & (li.l_shipdate <= days("1996-12-31"))]
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n.add_suffix("_1"), left_on="s_nationkey", right_on="n_nationkey_1")
        .merge(n.add_suffix("_2"), left_on="c_nationkey", right_on="n_nationkey_2")
    )
    m = m[
        ((m.n_name_1 == "FRANCE") & (m.n_name_2 == "GERMANY"))
        | ((m.n_name_1 == "GERMANY") & (m.n_name_2 == "FRANCE"))
    ].copy()
    m["l_year"] = pd.to_datetime(m.l_shipdate, unit="D").dt.year
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby(["n_name_1", "n_name_2", "l_year"])["volume"].sum().reset_index()
        .sort_values(["n_name_1", "n_name_2", "l_year"])
    )
    assert_rows_equal(
        res.rows,
        [(r_.n_name_1, r_.n_name_2, int(r_.l_year), round(r_.volume, 4)) for r_ in g.itertuples()],
        float_tol=1e-9,
    )


def test_q9(runner):
    res = runner.execute(
        """
        SELECT nation, o_year, sum(amount) AS sum_profit FROM (
          SELECT n_name AS nation, EXTRACT(YEAR FROM o_orderdate) AS o_year,
                 l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity AS amount
          FROM part, supplier, lineitem, partsupp, orders, nation
          WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey AND ps_partkey = l_partkey
            AND p_partkey = l_partkey AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
            AND p_name LIKE '%green%') AS profit
        GROUP BY nation, o_year
        ORDER BY nation, o_year DESC
        """
    )
    p = tpch_df("part", SCALE)
    s = tpch_df("supplier", SCALE)
    li = tpch_df("lineitem", SCALE)
    ps = tpch_df("partsupp", SCALE)
    o = tpch_df("orders", SCALE)
    n = tpch_df("nation", SCALE)
    m = (
        li.merge(p[p.p_name.str.contains("green")], left_on="l_partkey", right_on="p_partkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(ps, left_on=["l_partkey", "l_suppkey"], right_on=["ps_partkey", "ps_suppkey"])
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
    )
    m = m.copy()
    m["o_year"] = pd.to_datetime(m.o_orderdate, unit="D").dt.year
    m["amount"] = m.l_extendedprice * (1 - m.l_discount) - m.ps_supplycost * m.l_quantity
    g = (
        m.groupby(["n_name", "o_year"])["amount"].sum().reset_index()
        .sort_values(["n_name", "o_year"], ascending=[True, False])
    )
    assert_rows_equal(
        res.rows,
        [(r_.n_name, int(r_.o_year), round(r_.amount, 4)) for r_ in g.itertuples()],
        float_tol=1e-9,
    )


def test_q10(runner):
    res = runner.execute(
        """
        SELECT c_custkey, c_name, sum(l_extendedprice * (1 - l_discount)) AS revenue, c_acctbal
        FROM customer, orders, lineitem, nation
        WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
          AND o_orderdate >= DATE '1993-10-01' AND o_orderdate < DATE '1994-01-01'
          AND l_returnflag = 'R' AND c_nationkey = n_nationkey
        GROUP BY c_custkey, c_name, c_acctbal
        ORDER BY revenue DESC, c_custkey
        LIMIT 20
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    n = tpch_df("nation", SCALE)
    m = (
        c.merge(
            o[(o.o_orderdate >= days("1993-10-01")) & (o.o_orderdate < days("1994-01-01"))],
            left_on="c_custkey", right_on="o_custkey",
        )
        .merge(li[li.l_returnflag == "R"], left_on="o_orderkey", right_on="l_orderkey")
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby(["c_custkey", "c_name", "c_acctbal"])["revenue"].sum().reset_index()
        .sort_values(["revenue", "c_custkey"], ascending=[False, True]).head(20)
    )
    assert_rows_equal(
        res.rows,
        [
            (int(r_.c_custkey), r_.c_name, round(r_.revenue, 4), r_.c_acctbal)
            for r_ in g.itertuples()
        ],
        float_tol=1e-9,
    )


def test_q11(runner):
    res = runner.execute(
        """
        SELECT ps_partkey, sum(ps_supplycost * ps_availqty) AS value
        FROM partsupp, supplier, nation
        WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey AND n_name = 'GERMANY'
        GROUP BY ps_partkey
        HAVING sum(ps_supplycost * ps_availqty) > (
          SELECT sum(ps_supplycost * ps_availqty) * 0.0001
          FROM partsupp, supplier, nation
          WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey AND n_name = 'GERMANY')
        ORDER BY value DESC, ps_partkey
        """
    )
    ps = tpch_df("partsupp", SCALE)
    s = tpch_df("supplier", SCALE)
    n = tpch_df("nation", SCALE)
    m = ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey").merge(
        n[n.n_name == "GERMANY"], left_on="s_nationkey", right_on="n_nationkey"
    )
    m["value"] = m.ps_supplycost * m.ps_availqty
    g = m.groupby("ps_partkey")["value"].sum().reset_index()
    threshold = m.value.sum() * 0.0001
    g = g[g.value > threshold].sort_values(["value", "ps_partkey"], ascending=[False, True])
    assert_rows_equal(
        res.rows,
        [(int(r_.ps_partkey), round(r_.value, 4)) for r_ in g.itertuples()],
        float_tol=1e-9,
    )


def test_q15(runner):
    res = runner.execute(
        """
        WITH revenue0 AS (
          SELECT l_suppkey AS supplier_no, sum(l_extendedprice * (1 - l_discount)) AS total_revenue
          FROM lineitem
          WHERE l_shipdate >= DATE '1996-01-01' AND l_shipdate < DATE '1996-04-01'
          GROUP BY l_suppkey)
        SELECT s_suppkey, s_name, total_revenue
        FROM supplier, revenue0
        WHERE s_suppkey = supplier_no AND total_revenue = (SELECT max(total_revenue) FROM revenue0)
        ORDER BY s_suppkey
        """
    )
    li = tpch_df("lineitem", SCALE)
    s = tpch_df("supplier", SCALE)
    rev = (
        li[(li.l_shipdate >= days("1996-01-01")) & (li.l_shipdate < days("1996-04-01"))]
        .assign(rev=lambda d: d.l_extendedprice * (1 - d.l_discount))
        .groupby("l_suppkey")["rev"].sum()
    )
    top = rev[rev.round(4) == round(rev.max(), 4)]
    m = s[s.s_suppkey.isin(top.index)].sort_values("s_suppkey")
    assert_rows_equal(
        res.rows,
        [(int(r_.s_suppkey), r_.s_name, round(rev[r_.s_suppkey], 4)) for r_ in m.itertuples()],
        float_tol=1e-9,
    )


def test_q16(runner):
    res = runner.execute(
        """
        SELECT p_brand, p_type, p_size, count(DISTINCT ps_suppkey) AS supplier_cnt
        FROM partsupp, part
        WHERE p_partkey = ps_partkey AND p_brand <> 'Brand#45'
          AND p_type NOT LIKE 'MEDIUM POLISHED%'
          AND p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
        GROUP BY p_brand, p_type, p_size
        ORDER BY supplier_cnt DESC, p_brand, p_type, p_size
        """
    )
    ps = tpch_df("partsupp", SCALE)
    p = tpch_df("part", SCALE)
    pf = p[
        (p.p_brand != "Brand#45")
        & ~p.p_type.str.startswith("MEDIUM POLISHED")
        & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])
    ]
    m = ps.merge(pf, left_on="ps_partkey", right_on="p_partkey")
    g = (
        m.groupby(["p_brand", "p_type", "p_size"])["ps_suppkey"].nunique().reset_index(name="cnt")
        .sort_values(["cnt", "p_brand", "p_type", "p_size"], ascending=[False, True, True, True])
    )
    assert_rows_equal(
        res.rows,
        [(r_.p_brand, r_.p_type, int(r_.p_size), int(r_.cnt)) for r_ in g.itertuples()],
    )


def test_q4(runner):
    res = runner.execute(
        """
        SELECT o_orderpriority, count(*) AS order_count
        FROM orders
        WHERE o_orderdate >= DATE '1993-07-01' AND o_orderdate < DATE '1993-10-01'
          AND EXISTS (SELECT * FROM lineitem
                      WHERE l_orderkey = o_orderkey AND l_commitdate < l_receiptdate)
        GROUP BY o_orderpriority ORDER BY o_orderpriority
        """
    )
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    good = set(li[li.l_commitdate < li.l_receiptdate].l_orderkey)
    m = o[
        (o.o_orderdate >= days("1993-07-01"))
        & (o.o_orderdate < days("1993-10-01"))
        & o.o_orderkey.isin(good)
    ]
    exp = m.groupby("o_orderpriority").size().reset_index(name="c").sort_values("o_orderpriority")
    assert_rows_equal(res.rows, [tuple(r) for r in exp.itertuples(index=False)])


def test_q17(runner):
    res = runner.execute(
        """
        SELECT sum(l_extendedprice) / 7.0 AS avg_yearly
        FROM lineitem, part
        WHERE p_partkey = l_partkey AND p_brand = 'Brand#23'
          AND l_quantity < (SELECT 0.2 * avg(l_quantity) FROM lineitem l2
                            WHERE l2.l_partkey = p_partkey)
        """
    )
    li = tpch_df("lineitem", SCALE)
    p = tpch_df("part", SCALE)
    avg_by_part = li.groupby("l_partkey")["l_quantity"].mean()
    m = li.merge(p[p.p_brand == "Brand#23"], left_on="l_partkey", right_on="p_partkey")
    m = m[m.l_quantity < 0.2 * m.l_partkey.map(avg_by_part)]
    expected = m.l_extendedprice.sum() / 7.0 if len(m) else None
    got = res.rows[0][0]
    if expected is None:
        assert got is None
    else:
        assert abs(got - expected) <= 1e-9 * max(1.0, abs(expected))


def test_q22_shape(runner):
    res = runner.execute(
        """
        SELECT count(*) FROM customer
        WHERE c_acctbal > 500
          AND NOT EXISTS (SELECT * FROM orders
                          WHERE o_custkey = c_custkey AND o_totalprice > 100000)
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    has_big = set(o[o.o_totalprice > 100000].o_custkey)
    exp = int(((c.c_acctbal > 500) & ~c.c_custkey.isin(has_big)).sum())
    assert res.rows == [(exp,)]


def test_q2_shape(runner):
    res = runner.execute(
        """
        SELECT s_name, p_partkey, ps_supplycost
        FROM part, supplier, partsupp
        WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
          AND ps_supplycost = (SELECT min(ps_supplycost) FROM partsupp ps2
                               WHERE ps2.ps_partkey = p_partkey)
        ORDER BY p_partkey, s_name LIMIT 10
        """
    )
    p = tpch_df("part", SCALE)
    s = tpch_df("supplier", SCALE)
    ps = tpch_df("partsupp", SCALE)
    min_cost = ps.groupby("ps_partkey")["ps_supplycost"].min()
    m = ps.merge(p, left_on="ps_partkey", right_on="p_partkey").merge(
        s, left_on="ps_suppkey", right_on="s_suppkey"
    )
    m = m[m.ps_supplycost == m.ps_partkey.map(min_cost)]
    exp = m.sort_values(["p_partkey", "s_name"]).head(10)
    assert_rows_equal(
        res.rows,
        [(r.s_name, int(r.p_partkey), r.ps_supplycost) for r in exp.itertuples()],
        float_tol=1e-9,
    )
