"""Pallas kernel tests (interpret mode on CPU; the real lowering runs on TPU —
verified against XLA on hardware, see BASELINE.md)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trino_tpu.ops.pallas_kernels import BLOCK, q6_fused, q6_reference


def _inputs(n, seed=0, null_rate=0.0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.integers(8000, 10000, n, dtype=np.int32)),
        jnp.asarray(rng.integers(0, 11, n, dtype=np.int32)),
        jnp.asarray(rng.integers(0, 5100, n, dtype=np.int32)),
        jnp.asarray(rng.integers(0, 10**7, n, dtype=np.int32)),
        jnp.asarray((rng.random(n) >= null_rate).astype(np.int32)),
    )


PRED = (8766, 9131, 5, 7, 2400)


class TestQ6Kernel:
    def test_matches_xla(self):
        args = _inputs(BLOCK * 3)
        got = int(q6_fused(*args, *PRED, interpret=True))
        want = int(q6_reference(*args, *PRED))
        assert got == want

    def test_unaligned_length_padded(self):
        args = _inputs(BLOCK * 2 + 12345)
        got = int(q6_fused(*args, *PRED, interpret=True))
        want = int(q6_reference(*args, *PRED))
        assert got == want

    def test_mask_excludes_rows(self):
        args = _inputs(BLOCK, null_rate=0.3)
        got = int(q6_fused(*args, *PRED, interpret=True))
        want = int(q6_reference(*args, *PRED))
        assert got == want

    def test_empty_selection(self):
        args = _inputs(BLOCK)
        # impossible date range selects nothing
        got = int(q6_fused(*args, 0, 0, 5, 7, 2400, interpret=True))
        assert got == 0

    def test_exact_at_int32_product_limit(self):
        # products near int32 max exercise the low/high split recombination
        n = BLOCK
        sd = jnp.full(n, 9000, dtype=jnp.int32)
        disc = jnp.full(n, 7, dtype=jnp.int32)
        qty = jnp.zeros(n, dtype=jnp.int32)
        ep = jnp.full(n, 300_000_000, dtype=jnp.int32)  # 7*3e8 > 2^31? no: 2.1e9 < 2^31-1
        mask = jnp.ones(n, dtype=jnp.int32)
        got = int(q6_fused(sd, disc, qty, ep, mask, *PRED, interpret=True))
        assert got == n * 7 * 300_000_000


from trino_tpu.ops.pallas_kernels import grouped_sum_i32, grouped_sum_i64


class TestGroupedSums:
    def _case(self, n, G, seed=0, lo=-(10**12), hi=10**12):
        rng = np.random.default_rng(seed)
        vals = rng.integers(lo, hi, n, dtype=np.int64)
        gid = rng.integers(0, G, n, dtype=np.int32)
        w = rng.random(n) < 0.8
        want = np.zeros(G, dtype=np.int64)
        np.add.at(want, gid[w], vals[w])
        return jnp.asarray(vals), jnp.asarray(w), jnp.asarray(gid), want

    def test_sum_i64_matches_numpy(self):
        vals, w, gid, want = self._case(BLOCK * 2 + 777, 12)
        got = np.asarray(grouped_sum_i64(vals, w, gid, 12, interpret=True))
        np.testing.assert_array_equal(got, want)

    def test_sum_i64_extreme_magnitudes(self):
        # per-element values near int64 extremes: limb split must stay exact
        # (mod-2^64 wraparound identical to int64 accumulation)
        vals, w, gid, _ = self._case(BLOCK, 5, lo=-(2**62), hi=2**62)
        vnp, wnp, gnp = np.asarray(vals), np.asarray(w), np.asarray(gid)
        want = np.zeros(5, dtype=np.int64)
        np.add.at(want, gnp[wnp], vnp[wnp])
        got = np.asarray(grouped_sum_i64(vals, w, gid, 5, interpret=True))
        np.testing.assert_array_equal(got, want)

    def test_sum_i64_single_group_and_empty_groups(self):
        vals, w, gid, want = self._case(BLOCK, 1)
        got = np.asarray(grouped_sum_i64(vals, w, gid, 1, interpret=True))
        np.testing.assert_array_equal(got, want)
        # group domain larger than any observed gid: tail groups are zero
        got = np.asarray(grouped_sum_i64(vals, w, gid, 7, interpret=True))
        assert got[1:].tolist() == [0] * 6

    def test_sum_i32_count(self):
        rng = np.random.default_rng(3)
        n, G = BLOCK + 99, 9
        gid = rng.integers(0, G, n, dtype=np.int32)
        w = rng.random(n) < 0.5
        want = np.zeros(G, dtype=np.int64)
        np.add.at(want, gid[w], 1)
        got = np.asarray(
            grouped_sum_i32(
                jnp.asarray(w.astype(np.int32)), jnp.asarray(w), jnp.asarray(gid),
                G, interpret=True,
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_sum_i32_negative_values(self):
        rng = np.random.default_rng(4)
        n, G = BLOCK, 4
        vals = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int64).astype(np.int32)
        gid = rng.integers(0, G, n, dtype=np.int32)
        w = np.ones(n, dtype=bool)
        want = np.zeros(G, dtype=np.int64)
        np.add.at(want, gid, vals.astype(np.int64))
        got = np.asarray(
            grouped_sum_i32(jnp.asarray(vals), jnp.asarray(w), jnp.asarray(gid),
                            G, interpret=True)
        )
        np.testing.assert_array_equal(got, want)


class TestPallasAggregationEngine:
    """Executor integration: pallas_aggregation=interpret must give identical
    results to the XLA direct path on a real GROUP BY query."""

    Q1ISH = (
        "SELECT l_returnflag, l_linestatus, sum(l_quantity), "
        "sum(l_extendedprice * (1 - l_discount)), avg(l_discount), count(*) "
        "FROM lineitem WHERE l_shipdate <= DATE '1998-09-02' "
        "GROUP BY l_returnflag, l_linestatus "
        "ORDER BY l_returnflag, l_linestatus"
    )

    def test_q1_parity(self):
        from trino_tpu.runtime import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.01)
        runner.session.set("pallas_aggregation", "off")
        want = runner.execute(self.Q1ISH).rows
        runner.session.set("pallas_aggregation", "interpret")
        got = runner.execute(self.Q1ISH).rows
        assert got == want
