"""Central knob registry: every deployment env var and session property.

Reference blueprint: io.trino's config-property classes (io.trino.execution
TaskManagerConfig et al) + SystemSessionProperties.java — one declared,
typed, documented entry per knob, instead of ad-hoc ``os.environ`` reads
scattered through the runtime. Two tables live here:

- ``ENV_KNOBS``: every ``TRINO_TPU_*`` environment variable. The typed
  accessors below (``env_str``/``env_int``/``env_bytes``/...) are the ONLY
  sanctioned way to read them — the engine lint
  (``tools/lint`` rule ``env-read-outside-knobs``) fails any
  ``os.environ[...]`` read of a ``TRINO_TPU_*`` name outside this module.
  All accessors resolve at CALL time (late binding): an env var set after
  ``import trino_tpu`` still takes effect, matching the lazily-built
  memory pool and the result-cache deployment opt-in.

- ``SESSION_PROPERTIES``: name/type/default/description for every session
  property ``metadata.Session`` accepts. ``Session.DEFAULTS`` is built FROM
  this table, so a property cannot exist without a declared description.

``python -m trino_tpu.knobs`` renders both tables as the markdown knob
registry in ARCHITECTURE.md (``--write`` updates the section in place
between the ``knob-table`` markers); tests assert the committed table
matches the generator, so the hand-maintained doc can no longer drift.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


def parse_bytes(text) -> int:
    """``"512MB"``/``"2GB"``/``"4096"`` -> bytes (0 on empty/None/garbage).
    The canonical size parser — ``runtime.memory.parse_bytes`` re-exports it."""
    if text is None:
        return 0
    if isinstance(text, (int, float)):
        return int(text)
    s = str(text).strip().upper()
    if not s:
        return 0
    mult = 1
    for suffix, m in (
        ("TB", 1 << 40), ("GB", 1 << 30), ("MB", 1 << 20),
        ("KB", 1 << 10), ("B", 1),
    ):
        if s.endswith(suffix):
            s = s[: -len(suffix)]
            mult = m
            break
    try:
        return int(float(s) * mult)
    except ValueError:
        return 0


# --------------------------------------------------------------------------- #
# environment knobs
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class EnvKnob:
    name: str
    type: str  # int | float | bytes | path | str | flag
    default: str  # rendered default for the doc table ("unset" when optional)
    description: str


ENV_KNOBS: Tuple[EnvKnob, ...] = (
    EnvKnob(
        "TRINO_TPU_IO_THREADS", "int", "4",
        "size of the shared host-I/O thread pool (spill/prefetch/serde jobs)",
    ),
    EnvKnob(
        "TRINO_TPU_CAP_STORE", "path", "unset",
        "persisted per-stage capacity tuning store (single JSON, atomic "
        "rename); unset = in-process dict",
    ),
    EnvKnob(
        "TRINO_TPU_MEMORY_POOL_BYTES", "bytes", "unset",
        "process memory pool size (kB/MB/GB suffixes); unset/0 = memory "
        "arbitration off",
    ),
    EnvKnob(
        "TRINO_TPU_QUERY_MAX_MEMORY", "bytes", "unset",
        "deployment default for the query_max_memory_bytes session property "
        "(resolved at lookup time)",
    ),
    EnvKnob(
        "TRINO_TPU_MEMORY_RESERVE_TIMEOUT", "float", "30",
        "seconds a blocked user reservation waits (spill/kill escalation "
        "window) before MemoryReserveTimeout",
    ),
    EnvKnob(
        "TRINO_TPU_QUERY_HISTORY", "int", "100",
        "completed queries kept queryable in the QueryManager ring "
        "(system.runtime.queries)",
    ),
    EnvKnob(
        "TRINO_TPU_QUERY_HISTORY_PATH", "path", "unset",
        "coordinator persistent query-history JSONL (survives restarts, "
        "backs system.runtime.query_history)",
    ),
    EnvKnob(
        "TRINO_TPU_FLIGHT_RING", "int", "65536",
        "flight-recorder ring capacity in events; overflow is counted as "
        "dropped_events",
    ),
    EnvKnob(
        "TRINO_TPU_STATS_HISTORY", "path", "unset",
        "statistics-feedback history persistence file (atomic-rename merge); "
        "unset = bounded in-process dict",
    ),
    EnvKnob(
        "TRINO_TPU_RESULT_CACHE", "path", "unset",
        "result-cache persistence file; a set path is also the deployment "
        "opt-in for the result tier",
    ),
    EnvKnob(
        "TRINO_TPU_DEVICE_REPARTITION", "flag", "1",
        "kill-switch for the device-side repartition epilogue (0/false = "
        "legacy host path)",
    ),
    EnvKnob(
        "TRINO_TPU_INTERNAL_SECRET", "str", "unset",
        "shared HMAC secret authenticating intra-cluster coordinator/worker "
        "HTTP requests",
    ),
    EnvKnob(
        "TRINO_TPU_VALIDATE_PLAN", "flag", "unset",
        "force the validate_plan session default on (1/true) or off "
        "(0/false) process-wide; unset = on under pytest only",
    ),
    EnvKnob(
        "TRINO_TPU_HA_DIR", "path", "unset",
        "serving fabric substrate directory (leader lease + fencing state); "
        "set on every coordinator of an HA pair",
    ),
    EnvKnob(
        "TRINO_TPU_SHARED_CACHE_DIR", "path", "unset",
        "cross-process warm-tier directory on the object-store layer; a set "
        "path is also the deployment opt-in for the shared cache tier",
    ),
    EnvKnob(
        "TRINO_TPU_HEARTBEAT_SUSPECT_SECS", "float", "heartbeat/3",
        "heartbeat-loss grace window: a worker silent past this is SUSPECT "
        "(no new dispatch, no blacklist strike) before GONE",
    ),
    EnvKnob(
        "TRINO_TPU_CLUSTER_OBS", "flag", "unset",
        "server-process gate for the cluster observability plane "
        "(announcement metric/clock riders, /v1/flightrecorder?query_id=, "
        "/v1/metrics/cluster, /v1/query/{id}/profile); unset/0 = off with "
        "byte-identical responses",
    ),
    EnvKnob(
        "TRINO_TPU_QUERY_PROFILE_DIR", "path", "unset",
        "persisted query-profile bundle directory (one JSON per query, "
        "atomic rename); a set path is also the deployment opt-in for "
        "profile persistence and system.runtime.query_profiles",
    ),
    EnvKnob(
        "TRINO_TPU_ANNOUNCE_METRICS_MAX", "int", "256",
        "max metric series piggybacked on one worker announcement; overflow "
        "is dropped and counted "
        "(trino_tpu_announcement_metrics_dropped_total)",
    ),
    EnvKnob(
        "TRINO_TPU_HOSTPROF", "flag", "unset",
        "server-process gate for the host-path observability plane: starts "
        "the wall-clock sampling profiler and the GIL-contention probe for "
        "the process lifetime (coordinator/worker start()); unset/0 = off "
        "with no sampler thread and byte-identical query results",
    ),
    EnvKnob(
        "TRINO_TPU_HOSTPROF_INTERVAL_MS", "float", "19",
        "host-profiler sampling interval in milliseconds (floored at 1; "
        "the 19ms default is co-prime with common 10/20/100ms periodic "
        "work so samples don't alias against it)",
    ),
    EnvKnob(
        "TRINO_TPU_HOSTPROF_RING", "int", "4096",
        "host-profiler sample-ring capacity (per-thread stack samples); "
        "overflow is dropped and counted "
        "(trino_tpu_hostprof_dropped_samples_total)",
    ),
    EnvKnob(
        "TRINO_TPU_FLEET_DIR", "path", "unset",
        "coordinator-fleet membership substrate directory (heartbeat "
        "objects + follower-read status board); a set path is the opt-in "
        "for the active-active fleet plane",
    ),
    EnvKnob(
        "TRINO_TPU_FLEET_ROUTE", "str", "redirect",
        "non-owner statement handling: \"redirect\" answers 307 with the "
        "owner's address, \"proxy\" forwards the statement intake to the "
        "owner (result paging always goes direct)",
    ),
    EnvKnob(
        "TRINO_TPU_FLEET_PARTITION_BY", "str", "session",
        "ownership hash key: \"session\" = user@source, \"group\" = the "
        "resolved resource-group path (every session of a group lands on "
        "one coordinator, keeping its admission queue a single total order)",
    ),
    EnvKnob(
        "TRINO_TPU_FLEET_HEARTBEAT_SECS", "float", "1",
        "fleet membership heartbeat cadence; liveness TTL is 3 beats (one "
        "missed beat never reshuffles the ownership ring)",
    ),
    EnvKnob(
        "TRINO_TPU_FLEET_FOLLOWER_READS", "flag", "1",
        "serve system.*-only statements, warm result-cache hits, and "
        "GET /v1/query/{id} status polls from ANY fleet member (0/false = "
        "route every request to the owner)",
    ),
    EnvKnob(
        "TRINO_TPU_FLEET_FRONT_PORT", "int", "0",
        "shared SO_REUSEPORT client-facing port for the multi-process "
        "protocol front (each forked coordinator also binds a unique "
        "per-node port that membership advertises); 0 = no front listener",
    ),
    EnvKnob(
        "TRINO_TPU_HTTP_BACKLOG", "int", "0",
        "coordinator HTTP accept-backlog (listen(2) queue) size; 0 = the "
        "stdlib default (5). Part of the fleet front plane: the fleet CLI "
        "sets 128 per front process so a concurrent-session storm queues "
        "in the kernel instead of dropping SYNs into ~1s retransmits",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_RETRY_MAX", "int", "5",
        "max retries per object-store request (throttle/timeout) before "
        "the EXTERNAL-classified failure escapes to the failure plane",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_RETRY_INITIAL_MS", "int", "20",
        "object-store retry backoff base in ms (doubles per failure, "
        "0.5-1.5x jitter)",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_RETRY_CAP_MS", "int", "1000",
        "object-store retry backoff cap in ms",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_REQUEST_DEADLINE_MS", "int", "10000",
        "per-request deadline across all retries of one object-store "
        "request; past it the last failure escapes",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_RETRY_BUDGET", "int", "64",
        "process-wide object-store retry token bucket (each retry spends "
        "1, each clean request refunds 0.1): a store-wide throttling event "
        "degrades to first-failure instead of amplifying load",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_LIST_PAGE", "int", "1000",
        "object-store LIST page size in keys; each page is one retryable "
        "request",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_LIST_LAG_MS", "int", "0",
        "object-store list-after-write visibility lag in ms: objects "
        "younger than this are omitted from listings even though direct "
        "GETs succeed (0 = strongly consistent listing; the "
        "object_store_list_lag chaos site forces lag per listing)",
    ),
    EnvKnob(
        "TRINO_TPU_OBJECT_MULTIPART_THRESHOLD", "bytes", "8MB",
        "puts at or above this size upload as multipart (each part its "
        "own retryable request); unset/0 = 8MB",
    ),
    EnvKnob(
        "TRINO_TPU_ROOFLINE_PEAKS", "str", "built-in per-platform defaults",
        "measured roofline peaks per platform for kernel-cost diagnosis, "
        "\"platform=FLOPS:BYTES\" comma-separated (e.g. "
        "\"cpu=5e10:2e10,tpu=1.97e14:8.19e11\"); unset = conservative "
        "placeholder defaults (classification still honest, pct-of-roofline "
        "approximate)",
    ),
)

_ENV_BY_NAME: Dict[str, EnvKnob] = {k.name: k for k in ENV_KNOBS}


def _declared(name: str) -> EnvKnob:
    knob = _ENV_BY_NAME.get(name)
    if knob is None:
        raise KeyError(
            f"undeclared env knob {name!r}: add it to trino_tpu.knobs.ENV_KNOBS"
        )
    return knob


def env_raw(name: str) -> Optional[str]:
    """The one sanctioned ``os.environ`` read for ``TRINO_TPU_*`` names."""
    _declared(name)
    return os.environ.get(name)


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    v = env_raw(name)
    return v if v is not None else default


def env_path(name: str) -> Optional[str]:
    """Path-valued knob: empty string counts as unset."""
    return env_raw(name) or None


def env_int(name: str, default: int) -> int:
    raw = (env_raw(name) or "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        # a malformed env var must never fail queries mid-flight
        return default


def env_float(name: str, default: float) -> float:
    raw = (env_raw(name) or "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_bytes(name: str) -> int:
    """Size knob ("512MB"/"2GB"/plain bytes) -> int, 0 on unset/garbage."""
    return parse_bytes(env_raw(name))


def env_flag(name: str, default: bool) -> bool:
    raw = (env_raw(name) or "").strip().lower()
    if not raw:
        return default
    return raw not in ("0", "false", "no", "off")


def default_validate_plan() -> bool:
    """``validate_plan`` session default: on under pytest (every test run
    exercises the checkers over its whole query corpus), off on the
    production hot path; TRINO_TPU_VALIDATE_PLAN forces either way."""
    raw = (env_raw("TRINO_TPU_VALIDATE_PLAN") or "").strip().lower()
    if raw:
        return raw not in ("0", "false", "no", "off")
    return "PYTEST_CURRENT_TEST" in os.environ


# --------------------------------------------------------------------------- #
# session properties
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class SessionProperty:
    name: str
    type: str
    default: object
    description: str


SESSION_PROPERTIES: Tuple[SessionProperty, ...] = (
    SessionProperty(
        "join_distribution_type", "varchar", "AUTO",
        "AUTO | PARTITIONED | BROADCAST build-side placement "
        "(DetermineJoinDistributionType)",
    ),
    SessionProperty(
        "join_reordering_strategy", "varchar", "AUTOMATIC",
        "NONE (syntactic order) | ELIMINATE_CROSS_JOINS | AUTOMATIC "
        "(cost-based reorder of flat inner-join trees)",
    ),
    SessionProperty(
        "task_concurrency", "integer", 1,
        "worker-side task parallelism",
    ),
    SessionProperty(
        "split_target_rows", "integer", 1 << 20,
        "rows per split/page",
    ),
    SessionProperty(
        "hash_partition_count", "integer", 8,
        "partitions for FIXED_HASH stages",
    ),
    SessionProperty(
        "push_partial_aggregation", "boolean", True,
        "split SINGLE aggregations into PARTIAL below / FINAL above the "
        "exchange",
    ),
    SessionProperty(
        "broadcast_join_threshold_rows", "integer", 1_000_000,
        "estimated build rows at or below which AUTO joins broadcast",
    ),
    SessionProperty(
        "exchange_compression", "boolean", False,
        "LZ4-serialize pages crossing the DCN exchange tier (the ICI tier "
        "never serializes)",
    ),
    SessionProperty(
        "enable_dynamic_filtering", "boolean", True,
        "build-side key range narrows the probe side before evaluation "
        "(DynamicFilterService analogue)",
    ),
    SessionProperty(
        "query_max_memory_bytes", "bigint", 0,
        "per-query device-memory reservation limit (0 = unlimited); "
        "deployment default via TRINO_TPU_QUERY_MAX_MEMORY, resolved at "
        "lookup time",
    ),
    SessionProperty(
        "exchange_spill_trigger_bytes", "bigint", 0,
        "device-byte budget for stage outputs parked between fragments; "
        "beyond it pages spill to LZ4 host memory",
    ),
    SessionProperty(
        "spill_operator_threshold_bytes", "bigint", 0,
        "operator-state revoke threshold: grouped agg/join state beyond "
        "this hash-partitions to host memory (0 = off)",
    ),
    SessionProperty(
        "retry_policy", "varchar", "NONE",
        "NONE | QUERY (re-run once on retryable failure) | TASK "
        "(fault-tolerant execution: durable exchange + per-task retry)",
    ),
    SessionProperty(
        "task_retry_attempts", "integer", 2,
        "FTE attempts per task before the query fails",
    ),
    SessionProperty(
        "fte_exchange_dir", "varchar", "",
        "FTE durable exchange directory (default: a managed temp dir)",
    ),
    SessionProperty(
        "task_completion_timeout", "double", 300.0,
        "per-attempt completion deadline in seconds (0 = unbounded); a hung "
        "attempt fails the ATTEMPT, never the query",
    ),
    SessionProperty(
        "fte_task_concurrency", "integer", 8,
        "concurrent task attempts in flight per query",
    ),
    SessionProperty(
        "fte_retry_initial_delay", "double", 0.05,
        "classified-retry backoff initial delay (doubles per failure, "
        "0.5-1.5x jitter)",
    ),
    SessionProperty(
        "fte_retry_max_delay", "double", 2.0,
        "classified-retry backoff cap in seconds",
    ),
    SessionProperty(
        "fte_blacklist_ttl", "double", 60.0,
        "seconds a misbehaving worker sits out before timed re-admission",
    ),
    SessionProperty(
        "fte_speculation_enabled", "boolean", True,
        "stragglers past the quantile threshold get ONE speculative sibling "
        "attempt; first durable commit wins",
    ),
    SessionProperty(
        "fte_speculation_min_secs", "double", 10.0,
        "minimum task age before speculation triggers",
    ),
    SessionProperty(
        "fte_speculation_quantile", "double", 0.75,
        "completed-duration quantile feeding the straggler threshold",
    ),
    SessionProperty(
        "fte_speculation_multiplier", "double", 4.0,
        "straggler threshold = max(min_secs, multiplier x P[quantile])",
    ),
    SessionProperty(
        "distributed_sort", "boolean", True,
        "ORDER BY beyond one device: range shuffle + per-shard sort + merge "
        "gather",
    ),
    SessionProperty(
        "mesh_join_capacity_factor", "double", 1.0,
        "single-program ICI execution: initial join output capacity as a "
        "multiple of probe capacity (overflow retries double it)",
    ),
    SessionProperty(
        "use_ici_exchange", "boolean", True,
        "try lowering fragment trees into one shard_map program before the "
        "staged DCN path",
    ),
    SessionProperty(
        "target_partition_rows", "integer", 1_000_000,
        "adaptive partition counts: a FIXED_HASH/FIXED_RANGE fragment runs "
        "ceil(est_rows / this) parts, capped by worker count",
    ),
    SessionProperty(
        "max_tasks_per_worker", "integer", 0,
        "topology placement: tasks per worker before placement spills to "
        "the next tier (0 = unbounded)",
    ),
    SessionProperty(
        "pallas_aggregation", "varchar", "auto",
        "Pallas kernel tier for direct-indexed grouped aggregation: auto | "
        "off | force | interpret (resolve_pallas_aggregation documents the "
        "policy: AUTO keeps the XLA formulation — it wins on the measured "
        "shapes — and 'force' opts into the limb kernels)",
    ),
    SessionProperty(
        "pallas_fusion", "boolean", False,
        "fragment-fused Pallas megakernels (ops/megakernels.py): hash join "
        "+ partial agg + repartition epilogue in one launch; off = "
        "byte-identical serial op-chain path (same contract as "
        "device_batching)",
    ),
    SessionProperty(
        "pallas_interpret", "varchar", "auto",
        "megakernel execution mode: auto (pl.pallas_call interpret mode "
        "off on TPU, on elsewhere — the tier-1 CPU contract) | on | off",
    ),
    SessionProperty(
        "query_stats_sync", "boolean", False,
        "fence every operator for exact device/host/compile attribution "
        "(defeats async dispatch; EXPLAIN ANALYZE VERBOSE turns it on)",
    ),
    SessionProperty(
        "flight_recorder", "boolean", False,
        "record pipeline events into the process flight-recorder ring",
    ),
    SessionProperty(
        "kernel_cost", "boolean", False,
        "XLA cost-model attribution (runtime/kernelcost.py): per-plan-node "
        "FLOPs / HBM bytes / peak device memory with roofline diagnosis in "
        "EXPLAIN ANALYZE VERBOSE and system.runtime.kernel_costs; off = "
        "byte-identical execution path",
    ),
    SessionProperty(
        "statistics_feedback", "boolean", True,
        "collect per-node actual row counts, detect mis-estimates, record "
        "estimate-vs-actual history",
    ),
    SessionProperty(
        "history_based_stats", "boolean", False,
        "overlay recorded actuals onto the stats estimator on the next "
        "planning of a matching shape (Presto HBO analogue)",
    ),
    SessionProperty(
        "qerror_threshold", "double", 2.0,
        "q-error above which a plan node emits a cardinality_misestimate "
        "flight event + counter",
    ),
    SessionProperty(
        "result_cache", "boolean", False,
        "serve repeated queries from the full-result tier (a set "
        "$TRINO_TPU_RESULT_CACHE also opts the process in)",
    ),
    SessionProperty(
        "result_cache_max_bytes", "bigint", 64 << 20,
        "byte bound shared by the result and fragment tiers (LRU eviction)",
    ),
    SessionProperty(
        "result_cache_ttl", "double", 300.0,
        "staleness fallback for catalogs without a version hook; 0 = such "
        "plans bypass the result/fragment tiers",
    ),
    SessionProperty(
        "fragment_cache", "boolean", False,
        "materialize shared scan->filter->(partial-)agg prefixes once into "
        "the durable exchange store (single-flight dedup)",
    ),
    SessionProperty(
        "plan_cache_size", "integer", 0,
        "optimized-plan LRU by statement text + session state; a hit skips "
        "parse/analysis/optimization (0 = off)",
    ),
    SessionProperty(
        "validate_plan", "boolean", False,
        "run plan sanity checkers after EVERY optimizer rule "
        "(planner/sanity.py); default resolves dynamically — on under "
        "pytest, off otherwise, forced by TRINO_TPU_VALIDATE_PLAN",
    ),
    SessionProperty(
        "device_batching", "boolean", False,
        "pack compatible fragment subtrees from concurrent queries into "
        "one ragged device launch + shared-scan elimination "
        "(runtime/device_scheduler.py); off = byte-identical serial path",
    ),
    SessionProperty(
        "batch_max_lanes", "integer", 8,
        "device batching: max work-item lanes packed into one ragged "
        "launch (1 effectively disables packing, scans still share)",
    ),
    SessionProperty(
        "batch_admit_window_ms", "double", 2.0,
        "device batching: how long a batch leader holds admission open "
        "for compatible concurrent work items before launching",
    ),
    SessionProperty(
        "tensor_plane", "boolean", False,
        "tensor workload plane (ops/tensor.py): master gate for VECTOR "
        "top-k fusion and model scoring; off = plans and execution "
        "byte-identical (the similarity scalar family itself is always "
        "available, like any scalar function)",
    ),
    SessionProperty(
        "vector_topk_fusion", "boolean", False,
        "fuse ORDER BY <similarity> LIMIT k into ONE scores->top-k device "
        "program (optimizer fuse_vector_topn; needs tensor_plane); off = "
        "the serial Project + TopN pair, the bit-identity oracle",
    ),
    SessionProperty(
        "vector_query_batching", "boolean", False,
        "vector serving plane: coalesce concurrent VectorTopN work items "
        "that differ only in their constant query vector into ONE stacked "
        "device launch during the batch_admit_window_ms linger (needs "
        "device_batching); off = byte-identical per-query launches",
    ),
    SessionProperty(
        "ann_mode", "varchar", "off",
        "approximate vector search: off (exact scan, the recall oracle) | "
        "approx (IVF centroid pre-pass prunes cluster splits to the "
        "ann_nprobe nearest, like partition pruning) | approx(nprobe=N) "
        "(inline nprobe override)",
    ),
    SessionProperty(
        "ann_nprobe", "integer", 1,
        "IVF clusters probed per approximate vector top-k (ann_mode= "
        "approx); nprobe >= the index's cluster count reads every split "
        "in id order — bit-identical to exact mode",
    ),
    SessionProperty(
        "ann_recall_sample_rate", "double", 0.0,
        "fraction of ANN-pruned vector top-k executions re-run against "
        "the unpruned exact oracle to measure recall@k "
        "(system.runtime.ann_recall); 0 = never sample",
    ),
    SessionProperty(
        "model_scoring", "boolean", False,
        "SQL-surfaced model scoring: enables the linear_score / gbdt_score "
        "table functions (models compiled to XLA matmul / vectorized tree "
        "traversal; needs tensor_plane)",
    ),
    SessionProperty(
        "ha_plane", "boolean", False,
        "serving fabric plane (runtime/ha.py): journal FTE dispatch "
        "progress next to the durable exchange so a standby coordinator "
        "can replay it and resume in-flight queries after failover; off = "
        "byte-identical execution path",
    ),
    SessionProperty(
        "shared_cache_tier", "boolean", False,
        "cross-process warm tier: the result cache reads/publishes entries "
        "through $TRINO_TPU_SHARED_CACHE_DIR with leased single-flight so "
        "a coordinator fleet shares one warm cache (needs the env dir set)",
    ),
    SessionProperty(
        "elastic_workers", "boolean", False,
        "worker elasticity: the scale controller admits late-joining "
        "workers into running FTE queries and drains departing ones "
        "gracefully, driven by queue depth / memory pressure / blacklist "
        "churn signals",
    ),
    SessionProperty(
        "cluster_obs", "boolean", False,
        "cluster observability plane (runtime/clusterobs.py): cross-node "
        "trace assembly, per-stage time breakdown on FTE queries, query-"
        "profile persistence, and the EXPLAIN ANALYZE VERBOSE dominant-cost "
        "diagnosis; off = byte-identical execution path",
    ),
    SessionProperty(
        "slow_query_threshold", "double", 0.0,
        "wall-time seconds at or above which a completed query's profile "
        "bundle auto-persists to $TRINO_TPU_QUERY_PROFILE_DIR (0 = every "
        "completed query; needs cluster_obs + the profile dir)",
    ),
    SessionProperty(
        "host_profile", "boolean", False,
        "host-path observability plane (runtime/hostprof.py): run the "
        "wall-clock sampling profiler for this statement's execution "
        "(refcounted, like flight_recorder) — collapsed host stacks land "
        "in system.runtime.host_profile and the speedscope export; off = "
        "no sampler thread and byte-identical results",
    ),
    SessionProperty(
        "cache_aware_admission", "boolean", True,
        "serve result-cache hits BEFORE the resource-group queue gate (a "
        "warm hit never waits behind queued queries); no-op unless the "
        "result tier is enabled",
    ),
    SessionProperty(
        "protocol_first_response_wait", "double", 0.0,
        "seconds the initial POST /v1/statement response may wait for the "
        "query to reach a terminal state (the protocol's maxWait long-poll "
        "applied to the first response): a fast query — a warm cache hit "
        "above all — drains in ONE round trip instead of POST + GET; 0 = "
        "respond immediately (byte-identical protocol sequence)",
    ),
)

# session defaults resolved dynamically at LOOKUP time (metadata.Session.get):
# the static default above is what SHOW SESSION prints, the callable is what
# an unset property actually returns
DYNAMIC_SESSION_DEFAULTS = {
    "validate_plan": default_validate_plan,
}

# session defaults seeded from the environment at LOOKUP time
ENV_SESSION_DEFAULTS = {
    "query_max_memory_bytes": "TRINO_TPU_QUERY_MAX_MEMORY",
}


def session_property_names() -> frozenset:
    return frozenset(p.name for p in SESSION_PROPERTIES)


# --------------------------------------------------------------------------- #
# pallas-tier policy resolvers (THE documented policy — executor._pallas_mode
# and the device-batching admission check both delegate here, so the mode
# vocabulary cannot drift between the launch sites)
# --------------------------------------------------------------------------- #


def resolve_pallas_aggregation(value) -> str:
    """``pallas_aggregation`` session value -> static engine mode.

    - ``auto``/``off`` -> ``"off"``: the XLA direct-indexed formulation.
      Measured v5e SF1 (2026-07-29, chained-loop slope): XLA runs Q1 in
      0.98 ms and a G=60 3-key shape in 0.93 ms — both at the HBM roofline —
      while the Pallas limb kernels take 1.38 / 1.23 ms (the extra limb
      lanes cost bandwidth), so AUTO keeps XLA.
    - ``force`` -> ``"tpu"``: opt into the compiled limb kernels.
    - ``interpret`` -> ``"interpret"``: pl.pallas_call interpret mode, the
      CPU test hook.
    """
    mode = str(value or "auto").lower()
    if mode == "interpret":
        return "interpret"
    if mode == "force":
        return "tpu"
    return "off"


def resolve_ann_mode(value) -> Tuple[str, Optional[int]]:
    """``ann_mode`` session value -> ``(mode, nprobe_override)``.

    - ``off`` (default) -> ``("off", None)``: exact scans, no pruning.
    - ``approx`` -> ``("approx", None)``: centroid-pruned probing with the
      probe width taken from the ``ann_nprobe`` session knob.
    - ``approx(nprobe=N)`` -> ``("approx", N)``: inline probe-width
      override, clamped to >= 1.

    Unrecognised strings resolve to ``off`` — planner knobs degrade to the
    exact path, they never fail a query.
    """
    import re

    s = str(value or "off").strip().lower()
    if s == "approx":
        return ("approx", None)
    m = re.match(r"^approx\(\s*nprobe\s*=\s*(\d+)\s*\)$", s)
    if m:
        return ("approx", max(1, int(m.group(1))))
    return ("off", None)


def resolve_pallas_interpret(value, backend: str) -> bool:
    """``pallas_interpret`` session value -> interpret flag for megakernel
    launches: ``auto`` runs compiled on TPU and interpret everywhere else
    (the tier-1 bit-identity contract executes every fused kernel under
    interpret mode on CPU); ``on``/``off`` force either way."""
    mode = str(value or "auto").lower()
    if mode in ("on", "true", "1", "interpret"):
        return True
    if mode in ("off", "false", "0"):
        return False
    return backend != "tpu"


# --------------------------------------------------------------------------- #
# doc generation
# --------------------------------------------------------------------------- #

TABLE_BEGIN = "<!-- knob-table:begin (generated by python -m trino_tpu.knobs) -->"
TABLE_END = "<!-- knob-table:end -->"


def knob_table_markdown() -> str:
    """The generated ARCHITECTURE.md knob registry section."""
    lines: List[str] = [TABLE_BEGIN, ""]
    lines.append("**Environment knobs** (read only through `trino_tpu.knobs`):")
    lines.append("")
    lines.append("| env var | type | default | meaning |")
    lines.append("|---|---|---|---|")
    def esc(text) -> str:
        # markdown table cells: literal pipes must be escaped or the row
        # grows extra columns (join_distribution_type's "AUTO | PARTITIONED")
        return str(text).replace("|", "\\|")

    for k in ENV_KNOBS:
        lines.append(
            f"| `{k.name}` | {k.type} | `{k.default}` | {esc(k.description)} |"
        )
    lines.append("")
    lines.append("**Session properties** (`metadata.Session`, SET SESSION):")
    lines.append("")
    lines.append("| property | type | default | meaning |")
    lines.append("|---|---|---|---|")
    for p in SESSION_PROPERTIES:
        default = p.default if p.default != "" else "''"
        lines.append(
            f"| `{p.name}` | {p.type} | `{default}` | {esc(p.description)} |"
        )
    lines.append("")
    lines.append(TABLE_END)
    return "\n".join(lines)


def _replace_table(doc: str, table: str) -> str:
    start = doc.find(TABLE_BEGIN)
    end = doc.find(TABLE_END)
    if start < 0 or end < 0:
        raise SystemExit(
            "ARCHITECTURE.md is missing the knob-table markers; add "
            f"{TABLE_BEGIN!r} ... {TABLE_END!r} where the table belongs"
        )
    return doc[:start] + table + doc[end + len(TABLE_END):]


def main(argv: Optional[List[str]] = None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    table = knob_table_markdown()
    if "--write" in argv:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "ARCHITECTURE.md")
        doc = open(path).read()
        open(path, "w").write(_replace_table(doc, table))
        print(f"updated knob table in {path}", file=sys.stderr)
    else:
        print(table)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
