"""Connector factories for dynamic catalogs.

Reference blueprint: io.trino.connector.ConnectorServicesProvider +
each plugin's ConnectorFactory (getName()/create(catalogName, config)) —
CREATE CATALOG resolves the connector name against registered factories
and instantiates it from the WITH properties. The factory set here covers
the built-in connectors; external code registers more via
``register_connector_factory``.
"""

from __future__ import annotations

from typing import Callable, Dict

_FACTORIES: Dict[str, Callable] = {}


def register_connector_factory(name: str, factory: Callable) -> None:
    _FACTORIES[name.lower()] = factory


_KNOWN_PROPS: Dict[str, frozenset] = {}


def create_connector(name: str, props: Dict[str, object]):
    factory = _FACTORIES.get(name.lower())
    if factory is None:
        raise ValueError(
            f"unknown connector {name!r}; available: {sorted(_FACTORIES)}"
        )
    known = _KNOWN_PROPS.get(name.lower())
    if known is not None:
        bad = sorted(set(props) - set(known))
        if bad:
            # a typo'd property must fail loudly, never mount a
            # default-configured catalog (the reference rejects
            # unrecognized catalog properties the same way)
            raise ValueError(
                f"unknown catalog properties for {name!r}: {bad}; "
                f"supported: {sorted(known)}"
            )
    return factory(props)


def _tpch(props):
    from ..connectors.tpch import TpchConnector

    return TpchConnector(
        scale=float(props.get("tpch.scale", props.get("scale", 0.01))),
        split_target_rows=int(
            props.get("tpch.split-target-rows", props.get("split_target_rows", 1 << 20))
        ),
    )


def _tpcds(props):
    from ..connectors.tpcds import TpcdsConnector

    return TpcdsConnector(scale=float(props.get("tpcds.scale", props.get("scale", 0.01))))


def _memory(props):
    from ..connectors.memory import MemoryConnector

    return MemoryConnector()


def _blackhole(props):
    from ..connectors.memory import BlackHoleConnector

    return BlackHoleConnector()


def _lake(props):
    from ..connectors.lake import LakeConnector
    from ..fs import FileSystemManager, LocalFileSystem, Location

    warehouse = str(props.get("lake.warehouse", props.get("warehouse", "")))
    if not warehouse:
        raise ValueError("lake connector requires a 'warehouse' property")
    fsm = FileSystemManager()
    loc = Location.parse(warehouse)
    if loc.scheme not in ("local", "file"):
        # only the local filesystem ships; mapping s3:// etc. onto local
        # disk would silently bury data under ./bucket/... — fail loudly.
        # Custom schemes: construct LakeConnector directly with your own
        # FileSystemManager and register the catalog programmatically
        raise ValueError(
            f"no filesystem implementation for scheme {loc.scheme!r}; "
            "for custom schemes build LakeConnector(fs_manager, ...) with "
            "a FileSystemManager carrying your implementation"
        )
    root = str(props.get("lake.local-root", props.get("local_root", ".")))
    fsm.register(loc.scheme, lambda: LocalFileSystem(root))
    return LakeConnector(
        fsm,
        warehouse,
        max_rows_per_file=int(
            props.get("lake.max-rows-per-file", props.get("max_rows_per_file", 1_000_000))
        ),
    )


for _name, _f, _props in (
    ("tpch", _tpch, ("tpch.scale", "scale", "tpch.split-target-rows", "split_target_rows")),
    ("tpcds", _tpcds, ("tpcds.scale", "scale")),
    ("memory", _memory, ()),
    ("blackhole", _blackhole, ()),
    ("lake", _lake, ("lake.warehouse", "warehouse", "lake.local-root",
                     "local_root", "lake.max-rows-per-file", "max_rows_per_file")),
):
    register_connector_factory(_name, _f)
    _KNOWN_PROPS[_name] = frozenset(_props)
