"""Lakehouse-lite connector: partitioned Parquet over fs + metastore.

Reference blueprint: plugin/trino-hive (HiveMetadata.java:359 — table/
partition model, HiveSplitManager partition enumeration, HivePageSink
partitioned writes) with lib/trino-parquet's writer
(parquet/writer/ParquetWriter.java). The TPU build delegates the Parquet
byte format to Arrow (declared delegation, like the read path in
connectors/parquet.py) — the ENGINE side here is the storage model:

- every byte moves through the :mod:`trino_tpu.fs` object-store API (local
  today, s3-shaped by contract),
- the table/partition catalog is the JSON FileMetastore,
- INSERT/CTAS partition rows host-side by the table's partition columns and
  put one Parquet object per partition write (hive ``key=value`` layout),
  registering partitions in the metastore,
- reads enumerate metastore partitions, PRUNE on the absorbed TupleDomain,
  and decode files through the shared Arrow ingest; partition keys come
  back as constant columns (they are not stored in the files — hive
  semantics).
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..fs import FileSystemManager, Location
from ..metastore import FileMetastore, MetaColumn, MetaPartition, MetaTable
from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ..spi.page import Column, Page
from ..spi.predicate import TupleDomain
from ..spi.types import parse_type
from .arrow_ingest import arrow_table_to_page


class LakeConnector(Connector):
    name = "lake"

    def __init__(
        self,
        fs_manager: FileSystemManager,
        warehouse: str,
        max_rows_per_file: int = 1_000_000,
    ):
        self.fs_manager = fs_manager
        # scaled writers (ref: operator/output/SkewedPartitionRebalancer.
        # java:77): a skewed partition's write splits into multiple objects
        # so no single file serializes the whole skew
        self.max_rows_per_file = max(1, max_rows_per_file)
        self.metastore = FileMetastore(fs_manager, warehouse)
        self._meta = _LakeMetadata(self)
        self._splits = _LakeSplitManager(self)
        self._pages = _LakePageSource(self)
        self._file_counter = 0

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    def _fs(self, location: Location):
        return self.fs_manager.for_location(location)

    # ------------------------------------------------------------ write path

    def create_table(
        self,
        name: SchemaTableName,
        columns: Sequence[ColumnMetadata],
        partitioned_by: Sequence[str] = (),
    ) -> None:
        part = [c.lower() for c in partitioned_by]
        names = [c.name.lower() for c in columns]
        for p in part:
            if p not in names:
                raise ValueError(f"partition column {p!r} not in table columns")
        # column names store lowercased (the engine folds identifiers, and a
        # mixed-case stored name would never match the lowercased partition
        # column at write time)
        self.metastore.create_table(
            MetaTable(
                schema=name.schema,
                table=name.table,
                columns=[
                    MetaColumn(c.name.lower(), c.type.display()) for c in columns
                ],
                partition_columns=part,
            )
        )

    def drop_table(self, name: SchemaTableName, if_exists: bool = False) -> None:
        t = self.metastore.get_table(name.schema, name.table)
        if t is None:
            if if_exists:
                return
            raise ValueError(f"table not found: {name}")
        loc = Location.parse(t.location)
        fs = self._fs(loc)
        for entry in fs.list_files(loc):
            fs.delete(entry.location)
        self.metastore.drop_table(name.schema, name.table)

    def insert(self, name: SchemaTableName, page: Page) -> int:
        return self._insert_pages(name, page)[0]

    def _insert_pages(self, name: SchemaTableName, page: Page):
        """Partition rows by the table's partition columns and put one
        Parquet object per touched partition (HivePageSink's bucketing,
        minus buckets). Returns (rows, written_objects) — the object list is
        LOCAL so concurrent inserts cannot corrupt each other's manifests
        (iceberg-lite commits consume it)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        t = self.metastore.get_table(name.schema, name.table)
        if t is None:
            raise ValueError(f"table not found: {name}")
        active = np.asarray(page.active)
        decoded = {
            c.name: col.decode(active) for c, col in zip(t.columns, page.columns)
        }
        n = int(active.sum())
        if n == 0:
            return 0, []
        table_loc = Location.parse(t.location)
        part_cols = t.partition_columns
        data_cols = [c.name for c in t.columns if c.name not in part_cols]
        # written-object manifest for snapshotting subclasses (iceberg-lite);
        # LOCAL list: concurrent inserts must not corrupt each other's
        # manifests (returned via _insert_written)
        written_objects = []

        def write_object(sel: np.ndarray, part_values: tuple) -> None:
            arrays = {c: np.asarray(decoded[c])[sel] for c in data_cols}
            total = len(next(iter(arrays.values()))) if data_cols else int(sel.sum())
            rel = "/".join(
                f"{k}={v}" for k, v in zip(part_cols, part_values)
            )
            # scaled writer: chunk oversized partition writes into multiple
            # objects (the rebalancer's outcome without its feedback loop)
            step = self.max_rows_per_file
            for start in range(0, max(total, 1), step):
                chunk = {
                    c: arrays[c][start : start + step] for c in data_cols
                }
                tbl = pa.table({c: pa.array(list(chunk[c])) for c in data_cols})
                buf = io.BytesIO()
                pq.write_table(tbl, buf)
                self._file_counter += 1
                # uuid-unique names (hive's writer does the same): a restarted
                # connector must never overwrite an earlier insert's objects
                import uuid as _uuid

                fname = (
                    f"part-{self._file_counter:05d}-"
                    f"{_uuid.uuid4().hex[:12]}.parquet"
                )
                target = (
                    table_loc.child(rel, fname) if rel else table_loc.child(fname)
                )
                self._fs(table_loc).write(target, buf.getvalue())
                written_objects.append(
                    {"path": target.uri(), "partition": [str(v) for v in part_values]}
                )
            if part_cols:
                self.metastore.add_partition(
                    name.schema,
                    name.table,
                    MetaPartition(tuple(str(v) for v in part_values), rel),
                )

        if not part_cols:
            write_object(np.ones(n, dtype=bool), ())
            return n, written_objects
        keys = [np.asarray(decoded[c]) for c in part_cols]
        combos = sorted({tuple(str(k[i]) for k in keys) for i in range(n)})
        for combo in combos:
            sel = np.ones(n, dtype=bool)
            for k, v in zip(keys, combo):
                sel &= np.array([str(x) == v for x in k])
            write_object(sel, combo)
        return n, written_objects


class _LakeMetadata(ConnectorMetadata):
    def __init__(self, connector: LakeConnector):
        self.connector = connector

    def list_schemas(self):
        return sorted({s for s, _ in self.connector.metastore.list_tables()}) or [
            "default"
        ]

    def list_tables(self, schema: Optional[str] = None):
        return [
            SchemaTableName(s, t)
            for s, t in self.connector.metastore.list_tables(schema)
        ]

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        t = self.connector.metastore.get_table(name.schema, name.table)
        if t is None:
            return None
        cols = tuple(
            ColumnMetadata(c.name, parse_type(c.type_name)) for c in t.columns
        )
        return TableMetadata(name, cols)

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        return TableStatistics(row_count=None, columns={})

    def apply_filter(self, handle: TableHandle, domain: TupleDomain):
        # absorb for partition pruning (HiveMetadata.applyFilter)
        return TableHandle(handle.catalog, handle.schema_table, connector_handle=domain)


class _LakeSplitManager(ConnectorSplitManager):
    def __init__(self, connector: LakeConnector):
        self.connector = connector

    def get_splits(self, handle: TableHandle) -> List[Split]:
        ms = self.connector.metastore
        name = handle.schema_table
        t = ms.get_table(name.schema, name.table)
        if t is None:
            return []
        table_loc = Location.parse(t.location)
        fs = self.connector._fs(table_loc)
        domain: Optional[TupleDomain] = getattr(handle, "connector_handle", None)

        def partition_pruned(values: tuple) -> bool:
            """True when the absorbed domain excludes this partition
            (HiveSplitManager's partition pruning on key equality/range)."""
            if domain is None or not getattr(domain, "domains", None):
                return False
            vals = dict(zip(t.partition_columns, values))
            type_of = {c.name: c.type_name for c in t.columns}
            for col, d in domain.as_dict().items():
                if col not in vals or d is None:
                    continue
                raw = vals[col]
                # coerce by the COLUMN TYPE, not the value's shape: a varchar
                # partition value '5' must compare as a string
                tname = type_of.get(col, "varchar")
                try:
                    if tname in ("bigint", "integer", "smallint", "tinyint"):
                        v: object = int(raw)
                    elif tname in ("double", "real") or tname.startswith("decimal"):
                        v = float(raw)
                    else:
                        v = raw
                except ValueError:
                    v = raw
                if not d.contains_value(v):
                    return True
            return False

        infos: List[dict] = []
        if t.partition_columns:
            for p in ms.get_partitions(name.schema, name.table):
                if partition_pruned(p.values):
                    continue
                for entry in fs.list_files(table_loc.child(p.location)):
                    if entry.location.path.endswith(".parquet"):
                        infos.append(
                            {
                                "path": entry.location.uri(),
                                "partition": list(p.values),
                            }
                        )
        else:
            for entry in fs.list_files(table_loc):
                if entry.location.path.endswith(".parquet"):
                    infos.append({"path": entry.location.uri(), "partition": []})
        return [
            Split(table=handle, split_id=i, total_splits=len(infos), info=info)
            for i, info in enumerate(infos)
        ]


class _LakePageSource(ConnectorPageSourceProvider):
    def __init__(self, connector: LakeConnector):
        self.connector = connector
        self._dict_cache: Dict[tuple, object] = {}

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        import jax.numpy as jnp
        import pyarrow.parquet as pq

        ms = self.connector.metastore
        name = split.table.schema_table
        t = ms.get_table(name.schema, name.table)
        loc = Location.parse(split.info["path"])
        data = self.connector._fs(loc).read(loc)
        tbl = pq.read_table(io.BytesIO(data))
        part_values = dict(zip(t.partition_columns, split.info["partition"]))
        all_cols = [c.name for c in t.columns]
        wanted = [all_cols[i] for i in column_indexes]
        n = tbl.num_rows
        file_cols = [
            ColumnMetadata(
                c, parse_type(next(x.type_name for x in t.columns if x.name == c))
            )
            for c in wanted
            if c not in part_values
        ]
        file_page = (
            arrow_table_to_page(
                tbl.select([c.name for c in file_cols]),
                file_cols,
                self._dict_cache,
                (split.info["path"],),
            )
            if file_cols
            else None
        )
        by_name = (
            dict(zip([c.name for c in file_cols], file_page.columns))
            if file_page
            else {}
        )
        cols: List[Column] = []
        for cname in wanted:
            if cname in part_values:
                # partition keys are not in the file: constant columns
                # (hive partition-value projection)
                from ..spi.page import _scalar_from_pylist

                type_ = parse_type(
                    next(c.type_name for c in t.columns if c.name == cname)
                )
                raw = part_values[cname]
                conv: object = raw
                if type_.name in ("bigint", "integer", "smallint", "tinyint"):
                    conv = int(raw)
                elif type_.name in ("double", "real"):
                    conv = float(raw)
                cap = max(n, 1)
                col = _scalar_from_pylist(type_, [conv] * n, capacity=cap)
                cols.append(col)
            else:
                cols.append(by_name[cname])
        if file_page is not None:
            active = file_page.active
        else:
            active = (
                jnp.ones((n,), dtype=bool) if n else jnp.zeros((1,), dtype=bool)
            )
        # align capacities: constant partition columns were built at max(n,1)
        cap = int(active.shape[0])
        fixed: List[Column] = []
        for c in cols:
            if c.data.shape[0] != cap:
                pad = cap - c.data.shape[0]
                fixed.append(
                    Column(
                        c.type,
                        jnp.concatenate([c.data, jnp.zeros((pad,) + tuple(c.data.shape[1:]), c.data.dtype)]),
                        jnp.concatenate([c.valid, jnp.zeros((pad,), dtype=bool)]),
                        c.dictionary,
                    )
                    if pad > 0
                    else Column(c.type, c.data[:cap], c.valid[:cap], c.dictionary)
                )
            else:
                fixed.append(c)
        return Page(tuple(fixed), active)
