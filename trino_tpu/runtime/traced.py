"""Traced whole-query execution: plan -> one jittable function over scan pages.

Reference blueprint: the end state of PageFunctionCompiler-style codegen taken to
its XLA conclusion — instead of operator-at-a-time programs, an entire join-free
fragment (scan -> filter -> project -> aggregate -> topn) traces into ONE fused
XLA program. This is the hot path bench.py times and the unit __graft_entry__
exposes. Joins need a host sync to size their output (see executor.py), so plans
containing joins fall back to the operator-at-a-time executor; fixed-capacity
join tracing is a later-round extension.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..metadata import Metadata, Session
from ..planner.plan import (
    AggregationNode,
    FilterNode,
    JoinNode,
    LimitNode,
    LogicalPlan,
    OutputNode,
    PlanNode,
    ProjectNode,
    SemiJoinNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    visit_plan,
)
from ..spi.page import Page
from .executor import PlanExecutor, Relation, ExecutionError, _round_capacity

_TRACEABLE = (
    TableScanNode,
    FilterNode,
    ProjectNode,
    AggregationNode,
    SortNode,
    TopNNode,
    LimitNode,
    OutputNode,
)

# nodes that trace with a STATIC output capacity + overflow accounting (the
# caller must host-check the program's overflow scalar and retry larger)
_TRACEABLE_WITH_JOINS = _TRACEABLE + (
    JoinNode,
    SemiJoinNode,
    UnionNode,
    ValuesNode,
)


def is_traceable(
    plan: LogicalPlan, allow_joins: bool = False, extra_types: tuple = ()
) -> bool:
    ok = True
    allowed = (_TRACEABLE_WITH_JOINS if allow_joins else _TRACEABLE) + tuple(
        extra_types
    )

    def check(node: PlanNode):
        nonlocal ok
        if not isinstance(node, allowed):
            ok = False
        if isinstance(node, AggregationNode) and any(
            a.distinct for _, a in node.aggregations
        ):
            # distinct dedup host-syncs its intermediate capacity
            ok = False

    visit_plan(plan.root, check)
    return ok


class _TracedExecutor(PlanExecutor):
    """PlanExecutor with scans fed from arguments and no nested per-op jit:
    the entire eval happens inside one outer trace. Joins get a STATIC output
    capacity (probe capacity x ``join_capacity_factor``) and report overflow
    in ``self.overflows`` instead of host-syncing exact sizes — callers check
    the summed overflow after the run and retry with a larger factor."""

    allow_host_sync = False

    def __init__(
        self,
        plan,
        metadata,
        session,
        scan_pages: Dict[int, Page],
        join_capacity_factor: float = 1.0,
    ):
        super().__init__(plan, metadata, session)
        self._scan_pages = scan_pages
        self._scan_counter = 0
        self.join_capacity_factor = join_capacity_factor
        self.overflows: List[jnp.ndarray] = []

    def _choose_join_capacity(self, emit, probe_cap: int, build_cap: int) -> int:
        cap = _round_capacity(max(int(probe_cap * self.join_capacity_factor), 1))
        self.overflows.append(
            jnp.maximum(jnp.sum(emit).astype(jnp.int64) - cap, 0)
        )
        return cap

    def _exec_TableScanNode(self, node: TableScanNode) -> Relation:
        page = self._scan_pages[self._scan_counter]
        self._scan_counter += 1
        symbols = tuple(s for s, _ in node.assignments)
        return Relation(page, symbols)

    def _exec_AggregationNode(self, node: AggregationNode):
        # no host sync for output capacity under tracing: use input capacity
        import jax.numpy as jnp

        from .executor import (
            Page,
            _jit_aggregate,
            _jit_group_sort,
            _needed_agg_symbols,
        )

        from .executor import _direct_agg_domains, _jit_direct_aggregate

        distinct = [a for _, a in node.aggregations if a.distinct]
        if distinct:
            return super()._exec_AggregationNode(node)
        rel = self.eval(node.source)
        domains = _direct_agg_domains(rel, node)
        if domains is not None:
            page = _jit_direct_aggregate.__wrapped__(
                node.group_keys, node.aggregations, domains, rel.symbols, rel.page,
                self._pallas_mode(),
            )
            return Relation(
                page, node.group_keys + tuple(s for s, _ in node.aggregations)
            )
        needed = _needed_agg_symbols(node)
        if node.group_keys:
            sorted_page, new_group, num_groups = _jit_group_sort.__wrapped__(
                node.group_keys, needed, rel.symbols, rel.page
            )
            out_cap = rel.capacity
        else:
            cols = tuple(rel.column_for(s) for s in needed)
            sorted_page = Page(cols, rel.page.active)
            new_group, num_groups, out_cap = None, jnp.int32(1), 1
        # array_agg needs a host-synced lane width — unavailable under tracing
        page = _jit_aggregate.__wrapped__(
            node.group_keys,
            node.aggregations,
            needed,
            out_cap,
            0,
            sorted_page,
            new_group,
            num_groups,
        )
        return Relation(page, node.group_keys + tuple(s for s, _ in node.aggregations))


def _prepare_traced(plan: LogicalPlan, metadata: Metadata, session: Session):
    """Shared traced-compile scaffolding: gather scan pages in eval order
    (scan counter order == DFS order) and validate the root."""
    scans: List[TableScanNode] = []

    def collect(node: PlanNode):
        if isinstance(node, TableScanNode):
            scans.append(node)

    visit_plan(plan.root, collect)

    base = PlanExecutor(plan, metadata, session)
    example_pages: List[Page] = []
    for scan in scans:
        rel = base._exec_TableScanNode(scan)
        example_pages.append(rel.page)

    root = plan.root
    assert isinstance(root, OutputNode)
    return example_pages, root


def compile_query(
    plan: LogicalPlan, metadata: Metadata, session: Session
) -> Tuple[Callable[..., Page], List[Page], List[str]]:
    """Build (jittable_fn, example_scan_pages, output_column_names).

    ``jittable_fn(*scan_pages) -> Page`` runs the whole plan; scan pages are
    gathered once from the connectors as example inputs (callers may re-feed
    fresh pages of the same layout, e.g. per-split streaming).
    """
    if not is_traceable(plan):
        raise ExecutionError("plan contains nodes that require host syncs (joins)")
    example_pages, root = _prepare_traced(plan, metadata, session)

    def run(*pages: Page) -> Page:
        executor = _TracedExecutor(
            plan, metadata, session, dict(enumerate(pages))
        )
        rel = executor.eval(root.source)
        cols = [rel.column_for(s) for s in root.symbols]
        return Page(tuple(cols), rel.page.active)

    return run, example_pages, list(root.column_names)


def compile_query_joins(
    plan: LogicalPlan,
    metadata: Metadata,
    session: Session,
    join_capacity_factor: float = 1.0,
) -> Tuple[Callable[..., Tuple[Page, jnp.ndarray]], List[Page], List[str]]:
    """Whole-query tracing INCLUDING joins/semijoins: one XLA program for the
    entire plan, static join capacities (probe_cap x factor), and a summed
    overflow scalar the caller must host-check (retry with a larger factor on
    overflow — the single-chip analogue of mesh_runner's retry loop).

    Through a remote-TPU tunnel this collapses a join query's dozens of
    operator programs (each a 20-40s tunnel compile + host-sync re-upload)
    into ONE compile and ZERO mid-plan host syncs."""
    if not is_traceable(plan, allow_joins=True):
        raise ExecutionError("plan contains non-traceable nodes")
    example_pages, root = _prepare_traced(plan, metadata, session)

    def run(*pages: Page):
        executor = _TracedExecutor(
            plan, metadata, session, dict(enumerate(pages)), join_capacity_factor
        )
        rel = executor.eval(root.source)
        cols = [rel.column_for(s) for s in root.symbols]
        overflow = jnp.int64(0)
        for o in executor.overflows:
            overflow = overflow + o.astype(jnp.int64)
        return Page(tuple(cols), rel.page.active), overflow

    return run, example_pages, list(root.column_names)
