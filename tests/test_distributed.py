"""Distributed exchange + sharded aggregation over the 8-device virtual mesh.

The multi-"node" analogue of DistributedQueryRunner tests (SURVEY.md §4):
validates that hash repartition over all_to_all and partial->final aggregation
produce the same results as single-device execution.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trino_tpu.spi.page import Column, Page
from trino_tpu.spi.types import BIGINT
from trino_tpu.parallel import make_mesh
from trino_tpu.parallel.distributed import (
    distributed_filter_sum,
    distributed_groupby_sum,
    shard_pages,
)


N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"need {N_DEV} devices")
    return make_mesh(N_DEV)


def make_page(keys: np.ndarray, vals: np.ndarray, capacity: int) -> Page:
    return Page.from_arrays([BIGINT, BIGINT], [keys, vals], capacity=capacity)


def test_distributed_groupby_matches_local(mesh):
    rng = np.random.default_rng(7)
    n = 8 * 256
    keys = rng.integers(0, 37, size=n)
    vals = rng.integers(0, 1000, size=n)
    page = make_page(keys, vals, n)
    sharded = shard_pages([page], mesh)

    out, total = distributed_groupby_sum(mesh, sharded, 0, 1)
    assert int(total) == n

    # collect per-shard results to host and merge
    out_keys = np.asarray(out.columns[0].data)
    out_sums = np.asarray(out.columns[1].data)
    out_counts = np.asarray(out.columns[2].data)
    active = np.asarray(out.active)

    got = {}
    for k, s, c, a in zip(out_keys, out_sums, out_counts, active):
        if a:
            assert k not in got, f"group {k} appears on multiple shards"
            got[int(k)] = (int(s), int(c))

    import pandas as pd

    df = pd.DataFrame({"k": keys, "v": vals})
    exp = df.groupby("k")["v"].agg(["sum", "count"])
    assert len(got) == len(exp)
    for k, row in exp.iterrows():
        assert got[int(k)] == (int(row["sum"]), int(row["count"]))


def test_distributed_filter_sum(mesh):
    rng = np.random.default_rng(11)
    n = 8 * 128
    keys = rng.integers(0, 100, size=n)
    vals = rng.integers(0, 1000, size=n)
    page = make_page(keys, vals, n)
    sharded = shard_pages([page], mesh)

    def predicate(p: Page):
        return p.columns[0].data < 50

    total = distributed_filter_sum(mesh, sharded, predicate, 1)
    assert int(total) == int(vals[keys < 50].sum())


def test_repartition_preserves_rows(mesh):
    """all_to_all repartition: every active row lands on exactly one shard."""
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from trino_tpu.parallel import exchange

    rng = np.random.default_rng(3)
    n = 8 * 64
    keys = rng.integers(0, 1000, size=n)
    vals = np.arange(n)
    page = make_page(keys, vals, n)
    sharded = shard_pages([page], mesh)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("workers"),),
        out_specs=(P("workers"), P()),
    )
    def shuffle(p: Page):
        return exchange.repartition_by_keys(p, [0], N_DEV, "workers")

    out, overflow = shuffle(sharded)
    assert int(overflow) == 0
    active = np.asarray(out.active)
    got_vals = sorted(np.asarray(out.columns[1].data)[active].tolist())
    assert got_vals == list(range(n))
    # co-location: equal keys end up on the same shard
    out_keys = np.asarray(out.columns[0].data)
    shard_of = {}
    per_shard = len(out_keys) // N_DEV
    for i, (k, a) in enumerate(zip(out_keys, active)):
        if a:
            shard = i // per_shard
            assert shard_of.setdefault(int(k), shard) == shard
