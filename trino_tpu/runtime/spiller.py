"""Spilling: HBM -> host offload of idle pages + the shared host-I/O pool.

Reference blueprint: io.trino.spiller (FileSingleStreamSpiller/
GenericPartitioningSpiller with LZ4, SURVEY.md §5.7) — Trino spills operator
state to local disk under memory pressure. The TPU analogue's first memory tier
below HBM is host DRAM: spilled pages serialize through the page wire serde
(LZ4-compressed host bytes), freeing device memory; unspilling deserializes back
to device. Stage outputs parked between fragments are the natural spill unit.

This module also owns the process-wide host-I/O thread pool: LZ4
(de)compression of spill chunks, out-of-core bucket prefetch, and scan-batch
decode all ride it, so total background host parallelism stays bounded no
matter how many tiers overlap (the reference's bounded spiller executor,
io.trino.spiller.GenericSpillerFactory's shared ListeningExecutorService).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from .. import knobs
from ..spi.page import Page
from .observability import RECORDER, on_spill_read, on_spill_write
from .serde import deserialize_page, serialize_page

IO_THREADS_ENV = "TRINO_TPU_IO_THREADS"

_io_pool: Optional[ThreadPoolExecutor] = None
_io_pool_lock = threading.Lock()


def io_pool() -> ThreadPoolExecutor:
    """The shared host-I/O pool (lazily created; size via TRINO_TPU_IO_THREADS,
    default 4). Jobs submitted here must never themselves block on the pool
    (fan-out from inside a job deadlocks a saturated executor) — helpers that
    can run on either side take an optional pool and compress inline when
    called from a pool thread."""
    global _io_pool
    with _io_pool_lock:
        if _io_pool is None:
            # malformed values fall back to 4 inside the accessor — a
            # bad env var must not fail queries mid-flight
            n = max(1, knobs.env_int(IO_THREADS_ENV, 4))
            _io_pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="tpu-host-io"
            )
        return _io_pool


class Spiller:
    """Byte-budgeted page parking lot (SpillerFactory + SpillSpaceTracker rolled
    into one; disk tier arrives with multi-host).

    Memory-arbitration hookup (ref: MemoryRevokingScheduler + the revocable
    half of lib/trino-memory-context): pass ``memory`` (a pool-attached
    :class:`~trino_tpu.runtime.memory.AggregatedMemoryContext`) and parked
    device pages are accounted as REVOCABLE bytes, with the spiller
    registered as a pool revoker — a pool past pressure reclaims parked
    pages by spilling them to host even below ``trigger_bytes``, freeing HBM
    for blocked peers instead of letting them wedge."""

    def __init__(self, trigger_bytes: int = 0, compress: bool = True,
                 memory=None):
        """``trigger_bytes``: device-resident budget for parked pages; pages
        beyond it spill to host (0 = never spill proactively)."""
        self.trigger_bytes = trigger_bytes
        self.compress = compress
        self._lock = threading.Lock()
        self.spilled_bytes = 0
        self.spill_count = 0
        self.revoked_bytes = 0
        # revocable accounting: tracked entry lists are mutated IN PLACE by
        # revoke(), so consumers holding the returned list see the handles
        self._tracked: List[List[object]] = []
        self._revocable = None
        self._pool = None
        if memory is not None and getattr(memory, "pool", None) is not None:
            self._revocable = memory.new_local("parked_pages", revocable=True)
            self._pool = memory.pool
            self._pool.add_revoker(self)

    def _device_entries_locked(self):
        """(size, list, index, page) for every still-device-resident entry."""
        from .memory import page_bytes

        out = []
        for entries in self._tracked:
            for i, e in enumerate(entries):
                if isinstance(e, Page):
                    out.append((page_bytes(e), entries, i, e))
        return out

    def revoke(self, nbytes: int) -> int:
        """Pool-pressure callback: spill parked device pages (largest first)
        until ~``nbytes`` freed; returns bytes actually freed."""
        with self._lock:
            victims = []
            freed = 0
            for size, entries, i, p in sorted(
                self._device_entries_locked(), reverse=True,
                key=lambda v: v[0],
            ):
                if freed >= nbytes:
                    break
                victims.append((size, entries, i, p))
                freed += size
            if not victims:
                return 0
            blobs = list(io_pool().map(
                lambda v: serialize_page(v[3], compress=self.compress), victims
            ))
            for (size, entries, i, _), blob in zip(victims, blobs):
                entries[i] = _SpilledPage(blob)
                on_spill_write(len(blob), event=False)
                self.spilled_bytes += size
                self.spill_count += 1
                self.revoked_bytes += size
        if self._revocable is not None:
            self._revocable.add_bytes(-freed)
        return freed

    def detach(self) -> None:
        """Release revocable accounting + pool registration (query end)."""
        if self._pool is not None:
            self._pool.remove_revoker(self)
        if self._revocable is not None:
            self._revocable.close()
        with self._lock:
            self._tracked = []

    def maybe_spill(self, pages: List[Page]) -> List[object]:
        """Park a list of pages: returns entries that are either Pages (still
        device-resident) or spill handles, largest pages spilled first.
        Serialization (LZ4 per column buffer) of the chosen pages runs in
        parallel on the shared I/O pool."""
        if not self.trigger_bytes:
            out = list(pages)
            self._track(out)
            return out
        from .memory import page_bytes

        sized = [(page_bytes(p), i, p) for i, p in enumerate(pages)]
        total = sum(s for s, _, _ in sized)
        out: List[object] = list(pages)
        victims = []
        for size, i, p in sorted(sized, reverse=True):
            if total <= self.trigger_bytes:
                break
            victims.append((size, i, p))
            total -= size
        if not victims:
            self._track(out)
            return out
        with RECORDER.span(
            "spill_park", "spill", pages=len(victims),
            bytes=sum(s for s, _, _ in victims),
        ):
            blobs = io_pool().map(
                lambda v: serialize_page(v[2], compress=self.compress), victims
            )
            for (size, i, _), blob in zip(victims, blobs):
                out[i] = _SpilledPage(blob)
                on_spill_write(len(blob), event=False)
                with self._lock:
                    self.spilled_bytes += size
                    self.spill_count += 1
        self._track(out)
        return out

    def _track(self, entries: List[object]) -> None:
        """Account still-device-resident parked pages as revocable memory
        (no-op without a pool-attached context)."""
        if self._revocable is None:
            return
        from .memory import page_bytes

        device = sum(
            page_bytes(e) for e in entries if isinstance(e, Page)
        )
        with self._lock:
            self._tracked.append(entries)
        if device:
            # revocable reservations never block (see runtime/memory.py) —
            # they raise pressure the pool resolves by calling revoke()
            self._revocable.add_bytes(device)

    @staticmethod
    def load(entry: object) -> Page:
        if isinstance(entry, _SpilledPage):
            on_spill_read(len(entry.data))
            return deserialize_page(entry.data)
        return entry  # still a device Page


class _SpilledPage:
    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data
