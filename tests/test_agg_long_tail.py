"""Map-valued aggregates, listagg, aggregate ORDER BY, INTERSECT/EXCEPT ALL.

Model: the reference's TestMapAggAggregation / TestMultimapAggAggregation /
TestHistogram / listagg tests (operator/aggregation/) and
TestSetOperations INTERSECT ALL / EXCEPT ALL coverage (Trino lowers those via
rule/ImplementIntersectAll + ImplementExceptAll — row_number vs counts; the
planner here uses the same formulation).
"""

import pytest


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.0005)


def rows(runner, sql):
    return runner.execute(sql).rows


def one(runner, sql):
    r = rows(runner, sql)
    assert len(r) == 1
    return r[0]


class TestMapAgg:
    def test_grouped(self, runner):
        got = rows(
            runner,
            "SELECT k, map_agg(k2, v) FROM (VALUES ('a','x',1),('a','y',2),"
            "('b','x',3)) t(k,k2,v) GROUP BY k ORDER BY k",
        )
        assert got == [("a", {"x": 1, "y": 2}), ("b", {"x": 3})]

    def test_duplicate_keys_keep_one(self, runner):
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES ('x',1),('x',9)) t(k,v)",
        )
        assert set(m.keys()) == {"x"} and m["x"] in (1, 9)

    def test_null_keys_skipped_and_empty_is_null(self, runner):
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES ('x',1),(NULL,2)) t(k,v)",
        )
        assert m == {"x": 1}
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES ('x',1)) t(k,v) WHERE k='zz'",
        )
        assert m is None

    def test_bigint_keys(self, runner):
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES (10,'a'),(20,'b')) t(k,v)",
        )
        assert m == {10: "a", 20: "b"}


class TestHistogram:
    def test_basic(self, runner):
        (m,) = one(
            runner,
            "SELECT histogram(k) FROM (VALUES ('a'),('b'),('a'),(NULL)) t(k)",
        )
        assert m == {"a": 2, "b": 1}

    def test_grouped_numeric(self, runner):
        got = rows(
            runner,
            "SELECT g, histogram(v) FROM (VALUES (1,5),(1,5),(1,6),(2,7)) "
            "t(g,v) GROUP BY g ORDER BY g",
        )
        assert got == [(1, {5: 2, 6: 1}), (2, {7: 1})]


class TestMultimapAgg:
    def test_basic(self, runner):
        (m,) = one(
            runner,
            "SELECT multimap_agg(k, v) FROM (VALUES ('x',1),('x',2),('y',3)) t(k,v)",
        )
        assert m == {"x": [1, 2], "y": [3]}

    def test_grouped(self, runner):
        got = rows(
            runner,
            "SELECT g, multimap_agg(k, v) FROM (VALUES (1,'x',1),(1,'x',2),"
            "(2,'y',3)) t(g,k,v) GROUP BY g ORDER BY g",
        )
        assert got == [(1, {"x": [1, 2]}), (2, {"y": [3]})]


class TestListagg:
    def test_within_group(self, runner):
        got = rows(
            runner,
            "SELECT k, listagg(v, ',') WITHIN GROUP (ORDER BY v) FROM "
            "(VALUES ('g1','b'),('g1','a'),('g2','z')) t(k,v) GROUP BY k ORDER BY k",
        )
        assert got == [("g1", "a,b"), ("g2", "z")]

    def test_default_separator_and_nulls_skipped(self, runner):
        (s,) = one(
            runner,
            "SELECT listagg(v) WITHIN GROUP (ORDER BY v) FROM "
            "(VALUES ('b'),('a'),(NULL)) t(v)",
        )
        assert s == "ab"

    def test_desc_order(self, runner):
        (s,) = one(
            runner,
            "SELECT listagg(v, '-') WITHIN GROUP (ORDER BY v DESC) FROM "
            "(VALUES ('a'),('c'),('b')) t(v)",
        )
        assert s == "c-b-a"


class TestArrayAggOrderBy:
    def test_order_by_other_column(self, runner):
        (a,) = one(
            runner,
            "SELECT array_agg(v ORDER BY s DESC) FROM "
            "(VALUES ('p','a'),('q','b'),('r','c')) t(v,s)",
        )
        assert a == ["r", "q", "p"]

    def test_grouped_order_by(self, runner):
        got = rows(
            runner,
            "SELECT g, array_agg(v ORDER BY v) FROM "
            "(VALUES (1,3),(1,1),(2,5),(1,2)) t(g,v) GROUP BY g ORDER BY g",
        )
        assert got == [(1, [1, 2, 3]), (2, [5])]


class TestIntersectExceptAll:
    def test_intersect_all(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES (1),(1),(2),(3)) a(x) INTERSECT ALL "
            "SELECT y FROM (VALUES (1),(1),(1),(2)) b(y) ORDER BY x",
        )
        assert got == [(1,), (1,), (2,)]

    def test_except_all(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES (1),(1),(1),(2),(4)) a(x) EXCEPT ALL "
            "SELECT y FROM (VALUES (1),(2),(3)) b(y) ORDER BY x",
        )
        assert got == [(1,), (1,), (4,)]

    def test_intersect_all_strings(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES ('a'),('a'),('b')) a(x) INTERSECT ALL "
            "SELECT y FROM (VALUES ('a'),('c')) b(y)",
        )
        assert got == [("a",)]

    def test_except_all_empty_result(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES (1)) a(x) EXCEPT ALL "
            "SELECT y FROM (VALUES (1),(1)) b(y)",
        )
        assert got == []
