"""Tensor workload plane: VECTOR columns, MXU similarity kernels, model scoring.

Reference blueprint: "Accelerating ML Queries with Linear Algebra Query
Processing" (arXiv:2306.08367) — compile the vector/ML scalar family into
dense linear algebra — and "Query Processing on Tensor Computation Runtimes"
(arXiv:2203.01877) — the payoff comes from keeping the whole pipeline
on-device. ROADMAP item 3: this is the first workload class where the engine
should beat reference Trino by an order of magnitude instead of matching it,
because the MXU sits idle through every purely relational query.

Three pieces live here; the runtime wiring (fused top-k executor, optimizer
rule, fragmenter split) lives with its planes:

- **Similarity lowering** (:func:`compile_vector_call`): ``dot_product`` /
  ``cosine_similarity`` / ``l2_distance`` / ``vector_norm`` over
  ``VECTOR(n)`` columns. A vector column is one contiguous ``data[rows, n]``
  float64 buffer (spi.types.VectorType — the multi-lane scalar layout, NULL
  on the ordinary row mask), so batched evaluation against a constant query
  vector is literally ``data @ q`` — the ``(rows, n) x (n,)`` matvec the MXU
  exists for. Row-wise vector/vector forms (embedding joins) lower to an
  einsum over the lane axis.

- **Model scoring lowering** (:func:`compile_model_call`): linear models and
  small GBDT ensembles compiled to XLA. The model spec rides the IR as a
  hashable constant (plancodec-encodable, jit-static), features stack into a
  ``(rows, k)`` matrix: linear scoring is one ``(rows, k) @ (k,)`` matmul,
  GBDT traversal is ``depth`` vectorized gather steps over all rows AND all
  trees at once. SQL surface: the ``linear_score`` / ``gbdt_score``
  ConnectorTableFunctions (spi/table_function.py), gated on the
  ``model_scoring`` knob.

- **Observability**: ``trino_tpu_vector_kernel_launches_total`` +
  ``trino_tpu_vector_topk_fallbacks_total{reason}`` counters, and the paired
  ``vector_kernel`` / ``topk_fusion`` flight spans (rows/dim/k on E-args).
  Fallback labels, like the megakernel plane's, are short stable strings:
  ``unprojected_order_key`` (a fusable ORDER BY similarity whose other sort
  keys are not computed by the scoring projection), ``kernel_error`` (the
  fused program failed at runtime; the serial project+sort pair finished the
  query).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..spi.page import Column
from ..spi.types import VectorType, is_vector
from ..sql.functions import VECTOR_SCALAR_FUNCTIONS
from ..sql.ir import Call, Case, CastExpr, Constant, IrExpr
from .compiler import CVal, CompileError

# IR call names for compiled model scoring (emitted by the table functions,
# lowered by compile_model_call); arg 0 is the static spec constant
LINEAR_MODEL_CALL = "$linear_model"
GBDT_MODEL_CALL = "$gbdt_model"
MODEL_CALLS = frozenset({LINEAR_MODEL_CALL, GBDT_MODEL_CALL})


# --------------------------------------------------------------------------- #
# observability: launch/fallback counters + paired kernel/fusion spans
# --------------------------------------------------------------------------- #


def _launch_counter():
    from ..runtime.metrics import REGISTRY

    return REGISTRY.counter(
        "trino_tpu_vector_kernel_launches_total",
        help="tensor-plane device programs launched (vector similarity "
        "projections, fused score->top-k programs, model-scoring matmuls)",
    )


def _fallback_counter(reason: str):
    from ..runtime.metrics import REGISTRY

    return REGISTRY.counter(
        "trino_tpu_vector_topk_fallbacks_total",
        {"reason": reason},
        help="ORDER BY similarity LIMIT k shapes that fell back from the "
        "fused score->top-k program to the serial project+sort pair, "
        "by reason",
    )


def on_vector_kernel(n: int = 1) -> None:
    _launch_counter().inc(n)


def on_topk_fallback(reason: str) -> None:
    """One query shape declined (or abandoned) the fused top-k path;
    ``reason`` is a short stable label (ARCHITECTURE.md enumerates them)."""
    _fallback_counter(reason).inc()
    from ..runtime.observability import RECORDER

    RECORDER.instant("vector_topk_fallback", "tensor", reason=reason)


def vector_launches() -> float:
    return _launch_counter().value


def topk_fallbacks(reason: str) -> float:
    return _fallback_counter(reason).value


def vector_kernel_span(rows: int, dim: int):
    """Paired ``vector_kernel`` flight span; write rows/dim into the yielded
    dict so they land on the E event (the issue contract: E-args carry the
    shape). Callers: the executor's project path and the fused top-k node."""
    from ..runtime.observability import RECORDER

    return _shaped_span(RECORDER, "vector_kernel", rows=rows, dim=dim)


def topk_fusion_span(rows: int, dim: int, k: int):
    from ..runtime.observability import RECORDER

    return _shaped_span(RECORDER, "topk_fusion", rows=rows, dim=dim, k=k)


class _shaped_span:
    """Context manager stacking a RECORDER span and stamping the shape args
    onto the E event (the span yields a mutable dict for exactly this)."""

    def __init__(self, recorder, name: str, **shape):
        self._cm = recorder.span(name, "tensor")
        self._shape = {k: int(v) for k, v in shape.items()}

    def __enter__(self):
        args = self._cm.__enter__()
        args.update(self._shape)
        return args

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


# --------------------------------------------------------------------------- #
# vector serving plane observability: query-matrix batching + ANN index tier
# (runtime wiring: device_scheduler vector lanes, connectors/vector_index.py)
# --------------------------------------------------------------------------- #


def _serving_counter(name: str):
    from ..runtime.metrics import REGISTRY

    helps = {
        "trino_tpu_vector_batched_queries_total":
            "concurrent vector top-k statements served per-lane by one "
            "stacked batched device launch (the query-matrix batching win: "
            "lanes minus launches is the amortization)",
        "trino_tpu_ann_pruned_splits_total":
            "IVF cluster splits pruned by the ANN centroid-distance "
            "pre-pass (ann_mode=approx), the partition-pruning analogue "
            "for vector search",
        "trino_tpu_ann_recall_samples_total":
            "approximate vector top-k executions re-run against the "
            "unpruned exact oracle to measure recall@k "
            "(system.runtime.ann_recall rows)",
        "trino_tpu_ann_oracle_errors_total":
            "recall-oracle sampler runs that raised and were dropped "
            "(monitoring only — the serving query itself already "
            "succeeded; a nonzero rate means recall is under-observed)",
    }
    return REGISTRY.counter(name, help=helps[name])


def on_vector_batched(lanes: int) -> None:
    """One stacked launch served ``lanes`` concurrent vector statements."""
    _serving_counter("trino_tpu_vector_batched_queries_total").inc(lanes)


def on_ann_pruned(splits: int) -> None:
    if splits > 0:
        _serving_counter("trino_tpu_ann_pruned_splits_total").inc(splits)


def vector_batched_queries() -> float:
    return _serving_counter("trino_tpu_vector_batched_queries_total").value


def ann_pruned_splits() -> float:
    return _serving_counter("trino_tpu_ann_pruned_splits_total").value


def ann_recall_samples() -> float:
    return _serving_counter("trino_tpu_ann_recall_samples_total").value


def on_ann_oracle_error() -> None:
    """The recall-oracle sampler raised; the serving query already
    succeeded, so the failure is counted instead of propagated."""
    _serving_counter("trino_tpu_ann_oracle_errors_total").inc()


def vector_batch_launch_span(lanes: int, rows: int, dim: int, k: int):
    """Paired ``vector_batch_launch`` flight span around the one stacked
    device program serving a whole vector lane group."""
    from ..runtime.observability import RECORDER

    return _shaped_span(
        RECORDER, "vector_batch_launch", lanes=lanes, rows=rows, dim=dim, k=k
    )


def ann_probe_span(total: int, nprobe: int):
    """Paired ``ann_probe`` flight span around the centroid-distance
    pre-pass; the split manager stamps probed/pruned onto the E args."""
    from ..runtime.observability import RECORDER

    return _shaped_span(RECORDER, "ann_probe", total=total, nprobe=nprobe)


def register_vector_serving_metrics() -> None:
    """Eager registration (the run_batching_smoke convention): exposition
    and the HELP lint must see the families before the first batched
    launch / ANN probe happens to occur."""
    for name in (
        "trino_tpu_vector_batched_queries_total",
        "trino_tpu_ann_pruned_splits_total",
        "trino_tpu_ann_recall_samples_total",
    ):
        _serving_counter(name)


# bounded ring of measured recall@k samples, served by
# system.runtime.ann_recall: (table, k, nprobe, recall, probed, total)
_ANN_RECALL_MAX = 256
_ANN_RECALL: list = []
_ANN_RECALL_LOCK = None  # created lazily (module import must stay cheap)


def _recall_lock():
    global _ANN_RECALL_LOCK
    if _ANN_RECALL_LOCK is None:
        import threading

        _ANN_RECALL_LOCK = threading.Lock()
    return _ANN_RECALL_LOCK


def record_ann_recall(
    table: str, k: int, nprobe: int, recall: float, probed: int, total: int
) -> None:
    _serving_counter("trino_tpu_ann_recall_samples_total").inc()
    from ..runtime.observability import RECORDER

    RECORDER.instant(
        "ann_recall_sample", "tensor", table=table, k=int(k),
        nprobe=int(nprobe), recall=float(recall),
    )
    with _recall_lock():
        _ANN_RECALL.append(
            (str(table), int(k), int(nprobe), float(recall), int(probed),
             int(total))
        )
        del _ANN_RECALL[:-_ANN_RECALL_MAX]


def ann_recall_rows():
    with _recall_lock():
        return list(_ANN_RECALL)


def reset_ann_recall() -> None:
    global _ANN_SAMPLE_SEQ
    with _recall_lock():
        del _ANN_RECALL[:]
        _ANN_SAMPLE_SEQ = 0


_ANN_SAMPLE_SEQ = 0


def ann_sample_due(rate: float) -> bool:
    """Deterministic recall sampler: the Nth eligible execution samples when
    the cumulative expected sample count crosses an integer — rate=1.0
    samples every execution, rate=0.25 every fourth; no RNG, so tests and
    chaos replays are stable."""
    import math

    global _ANN_SAMPLE_SEQ
    r = min(max(float(rate), 0.0), 1.0)
    with _recall_lock():
        _ANN_SAMPLE_SEQ += 1
        s = _ANN_SAMPLE_SEQ
    return math.floor(s * r) > math.floor((s - 1) * r)


# --------------------------------------------------------------------------- #
# query-matrix batching: lane eligibility + the masked coalescing key
# --------------------------------------------------------------------------- #

# the binary similarity family whose constant-query form is one matvec —
# the shapes the vector lane tier stacks (vector_norm and the model calls
# carry no per-statement query constant; they ride subsumption instead)
BATCHABLE_SIM_FUNCS = frozenset(
    {"dot_product", "cosine_similarity", "l2_distance"}
)


def split_query_constant(expr: IrExpr):
    """``sim(col, CONST q)`` (either operand order) -> ``(name, col_expr,
    const_expr)``; None when the expression is not a constant-query
    similarity call. The score expr must BE the call — a wrapped score
    (CAST, arithmetic) stays on the serial fused path."""
    if not (
        isinstance(expr, Call)
        and expr.name in BATCHABLE_SIM_FUNCS
        and len(expr.args) == 2
    ):
        return None
    a, b = expr.args
    qa, qb = constant_vector_value(a), constant_vector_value(b)
    if qb is not None and qa is None:
        return (expr.name, a, b)
    if qa is not None and qb is None:
        return (expr.name, b, a)
    return None


def broadcast_similarity(expr: IrExpr, broadcast_syms) -> bool:
    """``sim(a.v, b.v)`` where exactly one side is a single-row broadcast
    build vector column (the embedding-JOIN shape _join_relations tags):
    semantically a constant-query lane — the stacked path serves it with
    the lane's own einsum closures, bit-identical to the serial pair."""
    from ..sql.ir import Reference

    if not broadcast_syms:
        return False
    if not (
        isinstance(expr, Call)
        and expr.name in BATCHABLE_SIM_FUNCS
        and len(expr.args) == 2
    ):
        return False
    a, b = expr.args
    if not (isinstance(a, Reference) and isinstance(b, Reference)):
        return False
    return (a.symbol in broadcast_syms) != (b.symbol in broadcast_syms)


def vector_batch_masked_node(node, broadcast_syms=frozenset()):
    """The coalescing key's plan half: the VectorTopNNode with the lead
    score's query constant replaced by a NULL placeholder of the same
    type, so statements differing ONLY in the query vector fingerprint
    identically. Collision-safe: a real NULL-query statement never
    becomes a constant-query lane (constant_vector_value returns None
    for NULL), so the placeholder can't alias a live plan.

    Returns ``(masked_node, kind)`` with kind ``"const"`` / ``"bcast"``,
    or None when the shape is not a stackable lane."""
    import dataclasses

    if node.partial or node.count < 0 or not node.orderings:
        return None
    assigned = dict(node.assignments)
    lead = assigned.get(node.orderings[0].symbol)
    if lead is None:
        return None
    if broadcast_similarity(lead, broadcast_syms):
        # the statement identity (source subtree incl. the build side)
        # already rides the fingerprint — nothing to mask
        return node, "bcast"
    parts = split_query_constant(lead)
    if parts is None:
        return None
    name, _col, const = parts
    placeholder = Constant(_type=const.type, value=None)
    masked_call = Call(
        name=name,
        args=tuple(
            placeholder if a is const else a for a in lead.args
        ),
        _type=lead.type,
    )
    masked_assignments = tuple(
        (s, masked_call if e is lead else e) for s, e in node.assignments
    )
    return dataclasses.replace(node, assignments=masked_assignments), "const"


# --------------------------------------------------------------------------- #
# IR analysis helpers (shared by the analyzer, the optimizer rule, the
# sanity checkers, and the executor's span/counter sites)
# --------------------------------------------------------------------------- #


def constant_vector_value(expr: IrExpr) -> Optional[Tuple[float, ...]]:
    """A non-NULL constant vector's host value, else None."""
    if isinstance(expr, Constant) and is_vector(expr.type) and expr.value is not None:
        return tuple(float(x) for x in expr.value)
    return None


def fold_constant_array(expr: IrExpr) -> Optional[Tuple[Optional[float], ...]]:
    """``ARRAY[...]`` of numeric constants -> host tuple of FLOAT VALUES
    (None per NULL element); None when any element is not a constant.
    Constants carry the *storage* representation, so decimal literals
    (``ARRAY[1.0, 2.5]`` parses as decimal(2,1)) descale here. Casts of
    constants fold at analysis time, so the elements are plain Constants."""
    from ..spi.types import ArrayType, DecimalType, DoubleType, RealType

    if isinstance(expr, CastExpr):
        # fold through a cast ONLY when it is value-preserving for the
        # float fold below (array -> array(double/real)); anything else
        # (array(bigint), narrower decimals) changes values — leave it to
        # the runtime CAST path so fold and execution never disagree
        t = expr.type
        if isinstance(t, ArrayType) and isinstance(
            t.element, (DoubleType, RealType)
        ):
            return fold_constant_array(expr.value)
        return None
    if not (isinstance(expr, Call) and expr.name == "$array"):
        return None
    from ..spi.types import UnknownType, is_numeric

    out = []
    for item in expr.args:
        if not isinstance(item, Constant):
            return None
        if not (is_numeric(item.type) or isinstance(item.type, UnknownType)):
            # strings/booleans/temporals never fold to float lanes — the
            # runtime cast path rejects them, and the fold must agree
            return None
        if item.value is None:
            out.append(None)
        elif isinstance(item.type, DecimalType):
            out.append(float(item.value) / 10**item.type.scale)
        else:
            out.append(float(item.value))
    return tuple(out)


def walk_vector_calls(expr: IrExpr):
    """Yield every tensor-plane Call (similarity family + model scoring)
    inside an IR expression."""
    stack = [expr]
    while stack:
        e = stack.pop()
        if isinstance(e, Call):
            if e.name in VECTOR_SCALAR_FUNCTIONS or e.name in MODEL_CALLS:
                yield e
            stack.extend(e.args)
        elif isinstance(e, CastExpr):
            stack.append(e.value)
        elif isinstance(e, Case):
            for c, r in e.whens:
                stack.append(c)
                stack.append(r)
            if e.default is not None:
                stack.append(e.default)


_ASSIGN_INFO: Dict[tuple, Optional[Tuple[int, int]]] = {}


def assignments_vector_info(assignments) -> Optional[Tuple[int, int]]:
    """(n_tensor_calls, max_dim) over a projection's assignments, or None
    when the projection touches no tensor-plane call. Memoized on the
    (hashable, frozen) assignments tuple — this runs per project execution
    on the hot path, the walk must not."""
    hit = _ASSIGN_INFO.get(assignments, False)
    if hit is not False:
        return hit
    count = 0
    max_dim = 0
    for _, e in assignments:
        for call in walk_vector_calls(e):
            count += 1
            for a in call.args:
                if is_vector(a.type):
                    max_dim = max(max_dim, a.type.dimension)
    info = (count, max_dim) if count else None
    if len(_ASSIGN_INFO) > 4096:  # bound the memo like the compiler cache
        _ASSIGN_INFO.clear()
    _ASSIGN_INFO[assignments] = info
    return info


def vector_dimension_problems(expr: IrExpr):
    """Static shape errors inside an expression, as text — the sanity plane's
    VECTOR-aware check (a dimension mismatch must fail plan validation
    naming the checker, never inside a kernel). Yields messages."""
    for call in walk_vector_calls(expr):
        if call.name in VECTOR_SCALAR_FUNCTIONS:
            dims = []
            for i, a in enumerate(call.args):
                if not is_vector(a.type):
                    yield (
                        f"{call.name} argument {i + 1} has type "
                        f"{a.type.display() if a.type else '?'}, expected vector"
                    )
                else:
                    dims.append(a.type.dimension)
            if len(dims) == 2 and dims[0] != dims[1]:
                yield (
                    f"{call.name}: vector dimensions do not match "
                    f"({dims[0]} vs {dims[1]})"
                )
        elif call.name == LINEAR_MODEL_CALL:
            spec = call.args[0].value if isinstance(call.args[0], Constant) else None
            if spec is not None and len(spec[0]) != len(call.args) - 1:
                yield (
                    f"$linear_model: {len(spec[0])} weights for "
                    f"{len(call.args) - 1} feature arguments"
                )
        elif call.name == GBDT_MODEL_CALL:
            spec = call.args[0].value if isinstance(call.args[0], Constant) else None
            if spec is not None:
                need = model_feature_count(GBDT_MODEL_CALL, spec)
                if need > len(call.args) - 1:
                    yield (
                        f"$gbdt_model: model references feature index "
                        f"{need - 1}, only {len(call.args) - 1} feature "
                        "arguments bound"
                    )


# --------------------------------------------------------------------------- #
# similarity lowering: IR Call -> XLA closure (ops/compiler.py dispatches
# the vector family here)
# --------------------------------------------------------------------------- #


def compile_vector_call(compiler, expr: Call):
    """Lower a vector-family Call. Constant query vectors take the matvec
    form ``data @ q`` — one MXU matmul per page; vector/vector rows (the
    embedding-join shape) lower to a lane-axis einsum. NULL semantics are
    the engine's standard: output valid = AND of input row validities."""
    name = expr.name
    for i, a in enumerate(expr.args):
        if not is_vector(a.type):
            raise CompileError(
                f"{name} argument {i + 1} must be a vector, got "
                f"{a.type.display() if a.type else '?'}"
            )
    if name == "vector_norm":
        inner, _ = compiler.compile(expr.args[0])

        def norm_fn(env) -> CVal:
            v = inner(env)
            data = v.data.astype(jnp.float64)
            return CVal(jnp.sqrt(jnp.sum(data * data, axis=1)), v.valid)

        return norm_fn, None

    a_expr, b_expr = expr.args
    if a_expr.type.dimension != b_expr.type.dimension:
        raise CompileError(
            f"{name}: vector dimensions do not match "
            f"({a_expr.type.dimension} vs {b_expr.type.dimension})"
        )
    # all three binary forms are symmetric: normalize a constant operand to
    # the right so the column side drives the matvec
    if constant_vector_value(a_expr) is not None and constant_vector_value(
        b_expr
    ) is None:
        a_expr, b_expr = b_expr, a_expr
    q = constant_vector_value(b_expr)
    fn_a, _ = compiler.compile(a_expr)

    if q is not None:
        q_np = np.asarray(q, dtype=np.float64)

        def matvec_fn(env) -> CVal:
            v = fn_a(env)
            data = v.data.astype(jnp.float64)
            qd = jnp.asarray(q_np)
            if name == "dot_product":
                out = data @ qd  # (rows, n) @ (n,) — the MXU form
            elif name == "cosine_similarity":
                dot = data @ qd
                na = jnp.sqrt(jnp.sum(data * data, axis=1))
                nq = jnp.sqrt(jnp.sum(qd * qd))
                out = dot / (na * nq)
            else:  # l2_distance — direct form; the expanded
                # ||a||^2 - 2ab + ||b||^2 cancels catastrophically
                diff = data - qd[None, :]
                out = jnp.sqrt(jnp.sum(diff * diff, axis=1))
            return CVal(out, v.valid)

        return matvec_fn, None

    fn_b, _ = compiler.compile(b_expr)

    def rowwise_fn(env) -> CVal:
        va, vb = fn_a(env), fn_b(env)
        a = va.data.astype(jnp.float64)
        b = vb.data.astype(jnp.float64)
        if name == "dot_product":
            out = jnp.einsum("rn,rn->r", a, b)
        elif name == "cosine_similarity":
            dot = jnp.einsum("rn,rn->r", a, b)
            na = jnp.sqrt(jnp.sum(a * a, axis=1))
            nb = jnp.sqrt(jnp.sum(b * b, axis=1))
            out = dot / (na * nb)
        else:
            diff = a - b
            out = jnp.sqrt(jnp.sum(diff * diff, axis=1))
        return CVal(out, va.valid & vb.valid)

    return rowwise_fn, None


# --------------------------------------------------------------------------- #
# model scoring: spec validation + IR lowering
# --------------------------------------------------------------------------- #


def linear_model_spec(weights, bias) -> tuple:
    """Validated hashable spec for ``$linear_model``: (weights, bias)."""
    w = tuple(float(x) for x in weights)
    if not w:
        raise ValueError("linear model needs at least one weight")
    return (w, float(bias))


def gbdt_model_spec(model: dict) -> tuple:
    """Validated hashable spec for ``$gbdt_model``.

    Input shape (the table function parses it from JSON):
    ``{"bias": float, "trees": [{"feature": [...], "threshold": [...],
    "leaf": [...]}, ...]}`` — each tree a FULL binary tree of depth d:
    2**d - 1 internal (feature, threshold) pairs in heap order, 2**d leaf
    values. Trees of differing depth PAD to the ensemble max: every leaf of
    a shallow tree copies its value onto all its padded descendants, so the
    fixed-length vectorized traversal reads the right value no matter how
    the dummy levels route. One uniform depth = one chain of ``depth``
    gather steps over all rows AND all trees at once.
    """
    trees = model.get("trees")
    if not trees:
        raise ValueError("gbdt model has no trees")
    parsed = []
    for i, t in enumerate(trees):
        feat = tuple(int(x) for x in t.get("feature", ()))
        thr = tuple(float(x) for x in t.get("threshold", ()))
        leaf = tuple(float(x) for x in t.get("leaf", ()))
        d = max(len(leaf), 1).bit_length() - 1
        if (1 << d) != len(leaf) or len(feat) != len(leaf) - 1 or len(
            thr
        ) != len(feat) or d < 1:
            raise ValueError(
                f"gbdt tree {i}: need 2**d leaves and 2**d - 1 "
                f"feature/threshold pairs (got {len(leaf)} leaves, "
                f"{len(feat)} features, {len(thr)} thresholds)"
            )
        if min(feat) < 0:
            raise ValueError(f"gbdt tree {i}: negative feature index")
        parsed.append((d, feat, thr, leaf))
    depth = max(d for d, _, _, _ in parsed)
    norm = []
    for d, feat, thr, leaf in parsed:
        if d < depth:
            pad_internal = (1 << depth) - 1 - len(feat)
            feat = feat + (0,) * pad_internal
            thr = thr + (0.0,) * pad_internal
            span = 1 << (depth - d)
            leaf = tuple(v for v in leaf for _ in range(span))
        norm.append((feat, thr, leaf))
    return (float(model.get("bias", 0.0)), tuple(norm))


def model_feature_count(name: str, spec: tuple) -> int:
    if name == LINEAR_MODEL_CALL:
        return len(spec[0])
    return max(f for tree in spec[1] for f in tree[0]) + 1


def compile_model_call(compiler, expr: Call):
    """Lower a ``$linear_model`` / ``$gbdt_model`` Call: features stack into
    one ``(rows, k)`` matrix; linear scoring is a single matvec (MXU), GBDT
    traversal is ``depth`` gather steps vectorized over rows x trees. A row
    with any NULL feature scores NULL (SQL strictness)."""
    spec_arg = expr.args[0]
    if not isinstance(spec_arg, Constant) or spec_arg.value is None:
        raise CompileError(f"{expr.name}: model spec must be a constant")
    spec = spec_arg.value
    feat_fns = [compiler.compile(a)[0] for a in expr.args[1:]]
    k = len(feat_fns)
    if k < model_feature_count(expr.name, spec):
        raise CompileError(
            f"{expr.name}: model references feature "
            f"{model_feature_count(expr.name, spec) - 1}, only {k} "
            "feature arguments bound"
        )

    def features(env):
        vals = [f(env) for f in feat_fns]
        X = jnp.stack([v.data.astype(jnp.float64) for v in vals], axis=1)
        valid = vals[0].valid
        for v in vals[1:]:
            valid = valid & v.valid
        return X, valid

    if expr.name == LINEAR_MODEL_CALL:
        weights, bias = spec
        if len(weights) != k:
            raise CompileError(
                f"$linear_model: {len(weights)} weights for {k} features"
            )
        w_np = np.asarray(weights, dtype=np.float64)

        def linear_fn(env) -> CVal:
            X, valid = features(env)
            out = X @ jnp.asarray(w_np) + jnp.float64(bias)
            return CVal(out, valid)

        return linear_fn, None

    bias, trees = spec
    feat_np = np.asarray([t[0] for t in trees], dtype=np.int32)  # (T, I)
    thr_np = np.asarray([t[1] for t in trees], dtype=np.float64)
    leaf_np = np.asarray([t[2] for t in trees], dtype=np.float64)  # (T, L)
    depth = leaf_np.shape[1].bit_length() - 1
    n_trees = feat_np.shape[0]
    inner = feat_np.shape[1]

    def gbdt_fn(env) -> CVal:
        X, valid = features(env)
        F = jnp.asarray(feat_np)
        TH = jnp.asarray(thr_np)
        LF = jnp.asarray(leaf_np)
        rows = X.shape[0]
        t_ix = jnp.arange(n_trees)[None, :]  # (1, T)
        idx = jnp.zeros((rows, n_trees), dtype=jnp.int32)
        for _ in range(depth):
            node_feat = F[t_ix, idx]  # (rows, T)
            fv = jnp.take_along_axis(X, node_feat, axis=1)
            go_right = (fv > TH[t_ix, idx]).astype(jnp.int32)
            idx = 2 * idx + 1 + go_right
        leaves = LF[t_ix, idx - inner]
        return CVal(jnp.float64(bias) + jnp.sum(leaves, axis=1), valid)

    return gbdt_fn, None


def gbdt_reference_score(spec: tuple, features: np.ndarray) -> np.ndarray:
    """Scalar host oracle for the GBDT lowering (tests): walk each tree with
    plain Python per row."""
    bias, trees = spec
    out = np.full(len(features), float(bias), dtype=np.float64)
    for r, row in enumerate(features):
        for feat, thr, leaf in trees:
            inner = len(feat)
            i = 0
            while i < inner:
                i = 2 * i + 1 + (1 if row[feat[i]] > thr[i] else 0)
            out[r] += leaf[i - inner]
    return out


# --------------------------------------------------------------------------- #
# ingest: array-layout -> dense vector column (INSERT / CTAS conversion)
# --------------------------------------------------------------------------- #


def column_to_vector(col: Column, vtype: VectorType) -> Column:
    """Convert an array-layout column (``data[cap, W]`` + lengths +
    elem_valid) into the dense vector layout. Host-side — this runs at
    ingest boundaries (INSERT INTO a vector column), where a host sync
    already happens. A NULL row stays NULL; a non-NULL row whose array
    length != n is a data error and raises (the dimension is declared on
    the table); a NULL *element* inside a row makes the row NULL — the
    dense layout carries no element mask (same degradation as the
    expression-level CAST, documented in ARCHITECTURE.md)."""
    n = vtype.dimension
    if isinstance(col.type, VectorType):
        if col.type.dimension != n:
            raise ValueError(
                f"cannot store vector({col.type.dimension}) into "
                f"vector({n})"
            )
        return col
    data = np.asarray(col.data)
    valid = np.asarray(col.valid)
    if data.ndim != 2:
        raise ValueError(
            f"cannot convert {col.type.display()} column to {vtype.display()}"
        )
    cap, w = data.shape
    lengths = (
        np.asarray(col.lengths)
        if col.lengths is not None
        else np.full(cap, w, dtype=np.int32)
    )
    bad = valid & (lengths != n)
    if bad.any():
        first = int(np.flatnonzero(bad)[0])
        raise ValueError(
            f"cannot store array of length {int(lengths[first])} into "
            f"{vtype.display()}"
        )
    if w < n:
        # every valid row has length n > W — only possible when all rows
        # are NULL; widen the (empty) lanes
        out = np.zeros((cap, n), dtype=np.float64)
        return Column(vtype, jnp.asarray(out), jnp.asarray(valid & False))
    ev = (
        np.asarray(col.elem_valid)
        if col.elem_valid is not None
        else np.ones((cap, w), dtype=np.bool_)
    )
    new_valid = valid & ev[:, :n].all(axis=1)
    out = np.where(
        new_valid[:, None], data[:, :n].astype(np.float64), 0.0
    )
    return Column(vtype, jnp.asarray(out), jnp.asarray(new_valid))
