"""Worker server: async task lifecycle + pull/ack output buffers.

Reference blueprint (SURVEY.md §2.7, §3.2-3.3):
- server/TaskResource.java:93 — `POST /v1/task/{id}` creates/updates a task,
  `GET /v1/task/{id}?maxWait=..` long-polls status (:230),
  `GET /v1/task/{id}/results/{buffer}/{token}` pulls pages (:334) with
  at-least-once delivery + token acknowledgement (:375),
  `DELETE /v1/task/{id}` aborts.
- execution/SqlTaskManager.java:109 — the task registry;
  execution/buffer/PartitionedOutputBuffer.java:42 — per-consumer buffers
  with backpressure (OutputBufferMemoryManager analogue: bounded unacked
  bytes block the producer).

A task = one fragment × one partition. The plan travels in the schema'd JSON
codec (runtime/plancodec.py) — never executable serialization — and every
internal request carries an HMAC-SHA256 signature under the cluster's shared
secret (ref: server/InternalAuthenticationManager.java).

Tasks pull their RemoteSource inputs directly from the producing workers'
output buffers (worker→worker, DirectExchangeClient.java:270 analogue), so
stages of one query overlap across the cluster instead of executing behind a
coordinator barrier.
"""

from __future__ import annotations

import hashlib
import heapq
import hmac as hmac_mod
import json
import socket
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..metadata import CatalogManager, Metadata, Session
from ..planner.plan import LogicalPlan
from ..runtime import plancodec
from ..runtime.failure import TaskDeadlineExceeded, chaos_fire
from ..runtime.observability import RECORDER, on_exchange_pull, on_exchange_push
from ..runtime.serde import deserialize_page, serialize_page
from ..runtime.tracing import TRACER
from .. import knobs

SECRET_ENV = "TRINO_TPU_INTERNAL_SECRET"
SIGNATURE_HEADER = "X-Trino-Tpu-Signature"
# producer-side backpressure: unacknowledged bytes per consumer buffer before
# add() blocks (OutputBufferMemoryManager analogue)
MAX_UNACKED_BYTES = 64 * 1024 * 1024


def sign(secret: Optional[str], method: str, path: str, body: bytes = b"") -> str:
    """HMAC over method + path + body hash: a captured signature cannot be
    replayed as a different method (status poll -> DELETE) or task id."""
    if not secret:
        return ""
    msg = (
        method.encode() + b"\n" + path.encode() + b"\n"
        + hashlib.sha256(body).digest()
    )
    return hmac_mod.new(secret.encode(), msg, hashlib.sha256).hexdigest()


def verify(
    secret: Optional[str], method: str, path: str, body: bytes, signature: Optional[str]
) -> bool:
    if not secret:
        return True  # localhost-only deployments may run unauthenticated
    if not signature:
        return False
    return hmac_mod.compare_digest(sign(secret, method, path, body), signature)


class TaskFailedError(RuntimeError):
    """A producer task reported FAILED. ``error_text`` is the task's error;
    callers distinguish infrastructure failures (retryable) from
    deterministic query errors by it."""

    def __init__(self, task_id: str, error_text: str):
        super().__init__(f"producer task {task_id} failed: {error_text}")
        self.error_text = error_text or ""


def pull_buffer(url: str, task_id: str, buffer_id: int, secret: Optional[str],
                deadline: Optional[float] = None):
    """Generator of page blobs from a producer task's output buffer — THE
    exchange-client wire protocol (token-acked pulls, at-least-once; ref:
    operator/DirectExchangeClient.java:270, HttpPageBufferClient:348). Shared
    by worker->worker input pulls and the coordinator's root-result pull.
    Raises TaskFailedError when the producer task failed.

    ``deadline`` (monotonic seconds) bounds the WHOLE pull: a producer that
    accepts its task then hangs raises TaskDeadlineExceeded here instead of
    stalling the consumer forever (each 2 s long-poll returning empty used
    to loop unbounded)."""
    token = 0
    while True:
        if deadline is not None and time.monotonic() >= deadline:
            raise TaskDeadlineExceeded(
                f"pull of task {task_id} buffer {buffer_id} exceeded its "
                f"completion deadline"
            )
        timeout = 300.0
        if deadline is not None:
            timeout = max(1.0, min(300.0, deadline - time.monotonic() + 5.0))
        rel = f"/v1/task/{task_id}/results/{buffer_id}/{token}"
        req = urllib.request.Request(f"{url.rstrip('/')}{rel}?maxWait=2", method="GET")
        req.add_header(SIGNATURE_HEADER, sign(secret, "GET", rel))
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            meta = json.loads(resp.headers.get("X-Page-Meta", "{}"))
            body = resp.read()
        # failure checked BEFORE completion: a task that failed without
        # emitting pages must never read as an empty successful buffer
        if meta.get("failed"):
            raise TaskFailedError(task_id, str(meta.get("error")))
        off = 0
        for size in meta.get("sizes", []):
            yield body[off : off + size]
            off += size
        token = int(meta.get("next_token", token))
        if meta.get("complete") and not meta.get("sizes"):
            return


class TaskState(Enum):
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELED = "CANCELED"


@dataclass
class TaskDescriptor:
    """What the coordinator ships per task (HttpRemoteTask's update payload).

    ``inputs``: fragment_id -> {"exchange_type": str, "buffer": int,
    "sources": [{"url": str, "task": str}], "inline": [page bytes hex]}.
    ``output``: {"kind": "partitioned"|"gather"|"broadcast", "n": int,
    "keys": [symbol, ...]} — how this task's output splits into buffers.
    """

    root: object = None
    types: Dict[str, object] = field(default_factory=dict)
    session_props: Dict[str, object] = field(default_factory=dict)
    partition: int = 0
    n_workers: int = 1
    inputs: Dict[int, dict] = field(default_factory=dict)
    output: dict = field(default_factory=lambda: {"kind": "gather", "n": 1})
    # coordinator-side trace parentage (Tracer.capture_ids()): worker task
    # spans join the query trace instead of orphaning — task creation
    # arrives over HTTP, so a same-process capture can't carry it
    trace: Optional[Dict[str, str]] = None
    # task completion deadline RELATIVE seconds (the scheduler's
    # task_completion_timeout): a task still queued past it fails instead
    # of starting work the coordinator already abandoned
    deadline_secs: Optional[float] = None
    # resource-group scheduling weight of the owning query (the
    # coordinator's device_scheduler.current_priority() at dispatch time):
    # the fair executor drains higher-weight groups first
    priority: float = 1.0


def encode_task(desc: TaskDescriptor) -> bytes:
    payload = {
        "root": plancodec.encode(desc.root),
        "types": plancodec.encode(desc.types),
        "session_props": plancodec.encode(desc.session_props),
        "partition": desc.partition,
        "n_workers": desc.n_workers,
        "inputs": {
            str(fid): {
                **{k: v for k, v in spec.items() if k != "inline"},
                "inline": [b.hex() for b in spec.get("inline", [])],
            }
            for fid, spec in desc.inputs.items()
        },
        "output": desc.output,
    }
    if desc.trace:
        payload["trace"] = desc.trace
    if desc.deadline_secs is not None:
        payload["deadline_secs"] = desc.deadline_secs
    if desc.priority != 1.0:
        payload["priority"] = desc.priority
    return json.dumps(payload, separators=(",", ":")).encode()


def decode_task(data: bytes) -> TaskDescriptor:
    payload = json.loads(data)
    return TaskDescriptor(
        root=plancodec.decode(payload["root"]),
        types=plancodec.decode(payload["types"]),
        session_props=plancodec.decode(payload["session_props"]),
        partition=payload["partition"],
        n_workers=payload["n_workers"],
        inputs={
            int(fid): {
                **{k: v for k, v in spec.items() if k != "inline"},
                "inline": [bytes.fromhex(h) for h in spec.get("inline", [])],
            }
            for fid, spec in payload["inputs"].items()
        },
        output=payload["output"],
        trace=payload.get("trace"),
        deadline_secs=payload.get("deadline_secs"),
        priority=float(payload.get("priority", 1.0)),
    )


class OutputBuffer:
    """Per-task partitioned output: n consumer buffers of serialized pages,
    pull-based with token acknowledgement (at-least-once + dedup by token,
    ref: execution/buffer/PartitionedOutputBuffer.java:42, ClientBuffer).
    Acknowledged pages are FREED — the ack exists to release memory, not just
    to relieve backpressure accounting.

    Backpressure accounting is a TRACKED byte counter per consumer buffer
    (the old path re-summed every buffered page on each 0.1 s poll wakeup —
    O(pages) work burning CPU under a slow consumer); producers now block on
    the condition and are woken by the ack that frees bytes. Broadcast blobs
    are stored once (one shared bytes object in every buffer) and their
    length is CHARGED once, split across the consumer buffers — the old
    accounting charged the same blob n times and tripped backpressure at 1/n
    of the real memory limit."""

    def __init__(self, n_buffers: int):
        self._cond = threading.Condition()
        self._pages: List[List[bytes]] = [[] for _ in range(n_buffers)]
        # charged bytes per buffered page (== len(page) for exclusive blobs,
        # a 1/n share for broadcast blobs), aligned with _pages
        self._charges: List[List[int]] = [[] for _ in range(n_buffers)]
        self._bytes: List[int] = [0] * n_buffers  # tracked unacked charge
        self._base: List[int] = [0] * n_buffers  # token of _pages[b][0]
        self._complete = False

    def buffered_bytes(self) -> int:
        """Total unacked charged bytes (observability; shared blobs once)."""
        with self._cond:
            return sum(self._bytes)

    def _append_locked(self, buffer_id: int, page: bytes, charge: int) -> None:
        self._pages[buffer_id].append(page)
        self._charges[buffer_id].append(charge)
        self._bytes[buffer_id] += charge
        self._cond.notify_all()

    def add(self, buffer_id: int, page: bytes) -> None:
        on_exchange_push(len(page))
        with self._cond:
            # backpressure: block while this consumer is too far behind
            # (woken by the ack in get() or by set_complete — no polling)
            while self._bytes[buffer_id] > MAX_UNACKED_BYTES and not self._complete:
                self._cond.wait()
            self._append_locked(buffer_id, page, len(page))

    def add_broadcast(self, page: bytes) -> None:
        """One blob into EVERY consumer buffer: stored shared (n references
        to one bytes object) and charged ONCE — len(page) split across the
        buffers — so a broadcast edge hits backpressure at the same real
        memory bound as a partitioned one."""
        n = len(self._pages)
        if n == 0:
            return
        on_exchange_push(len(page))  # pushed once, not n times
        share, rem = divmod(len(page), n)
        with self._cond:
            while (
                max(self._bytes) > MAX_UNACKED_BYTES and not self._complete
            ):
                self._cond.wait()
            for b in range(n):
                self._append_locked(b, page, share + (1 if b < rem else 0))

    def set_complete(self) -> None:
        with self._cond:
            self._complete = True
            self._cond.notify_all()

    def get(
        self, buffer_id: int, token: int, max_wait: float
    ) -> Tuple[List[bytes], int, bool]:
        """Pages from sequence ``token`` on; requesting token N acknowledges
        (and frees) everything below N. Re-requests of unacked tokens are
        served (at-least-once); acked tokens are gone."""
        deadline = time.monotonic() + max_wait
        with self._cond:
            drop = max(0, min(token - self._base[buffer_id], len(self._pages[buffer_id])))
            if drop:
                self._bytes[buffer_id] -= sum(self._charges[buffer_id][:drop])
                del self._pages[buffer_id][:drop]
                del self._charges[buffer_id][:drop]
                self._base[buffer_id] += drop
            self._cond.notify_all()
            while True:
                start = token - self._base[buffer_id]
                pages = self._pages[buffer_id][max(start, 0):]
                if pages or self._complete:
                    return pages, token + len(pages), self._complete
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], token, False
                self._cond.wait(remaining)


@dataclass
class Task:
    task_id: str
    state: TaskState = TaskState.RUNNING
    error: Optional[str] = None
    buffer: Optional[OutputBuffer] = None
    version: int = 0  # bumped on each state change (status long-poll)
    ended_at: Optional[float] = None  # monotonic time of terminal transition
    # scheduling observability (PrioritizedSplitRunner stats analogue)
    queued_at: Optional[float] = None
    started_at: Optional[float] = None
    # absolute (monotonic) completion deadline, from the descriptor
    deadline: Optional[float] = None

    @property
    def queued_secs(self) -> Optional[float]:
        if self.queued_at is None or self.started_at is None:
            return None
        return self.started_at - self.queued_at

    @property
    def run_secs(self) -> Optional[float]:
        if self.started_at is None:
            return None
        return (self.ended_at or time.monotonic()) - self.started_at


class FairTaskExecutor:
    """Bounded worker pool draining a FAIR queue: the next task to start is
    the one whose QUERY has accumulated the least WEIGHTED scheduled time
    (ref: executor/timesharing/TimeSharingTaskExecutor.java:84 +
    MultilevelSplitQueue; weights are the reference's resource-group
    scheduling weights). Our work units are whole single-dispatch device
    programs — not preemptible mid-run on a TPU — so the reference's 1 s
    quanta fairness acts at task-start granularity here: a query that has
    consumed the executor yields the next slot to the least-served query.
    The heap key is ``usage / weight``: a weight-4 group's query pops
    ahead of an equal-usage weight-1 query (it is "owed" 4x the share) —
    the round-9 per-query FIFO ignored the group weight entirely, so
    high-priority groups queued behind whoever arrived first. Per-task
    queue/run times are recorded for EXPLAIN-level observability (the
    PrioritizedSplitRunner stats analogue)."""

    def __init__(self, n_threads: int = 4):
        self._cond = threading.Condition()
        # per-query FIFO + a heap of (usage/weight snapshot, head seq,
        # query_id): picking the next task is O(log n) instead of the old
        # full re-sort under the lock. Heap entries go stale when a query's
        # usage (or weight) moves between push and pop; a stale entry is
        # re-pushed with the current key (lazy decrease-key), so each pop
        # is amortized O(log n).
        self._queues: Dict[str, deque] = {}  # query -> [(seq, task_id, fn), ...]
        self._heap: list = []  # [usage/weight, head_seq, query_id]
        self._in_heap: set = set()
        self._pending = 0
        self._usage: Dict[str, float] = {}
        self._weights: Dict[str, float] = {}  # query -> group weight (>=, max)
        self._running: Dict[str, int] = {}  # query -> in-flight task count
        self._seq = 0
        self._shutdown = False
        self._threads = [
            threading.Thread(target=self._loop, daemon=True, name=f"fair-exec-{i}")
            for i in range(max(1, n_threads))
        ]
        for t in self._threads:
            t.start()

    def _key_locked(self, query_id: str) -> float:
        return self._usage.get(query_id, 0.0) / self._weights.get(query_id, 1.0)

    def submit(self, query_id: str, task_id: str, fn, weight: float = 1.0) -> None:
        with self._cond:
            self._seq += 1
            self._usage.setdefault(query_id, 0.0)
            self._weights[query_id] = max(
                self._weights.get(query_id, 1.0), float(weight) or 1.0
            )
            dq = self._queues.get(query_id)
            if dq is None:
                dq = self._queues[query_id] = deque()
            dq.append((self._seq, task_id, fn))
            self._pending += 1
            if query_id not in self._in_heap:
                heapq.heappush(
                    self._heap, (self._key_locked(query_id), dq[0][0], query_id)
                )
                self._in_heap.add(query_id)
            # bound the usage ledger on long-lived workers: evict idle
            # queries (none queued) once the ledger grows past a cap —
            # re-arrival simply restarts them at zero (slightly favored,
            # exactly how a fresh query is treated)
            if len(self._usage) > 512:
                active = {q for q, dq in self._queues.items() if dq} | {
                    q for q, n in self._running.items() if n > 0
                }
                for q in [q for q in self._usage if q not in active][:256]:
                    del self._usage[q]
                    self._weights.pop(q, None)
            self._cond.notify()

    def _pop_locked(self):
        """Least weighted-served query first; FIFO within a query (heap
        invariant: every query with queued tasks has exactly one heap
        entry)."""
        while True:
            key, _, query_id = heapq.heappop(self._heap)
            q = self._queues.get(query_id)
            if not q:  # ledger-evicted or drained under a stale entry
                self._in_heap.discard(query_id)
                continue
            current = self._key_locked(query_id)
            if key != current:  # stale snapshot: re-key and retry
                heapq.heappush(self._heap, (current, q[0][0], query_id))
                continue
            seq, task_id, fn = q.popleft()
            self._pending -= 1
            if q:
                heapq.heappush(self._heap, (current, q[0][0], query_id))
            else:
                del self._queues[query_id]
                self._in_heap.discard(query_id)
            return query_id, seq, task_id, fn

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._shutdown:
                    self._cond.wait()
                if self._shutdown:
                    return
                query_id, _, task_id, fn = self._pop_locked()
                self._running[query_id] = self._running.get(query_id, 0) + 1
            t0 = time.monotonic()
            try:
                fn()
            finally:
                with self._cond:
                    self._usage[query_id] = (
                        self._usage.get(query_id, 0.0) + time.monotonic() - t0
                    )
                    left = self._running.get(query_id, 1) - 1
                    if left:
                        self._running[query_id] = left
                    else:
                        self._running.pop(query_id, None)

    def stop(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()


def _query_of(task_id: str) -> str:
    """Task ids are '<query>_f<fid>_p<p>...' — fall back to the whole id."""
    return task_id.split("_f")[0] if "_f" in task_id else task_id


# process-wide TaskManager registry: system.runtime.tasks snapshots every
# live manager in this host process (weak — a stopped WorkerServer's manager
# disappears with it)
import weakref

_TASK_MANAGERS: "weakref.WeakSet" = weakref.WeakSet()


def all_task_managers():
    """Live TaskManagers in this process (system.runtime.tasks source)."""
    return list(_TASK_MANAGERS)


class TaskManager:
    """ref: execution/SqlTaskManager.java:109 — the worker-side registry.
    Terminal tasks are evicted after ``task_ttl_secs`` (QueryTracker-style
    expiry), so long-lived workers don't retain query outputs forever."""

    def __init__(
        self,
        metadata: Metadata,
        secret: Optional[str],
        task_ttl_secs: float = 300.0,
        task_threads: int = 4,
        memory_pool=None,
        recorder=None,
    ):
        from ..runtime.memory import default_pool

        self.metadata = metadata
        self.secret = secret
        self.task_ttl_secs = task_ttl_secs
        # cluster observability plane: the flight recorder this worker's
        # task spans land in and /v1/flightrecorder serves from. Defaults
        # to the process-global ring (one process = one node); tests and
        # multi-worker-per-process harnesses install per-node rings here.
        self.recorder = recorder if recorder is not None else RECORDER
        # worker memory pool (ref: the worker half of io.trino.memory): task
        # fragment executors reserve against it under the TASK id, so one
        # worker's HBM backpressures its tasks; the pool state rides the
        # announcement path for the coordinator's ClusterMemoryManager.
        # Kill decisions stay coordinator-side (no kill_fn here).
        self.memory_pool = memory_pool if memory_pool is not None else default_pool()
        self._tasks: Dict[str, Task] = {}
        self.created_total = 0  # lifetime counter (placement observability)
        self._cond = threading.Condition()
        self.executor = FairTaskExecutor(task_threads)
        # local-exchange shortcut: this worker's own URLs (set by
        # WorkerServer.start) — pulls from self read the producer buffer
        # in-process instead of looping through HTTP
        self.self_urls: set = set()
        self.local_exchange_pages = 0
        # system.runtime.tasks identity (WorkerServer sets the bound address)
        self.node_id = "worker"
        _TASK_MANAGERS.add(self)

    def count(self) -> int:
        """Lifetime created-task count (scheduler-placement observability)."""
        return self.created_total

    def snapshot(self) -> List[dict]:
        """Lock-brief task rows for system.runtime.tasks: the registry lock
        is held only to copy the task list; per-task fields are plain reads
        of monotonic attributes (a racing transition skews one row by one
        state, which the eventually-consistent contract allows)."""
        with self._cond:
            tasks = list(self._tasks.values())
        rows = []
        for t in tasks:
            buffered = None
            if t.buffer is not None:
                buffered = t.buffer.buffered_bytes()
            rows.append({
                "nodeId": self.node_id,
                "taskId": t.task_id,
                "queryId": _query_of(t.task_id),
                "state": t.state.value,
                "error": t.error,
                "queuedSecs": t.queued_secs,
                "runSecs": t.run_secs,
                "bufferedPages": buffered,
            })
        return rows

    def get(self, task_id: str) -> Optional[Task]:
        with self._cond:
            return self._tasks.get(task_id)

    def _evict_expired_locked(self) -> None:
        now = time.monotonic()
        for tid in [
            t.task_id
            for t in self._tasks.values()
            if t.state != TaskState.RUNNING
            and t.ended_at is not None
            and now - t.ended_at > self.task_ttl_secs
        ]:
            del self._tasks[tid]

    def create(self, task_id: str, desc: TaskDescriptor) -> Task:
        with self._cond:
            self._evict_expired_locked()
            existing = self._tasks.get(task_id)
            if existing is not None:
                return existing  # idempotent create-or-update
            self.created_total += 1
            task = Task(task_id, buffer=OutputBuffer(int(desc.output.get("n", 1))))
            task.queued_at = time.monotonic()
            if desc.deadline_secs is not None:
                task.deadline = task.queued_at + float(desc.deadline_secs)
            self._tasks[task_id] = task
        # ONLY fully self-contained tasks ride the bounded fair pool: durable
        # (FTE) outputs commit to the exchange store and push a zero-byte
        # buffer marker, so a pooled task can never block. Tasks that either
        # PULL peer buffers ("sources" inputs) or PRODUCE consumer-pulled
        # buffers can block on peers/backpressure while holding a pool
        # thread — with a bounded pool that deadlocks (producers waiting on
        # a consumer that waits on a queued producer) — so they keep a
        # dedicated thread (ThreadPerDriverTaskExecutor role).
        streaming = (
            any(spec.get("sources") for spec in desc.inputs.values())
            or desc.output.get("kind") != "durable"
        )
        # trace-context propagation: FTE/streaming task threads (and fair-
        # pool slots) get fresh Tracer thread-local stacks — capture the
        # submitting thread's span so task spans join the query trace
        run = TRACER.wrap(lambda: self._run(task, desc))
        if streaming:
            thread = threading.Thread(
                target=run, daemon=True, name=f"task-{task_id}",
            )
            thread.start()
        else:
            # the descriptor carries the owning query's resource-group
            # scheduling weight — the fair pop drains heavy groups first
            self.executor.submit(
                _query_of(task_id), task_id, run, weight=desc.priority
            )
        return task

    def cancel(self, task_id: str) -> Optional[Task]:
        task = self.get(task_id)
        if task is not None:
            self._transition(task, TaskState.CANCELED)
            task.buffer.set_complete()
        return task

    def delete(self, task_id: str) -> Optional[Task]:
        """Abort + drop immediately (the coordinator's end-of-query cleanup)."""
        task = self.cancel(task_id)
        with self._cond:
            self._tasks.pop(task_id, None)
        return task

    def status_longpoll(self, task_id: str, version: int, max_wait: float) -> Optional[Task]:
        deadline = time.monotonic() + max_wait
        with self._cond:
            while True:
                task = self._tasks.get(task_id)
                if task is None or task.version > version or task.state != TaskState.RUNNING:
                    return task
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return task
                self._cond.wait(remaining)

    def _transition(self, task: Task, state: TaskState, error: Optional[str] = None):
        with self._cond:
            if task.state == TaskState.RUNNING:
                task.state = state
                task.error = error
                task.ended_at = time.monotonic()
            task.version += 1
            self._cond.notify_all()

    # --------------------------------------------------------------- execution

    def _run(self, task: Task, desc: TaskDescriptor) -> None:
        from ..runtime.memory import memory_scope

        task.started_at = time.monotonic()
        try:
            if task.deadline is not None and task.started_at > task.deadline:
                # queued past its completion deadline: the coordinator has
                # already abandoned this attempt — fail fast instead of
                # burning executor time on work nobody will read
                raise TaskDeadlineExceeded(
                    f"task {task.task_id} started after its completion deadline"
                )
            # parentage into the query trace comes from desc.trace (the
            # coordinator's capture_ids(), shipped in the descriptor — task
            # creation arrives over HTTP on a span-less handler thread) or,
            # for in-process schedulers, the context captured at create()
            # via TRACER.wrap. Without either the task span would orphan.
            # The memory scope charges the fragment executor's reservations
            # to the worker pool under the TASK id (freed when it ends).
            with TRACER.attach_remote(desc.trace), TRACER.span(
                "task", task_id=task.task_id
            ), self.recorder.span("task", "task", task_id=task.task_id), \
                    memory_scope(task.task_id, self.memory_pool):
                self._run_inner(task, desc)
            task.buffer.set_complete()
            self._transition(task, TaskState.FINISHED)
        except Exception as e:  # noqa: BLE001 — failures become task state
            # transition BEFORE completing the buffer: a consumer woken by
            # set_complete must observe FAILED, never a "successful" partial
            # buffer (cancel() relies on the same order)
            self._transition(task, TaskState.FAILED, f"{type(e).__name__}: {e}")
            task.buffer.set_complete()
        finally:
            if self.memory_pool is not None:
                self.memory_pool.free_owner(task.task_id)

    def memory_info(self) -> dict:
        """This worker's pool state for the announcement path (empty dict
        when no pool is configured — arbitration is opt-in)."""
        if self.memory_pool is None:
            return {}
        return self.memory_pool.memory_announcement()

    def _run_inner(self, task: Task, desc: TaskDescriptor) -> None:
        from ..parallel.runner import _FragmentExecutor, run_fragment_partition
        from ..spi.host_pages import (
            page_from_host_chunks as _page_from_host_chunks,
            page_to_host as _page_to_host,
        )

        from ..runtime.spiller import io_pool

        staged = {}
        for fid, spec in desc.inputs.items():
            # deserialize on the shared I/O pool: frame decode (LZ4 +
            # device_put) of blob i overlaps the pull of blob i+1 — the
            # exchange-tier mirror of the OOC double buffer
            pool = io_pool()
            futs = [
                pool.submit(deserialize_page, b) for b in spec.get("inline", [])
            ]
            for src in spec.get("sources", []):
                for blob in self._pull_pages(
                    src["url"], src["task"], int(spec.get("buffer", 0))
                ):
                    futs.append(pool.submit(deserialize_page, blob))
            pages = [f.result() for f in futs]
            durable = spec.get("durable")
            if durable is not None:
                # worker-direct FTE data plane: read this task's input
                # parts straight from the durable exchange store — the
                # coordinator shipped only this descriptor (ref:
                # FileSystemExchangeSource; exchange bytes never touch
                # the coordinator)
                from ..runtime.fte_plane import stage_durable_input

                staged[fid] = [stage_durable_input(durable, desc.types)]
                continue
            if not pages:
                raise RuntimeError(f"no input pages for fragment {fid}")
            staged[fid] = [
                _page_from_host_chunks([_page_to_host(p) for p in pages])
            ]
        session = Session(properties=dict(desc.session_props))
        plan = LogicalPlan(desc.root, desc.types)
        executor = _FragmentExecutor(
            plan, self.metadata, session, staged, desc.partition, desc.n_workers
        )
        # device batching plane: concurrent tasks on this worker pack
        # compatible fragment subtrees / share overlapping scans (no-op
        # unless the session ships device_batching=true)
        from ..runtime.device_scheduler import attach as _attach_batching

        _attach_batching(
            executor, self.metadata, session,
            catalogs=getattr(self.metadata, "catalogs", None),
            scope=f"part{desc.partition}/{desc.n_workers}",
        )
        # megakernel plane: tell the executor this fragment's output feeds a
        # hash exchange, so a fused root runs the repartition epilogue as
        # its kernel output stage (ops/megakernels.attach_epilogue) and
        # _emit_output's repartition skips the standalone hash program
        out_keys = list(desc.output.get("keys", []))
        out_n = int(desc.output.get("n", 1))
        if out_keys and out_n > 1 and desc.output.get("kind") not in (
            "gather", "broadcast",
        ):
            executor.repartition_hint = (tuple(out_keys), out_n)
        out_page = run_fragment_partition(executor, desc.root)
        self._emit_output(task, desc, out_page)

    def _emit_output(self, task: Task, desc: TaskDescriptor, page) -> None:
        from ..ops.repartition import (
            device_repartition_enabled,
            repartition_frames,
            supports_device_repartition,
        )
        from ..runtime.spiller import io_pool
        from ..spi.host_pages import (
            host_partition_targets,
            page_to_host as _page_to_host,
            pages_from_host_rows as _pages_from_host_rows,
        )

        kind = desc.output.get("kind", "gather")
        n = int(desc.output.get("n", 1))
        if kind == "durable":
            # worker-direct FTE data plane: partition + COMMIT to the durable
            # exchange here; the coordinator learns success from task state
            # only (ref: FileSystemExchangeSink — workers write shuffle
            # storage directly)
            self._emit_durable(desc, page)
            task.buffer.add(0, b"")  # completion marker, no payload
            return
        if kind == "gather" or n == 1:
            task.buffer.add(0, serialize_page(page))
            return
        if kind == "broadcast":
            # serialized once, stored shared, charged once (add_broadcast)
            task.buffer.add_broadcast(serialize_page(page))
            return
        # partitioned: split rows by key hash. Primary path is the compiled
        # device epilogue (ops/repartition.py): ONE D2H of a partition-
        # contiguous page + sliced v2 frames, instead of whole-page D2H +
        # numpy hashing + n boolean selection passes.
        out_syms = list(desc.output.get("symbols", []))
        key_idx = [out_syms.index(k) for k in desc.output.get("keys", [])]
        if (
            page.columns
            and device_repartition_enabled()
            and supports_device_repartition(page)
        ):
            blobs, _ = repartition_frames(page, key_idx, n, pool=io_pool())
            for b in range(n):
                task.buffer.add(b, blobs[b])
            return
        # host fallback: nested layouts or the A/B kill-switch
        cols = _page_to_host(page)
        if not cols or len(cols[0][1]) == 0:
            task.buffer.add_broadcast(serialize_page(page))
            return
        target = host_partition_targets(cols, key_idx, n)
        for b in range(n):
            sel = target == b
            task.buffer.add(b, serialize_page(_pages_from_host_rows(cols, sel)))

    def _emit_durable(self, desc: TaskDescriptor, page) -> None:
        from ..runtime.fte_plane import emit_durable_output

        emit_durable_output(desc.output, page)

    def _pull_pages(self, url: str, producer_task: str, buffer_id: int):
        """STREAM one producer's buffer to completion (DirectExchangeClient):
        blobs yield as they arrive (exchange-pull accounted per frame), so
        the caller overlaps deserialization with the remaining pulls. When
        the producer runs on THIS worker the pages hand off in-process
        (LocalExchange.java:66 role — no HTTP loop through the kernel)."""
        if url.rstrip("/") in self.self_urls:
            source = self._pull_local(producer_task, buffer_id)
        else:
            source = pull_buffer(url, producer_task, buffer_id, self.secret)
        for p in source:
            on_exchange_pull(len(p))
            yield p

    def _pull_local(self, producer_task: str, buffer_id: int):
        token = 0
        while True:
            task = self.get(producer_task)
            if task is None:
                raise TaskFailedError(producer_task, "task vanished")
            blobs, next_token, complete = task.buffer.get(
                buffer_id, token, max_wait=2.0
            )
            # failure checked BEFORE completion (same order as the HTTP
            # handler): a failed task must never read as an empty success
            if task.state == TaskState.FAILED:
                raise TaskFailedError(producer_task, str(task.error))
            self.local_exchange_pages += len(blobs)
            yield from blobs
            token = next_token
            if complete and not blobs:
                return


class WorkerServer:
    """Executes fragments against locally-registered catalogs (workers mount
    the same catalog config as the coordinator, as in Trino)."""

    def __init__(
        self,
        catalogs: CatalogManager,
        host: str = "127.0.0.1",
        port: int = 0,
        secret: Optional[str] = None,
        task_threads: int = 4,
    ):
        self.catalogs = catalogs
        self.metadata = Metadata(catalogs)
        self.host = host
        self.secret = secret if secret is not None else knobs.env_str(SECRET_ENV)
        if host not in ("127.0.0.1", "localhost") and not self.secret:
            raise ValueError(
                "non-localhost workers require a shared secret "
                f"({SECRET_ENV} or secret=...) for request authentication"
            )
        self.tasks = TaskManager(self.metadata, self.secret, task_threads=task_threads)
        # cluster observability: RTT of the last announce round trip (µs),
        # carried on the NEXT announcement's clock rider; None until the
        # first round trip is measured (a claimed rtt=0 would win ClockSync's
        # min-RTT rule forever and lock in a one-way-delay-biased offset)
        self._last_announce_rtt_us: Optional[float] = None
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _chaos_transport(self) -> bool:
                """Chaos-harness RPC faults (ref: InjectedFailureType's
                TASK_MANAGEMENT_REQUEST_FAILURE/TIMEOUT): ``transport_refuse``
                drops the connection unanswered (the client sees a reset,
                exactly like a crashed worker), ``transport_hang`` stalls the
                reply past the caller's deadline, ``transport_slow`` adds
                latency but answers. Returns True when the request was
                swallowed."""
                text = self.path
                if chaos_fire("transport_refuse", text=text) is not None:
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:  # lint: disable=bare-except-swallow -- chaos refusal path: the socket may already be gone
                        pass
                    self.close_connection = True
                    return True
                act = chaos_fire("transport_hang", text=text)
                if act is not None:
                    time.sleep(float(act.get("delay", 5.0)))
                else:
                    act = chaos_fire("transport_slow", text=text)
                    if act is not None:
                        time.sleep(float(act.get("delay", 0.1)))
                return False

            def _reply(self, code: int, body: bytes = b"", headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _task_parts(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if len(parts) >= 3 and parts[0] == "v1" and parts[1] == "task":
                    return parts[2:]
                return None

            def do_POST(self):
                # host-path plane: the worker's protocol phases (accept ->
                # HMAC verify -> parse/decode -> dispatch) get the same
                # paired proto_* spans as the coordinator front
                from ..runtime.hostprof import phase_span

                rec = worker.tasks.recorder
                with phase_span(rec, "accept", path="task_create"):
                    if self._chaos_transport():
                        return
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    rel = self.path.split("?")[0]
                    with phase_span(rec, "verify"):
                        ok = verify(
                            worker.secret, "POST", rel, body,
                            self.headers.get(SIGNATURE_HEADER),
                        )
                    if not ok:
                        self._reply(401, b"invalid signature")
                        return
                    parts = self._task_parts()
                    if parts is None or len(parts) != 1:
                        self._reply(404)
                        return
                    try:
                        with phase_span(rec, "parse", task_id=parts[0]):
                            desc = decode_task(body)
                        with phase_span(rec, "dispatch", task_id=parts[0]):
                            task = worker.tasks.create(parts[0], desc)
                        self._reply(200, _status_json(task))
                    except Exception as e:  # noqa: BLE001
                        self._reply(400, f"{type(e).__name__}: {e}".encode())

            def do_GET(self):
                if self._chaos_transport():
                    return
                if self.path.split("?")[0] == "/v1/flightrecorder":
                    # cluster observability plane: this node's flight-
                    # recorder segment, filtered by query id — the
                    # coordinator's cross-node trace assembly pulls it.
                    # Gated on $TRINO_TPU_CLUSTER_OBS (404 when off, byte-
                    # identical to the pre-plane worker) and signed like
                    # every other internal request.
                    from ..runtime import clusterobs

                    if not clusterobs.server_enabled():
                        self._reply(404)
                        return
                    if not verify(
                        worker.secret, "GET", "/v1/flightrecorder", b"",
                        self.headers.get(SIGNATURE_HEADER),
                    ):
                        self._reply(401, b"invalid signature")
                        return
                    query = dict(
                        kv.split("=", 1)
                        for kv in (self.path.split("?", 1) + [""])[1].split("&")
                        if "=" in kv
                    )
                    qid = query.get("query_id", "")
                    trace = clusterobs.local_segment(
                        [qid] if qid else [], recorder=worker.tasks.recorder
                    )
                    self._reply(200, json.dumps({
                        "node": worker.tasks.node_id,
                        "monoUs": time.monotonic_ns() // 1000,
                        "trace": trace,
                    }).encode())
                    return
                if self.path.split("?")[0] == "/v1/memory":
                    # worker pool state (the announcement payload's source of
                    # truth) — signed like every other worker request: pool
                    # pressure is cluster-internal state
                    if not verify(
                        worker.secret, "GET", "/v1/memory", b"",
                        self.headers.get(SIGNATURE_HEADER),
                    ):
                        self._reply(401, b"invalid signature")
                        return
                    self._reply(
                        200, json.dumps(worker.tasks.memory_info()).encode()
                    )
                    return
                parts = self._task_parts()
                if parts is None:
                    self._reply(404)
                    return
                if not verify(
                    worker.secret,
                    "GET",
                    self.path.split("?")[0],
                    b"",
                    self.headers.get(SIGNATURE_HEADER),
                ):
                    self._reply(401, b"invalid signature")
                    return
                query = dict(
                    kv.split("=", 1)
                    for kv in (self.path.split("?", 1) + [""])[1].split("&")
                    if "=" in kv
                )
                if len(parts) == 1:
                    task = worker.tasks.status_longpoll(
                        parts[0],
                        int(query.get("version", -1)),
                        float(query.get("maxWait", 0)),
                    )
                    if task is None:
                        self._reply(404)
                    else:
                        self._reply(200, _status_json(task))
                    return
                if len(parts) == 4 and parts[1] == "results":
                    task = worker.tasks.get(parts[0])
                    if task is None:
                        self._reply(404)
                        return
                    from ..runtime.hostprof import phase_span

                    with phase_span(
                        worker.tasks.recorder, "result_stream",
                        task_id=parts[0],
                    ) as stream_end:
                        pages, next_token, complete = task.buffer.get(
                            int(parts[2]), int(parts[3]),
                            float(query.get("maxWait", 1.0)),
                        )
                        meta = {
                            "sizes": [len(p) for p in pages],
                            "next_token": next_token,
                            "complete": complete,
                            "failed": task.state == TaskState.FAILED,
                            "error": task.error,
                        }
                        stream_end["bytes"] = sum(len(p) for p in pages)
                        self._reply(
                            200,
                            b"".join(pages),
                            headers=[("X-Page-Meta", json.dumps(meta))],
                        )
                    return
                self._reply(404)

            def do_DELETE(self):
                parts = self._task_parts()
                if parts is None or len(parts) != 1:
                    self._reply(404)
                    return
                if not verify(
                    worker.secret,
                    "DELETE",
                    self.path.split("?")[0],
                    b"",
                    self.headers.get(SIGNATURE_HEADER),
                ):
                    self._reply(401, b"invalid signature")
                    return
                task = worker.tasks.delete(parts[0])
                self._reply(200 if task else 404, _status_json(task) if task else b"")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def announcement_body(self) -> dict:
        """The /v1/announcement payload this worker reports: uri + version +
        device + live memory-pool state (ref: node/Announcer.java with the
        MemoryInfo rider). With $TRINO_TPU_CLUSTER_OBS on, the announcement
        additionally piggybacks a BOUNDED metric snapshot (federated
        metrics) and a clock rider — this node's monotonic timestamp plus
        the last observed announce round-trip — from which the coordinator
        estimates the clock offset (RTT midpoint) that skew-aligns this
        node's trace segments. Flag off: byte-identical to the pre-plane
        payload."""
        from .. import __version__
        from ..connectors.system import device_kind
        from ..runtime import clusterobs

        body = {
            "uri": f"http://{self.address}",
            "version": __version__,
            "device": device_kind(),
            "memory": self.tasks.memory_info(),
        }
        if clusterobs.server_enabled():
            from ..runtime import hostprof, kernelcost

            # host-path plane rider: refresh the runnable/blocked thread
            # gauges at announce time so the federated cluster tables carry
            # live values, not the last sampler tick (no-op when off — the
            # series never registers and the payload is byte-identical)
            if hostprof.server_enabled():
                hostprof.update_thread_gauges()
            series, _dropped = clusterobs.announcement_metrics()
            body["metrics"] = series
            # kernel cost plane rider: bounded latest-attributions snapshot
            # so system.runtime.kernel_costs on the coordinator shows every
            # node's rows (omitted while the ledger is empty)
            kc_rows = kernelcost.announcement_rows()
            if kc_rows:
                body["kernel_costs"] = kc_rows
            body["clock"] = {
                "mono_us": time.monotonic_ns() // 1000,
                # null until measured: the receiver ranks an unmeasured
                # sample below any real RTT instead of trusting a fake 0
                "rtt_us": (
                    None if self._last_announce_rtt_us is None
                    else int(self._last_announce_rtt_us)
                ),
            }
        return body

    def announce_to(self, coordinator_url: str, timeout: float = 5.0) -> bool:
        """PUT one announcement to ``coordinator_url`` and record the
        observed round-trip — the next announcement's clock rider carries
        it (the coordinator's RTT-midpoint offset estimate needs the
        sender-side RTT). Returns True on a 2xx response."""
        body = json.dumps(self.announcement_body()).encode()
        url = (
            f"{coordinator_url.rstrip('/')}/v1/announcement/"
            f"{self.tasks.node_id or self.address}"
        )
        req = urllib.request.Request(url, data=body, method="PUT")
        req.add_header("Content-Type", "application/json")
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                ok = 200 <= resp.status < 300
        except OSError:
            return False
        self._last_announce_rtt_us = (time.monotonic() - t0) * 1e6
        return ok

    def start(self) -> "WorkerServer":
        # named: the hostprof sampler and the deterministic-tid Perfetto
        # contract both group on thread names
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"worker-http-{self.port}",
        )
        self._thread.start()
        # host-path plane: $TRINO_TPU_HOSTPROF runs the sampling profiler +
        # GIL-contention probe for the process lifetime (no-op when off)
        from ..runtime.hostprof import start_server_profiling

        start_server_profiling()
        # the local-exchange shortcut recognizes pulls addressed to self
        self.tasks.self_urls = {
            f"http://{self.address}", f"http://localhost:{self._server.server_port}"
        }
        self.tasks.node_id = self.address
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self.tasks.executor.stop()


def _status_json(task: Task) -> bytes:
    return json.dumps(
        {
            "taskId": task.task_id,
            "state": task.state.value,
            "error": task.error,
            "version": task.version,
            # per-driver scheduling stats (PrioritizedSplitRunner analogue)
            "queuedSecs": task.queued_secs,
            "runSecs": task.run_secs,
        }
    ).encode()
