"""Warm-path cache plane: result, fragment, and plan caches keyed on
structural fingerprints.

Dashboard-style traffic is dominated by repeated and overlapping queries,
yet every arrival used to pay the full parse->analyze->plan->compile->execute
pipeline. "Query Processing on Tensor Computation Runtimes" (arXiv:2203.01877)
shows compilation/dispatch overhead dominating short tensor-runtime queries —
exactly the cost a warm path amortizes. Three tiers, coldest to warmest:

- **plan cache** — optimized LogicalPlans keyed on the statement TEXT plus
  the session state that feeds planning (catalog/schema/user + every
  explicitly-set session property). A hit skips parse, analysis, and
  optimization. Bypassed for statements whose text mentions a
  time/nondeterministic function (planning may constant-fold ``now()``),
  when ``history_based_stats`` is on (plans are *supposed* to change run to
  run), and inside explicit transactions.
- **fragment cache** — a shared scan->filter->(partial-)agg prefix,
  recognized across concurrent or successive queries by its SUBTREE
  fingerprint (``plancodec.fingerprint`` — the same notion of plan identity
  capstore and statstore key on), is materialized ONCE into the durable
  exchange store and later consumers read the committed attempt instead of
  re-executing. Single-flight: N concurrent identical prefixes execute once
  while N-1 block on the winner's commit; a winner that dies (or hits the
  ``cache_poison`` chaos site) commits nothing, and the blocked peers fall
  back to executing themselves — a poisoned entry can never be served.
- **result cache** — the full result set keyed on the structural plan
  fingerprint + per-table catalog versions. iceberg-lite snapshot ids give
  EXACT invalidation (a DML bump changes the key); static catalogs
  (tpch/tpcds) version on their scale; catalogs that cannot report a
  version fall back to conservative TTL (``result_cache_ttl``; 0 = bypass).
  Bounded by bytes with LRU eviction and persisted capstore-style (single
  JSON file, atomic rename) under ``$TRINO_TPU_RESULT_CACHE``.

Correctness gates shared by the result/fragment tiers:

- versions are resolved at ONE point before execution and re-resolved after
  it; an entry is stored only when both resolutions agree, so a run racing
  a DML can never record a row set assembled from a mixed snapshot.
- nondeterministic expressions (random/uuid/now/current_*) bypass.
- an open explicit transaction bypasses (its uncommitted writes are
  invisible to other sessions; neither tier may serve or record them).
- session properties ride the key; a property change can only miss, never
  serve a stale shape.

Observability: ``cache_lookup``/``cache_store``/``cache_invalidate`` flight
spans (hit/miss outcome on the E-event args),
``trino_tpu_cache_{hits,misses,evictions,invalidations}_total`` counters
labeled by tier, and the ``system.runtime.caches`` snapshot table.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import tempfile
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .. import knobs

ENV_RESULT = "TRINO_TPU_RESULT_CACHE"

# version token for connectors that must NEVER be cached (``cache_bypass``
# attr: system.runtime.* snapshots, information_schema) — volatile engine
# state served stale defeats the point of querying it
BYPASS = "__cache_bypass__"

# how long a single-flight loser waits on the winner before giving up and
# executing the prefix itself (a hung winner must never wedge consumers)
SINGLE_FLIGHT_WAIT_SECS = 120.0

# functions whose presence in a statement/plan must bypass the result and
# fragment tiers: per-row nondeterministic (random/uuid) or query-start
# constants the optimizer may fold into the plan at PLANNING time (now,
# current_*) — a cached fold would freeze time for every later consumer
_NONDET_TOKENS = (
    "random", "rand", "uuid", "shuffle", "now",
    "current_timestamp", "current_date", "current_time",
    "localtimestamp", "localtime",
)
_NONDET_CALLS = frozenset(_NONDET_TOKENS)

# word-boundary match, NOT substring: `i_brand` must not read as "rand" and
# `known` must not read as "now" — false positives here silently disable
# the plan tier for perfectly cacheable dashboard statements
_NONDET_RE = re.compile(
    r"\b(" + "|".join(re.escape(t) for t in _NONDET_TOKENS) + r")\b"
)


# --------------------------------------------------------------- observability


def _counter(name: str, tier: str):
    from .metrics import REGISTRY

    helps = {
        "trino_tpu_cache_hits_total": "warm-path cache hits by tier",
        "trino_tpu_cache_misses_total": "warm-path cache misses by tier",
        "trino_tpu_cache_evictions_total":
            "warm-path cache entries evicted (LRU/bytes/TTL) by tier",
        "trino_tpu_cache_invalidations_total":
            "warm-path cache entries invalidated (DML/DDL/snapshot bump) by tier",
    }
    return REGISTRY.counter(name, {"tier": tier}, help=helps[name])


@contextmanager
def _span(name: str, tier: str, **args):
    # a @contextmanager wrapper (not a returned raw span): the RECORDER.span
    # B/E pair is structural here, instead of depending on every caller
    # remembering `with` (lint rule unpaired-flight-span)
    from .observability import RECORDER

    with RECORDER.span(name, "cache", tier=tier, **args) as sp:
        yield sp


@dataclass
class TierStats:
    hits: int = 0
    misses: int = 0
    evictions: int = 0
    invalidations: int = 0


# ------------------------------------------------------------- key derivation


def session_props_key(session) -> Tuple:
    """The session state a cache key must carry: resolution defaults plus
    every EXPLICITLY-SET property (defaults are compiled into the engine —
    they cannot differ between two runs of one process)."""
    props = tuple(
        (k, str(v)) for k, v in sorted(session.properties.items())
        # cache knobs and observability toggles do not change result bytes;
        # keying on them would only split warm entries pointlessly
        if k not in (
            "result_cache", "result_cache_max_bytes", "result_cache_ttl",
            "fragment_cache", "plan_cache_size", "query_stats_sync",
            "flight_recorder", "statistics_feedback", "qerror_threshold",
            # device batching is bit-identical by contract — keying on its
            # knobs would only split warm entries pointlessly
            "device_batching", "batch_max_lanes", "batch_admit_window_ms",
            # vector-lane coalescing shares the contract; recall SAMPLING is
            # measurement, not result bytes (ann_mode/ann_nprobe DO change
            # bytes and stay keyed)
            "vector_query_batching", "ann_recall_sample_rate",
        )
    )
    return (session.catalog, session.schema, props)


def sql_mentions_nondeterminism(sql: str) -> bool:
    return _NONDET_RE.search(sql.lower()) is not None


def _collect_exprs(obj, found: List) -> None:
    """Collect IrExpr instances from arbitrary field values without
    crossing into PlanNodes (the subtree walk handles those)."""
    import dataclasses

    from ..planner.plan import PlanNode
    from ..sql.ir import IrExpr

    if isinstance(obj, IrExpr):
        found.append(obj)
        return
    if isinstance(obj, PlanNode):
        return
    if isinstance(obj, (tuple, list)):
        for x in obj:
            _collect_exprs(x, found)
        return
    if isinstance(obj, dict):
        for v in obj.values():
            _collect_exprs(v, found)
        return
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            _collect_exprs(getattr(obj, f.name, None), found)


def _walk_exprs(node, found: List) -> None:
    """Collect IrExpr instances from THIS plan node's own fields (children
    are reached by the caller's subtree walk, not here)."""
    import dataclasses

    for f in dataclasses.fields(node):
        _collect_exprs(getattr(node, f.name, None), found)


def _expr_cache_safe(expr) -> bool:
    """Stricter than ir.is_deterministic: current_timestamp et al. are
    deterministic for plan REWRITES (constant per query) but poison for a
    cross-query cache."""
    from ..sql import ir

    safe = True

    def walk(e):
        nonlocal safe
        if isinstance(e, ir.Call) and e.name in _NONDET_CALLS:
            safe = False
        import dataclasses

        if dataclasses.is_dataclass(e) and not isinstance(e, type):
            for f in dataclasses.fields(e):
                v = getattr(e, f.name, None)
                if isinstance(v, ir.IrExpr):
                    walk(v)
                elif isinstance(v, (tuple, list)):
                    for x in v:
                        if isinstance(x, ir.IrExpr):
                            walk(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ir.IrExpr):
                                    walk(y)

    walk(expr)
    return safe


@dataclass
class PlanProfile:
    """Everything the result tier needs to know about a plan, computed once
    (and carried alongside plan-cache entries so a plan-cache hit derives
    its result key without re-walking the tree)."""

    fingerprint: str
    # ((catalog, schema, table, pinned_version_or_None), ...)
    tables: Tuple[Tuple[str, str, str, Optional[str]], ...]
    cache_safe: bool  # False: nondeterministic expression somewhere


def profile_plan(plan) -> PlanProfile:
    from ..planner.plan import TableScanNode
    from .plancodec import fingerprint

    root = getattr(plan, "root", plan)
    tables: List[Tuple[str, str, str, Optional[str]]] = []
    safe = True

    def walk(node):
        nonlocal safe
        if isinstance(node, TableScanNode):
            h = node.table
            pinned = None
            ch = h.connector_handle
            if isinstance(ch, dict) and "snapshot_id" in ch:
                pinned = str(ch["snapshot_id"])
            tables.append(
                (h.catalog, h.schema_table.schema, h.schema_table.table, pinned)
            )
        exprs: List = []
        _walk_exprs(node, exprs)
        for e in exprs:
            if not _expr_cache_safe(e):
                safe = False
        for s in node.sources:
            walk(s)

    walk(root)
    return PlanProfile(
        fingerprint=fingerprint(root), tables=tuple(tables), cache_safe=safe
    )


def table_version(metadata, catalog: str, schema: str, table: str,
                  pinned: Optional[str]) -> Optional[str]:
    """One table's version token, resolved at CALL time (the late-binding
    idiom of Session.get): a time-travel pin is immutable; a connector that
    reports ``cache_table_version`` gives exact staleness; ``None`` means
    unversioned -> the conservative TTL-or-bypass path.

    CONTRACT for ``cache_table_version`` implementers: equal tokens must
    imply equal DATA — globally, across connector instances and process
    restarts, because entries persist. A bare local counter is NOT enough:
    qualify it with content identity (iceberg-lite: storage location +
    snapshot id; tpch/tpcds: resolved scale; memory: a per-instance nonce,
    which correctly forfeits cross-instance/cross-process reuse)."""
    if schema == "information_schema":
        # resolved against the BACKING catalog's connector below, which
        # knows nothing of metadata's information_schema overlay — and
        # "metadata is never stale" must survive the result tier, so these
        # scans bypass outright (out-of-band DDL in a shared warehouse
        # would otherwise serve a TTL-old table list)
        return BYPASS
    connector = metadata.connector_by_name(catalog)
    if connector is not None and getattr(connector, "cache_bypass", False):
        return BYPASS  # volatile engine state: never cached, pinned or not
    if pinned is not None:
        return f"pin:{pinned}"
    if connector is None:
        return None
    fn = getattr(connector, "cache_table_version", None)
    if fn is None:
        return None
    try:
        v = fn(schema, table)
    except Exception:  # noqa: BLE001 — version probe must not fail the query
        return None
    return None if v is None else str(v)


def resolve_versions(metadata, tables) -> Tuple[Optional[str], ...]:
    """Version tokens for every scanned table, resolved at one point in
    time. Callers snapshot BEFORE execution and re-resolve AFTER it; a
    result may only be recorded when the two agree (the mixed-snapshot
    guard: a cache entry recorded mid-DML would otherwise serve a row set
    that is half old snapshot, half new)."""
    return tuple(
        table_version(metadata, c, s, t, pinned) for c, s, t, pinned in tables
    )


def versions_provenance(tables, versions) -> str:
    """Human text for EXPLAIN / flight events: "snapshot 42" for a single
    versioned lake table, a compact list otherwise."""
    parts = []
    for (c, s, t, _pin), v in zip(tables, versions):
        if v is None:
            parts.append(f"{c}.{s}.{t}@ttl")
        elif v.isdigit():
            parts.append(f"{c}.{s}.{t}@snapshot {v}")
        else:
            parts.append(f"{c}.{s}.{t}@{v}")
    if len(parts) == 1:
        return parts[0].split("@", 1)[1]
    return ", ".join(parts)


def encode_result_rows(rows) -> Tuple[int, Any]:
    """-> (byte charge, codec-encoded rows or None). ONE encode serves both
    the LRU byte bound and persistence (the entry memoizes it) — the store
    path must not pay the O(rows) encode twice. Unencodable values fall
    back to a repr-length estimate and a memory-only entry."""
    from . import plancodec

    try:
        enc = plancodec.encode([tuple(r) for r in rows])
        nbytes = len(json.dumps(enc, separators=(",", ":")).encode()) + 64
        return nbytes, enc
    except Exception:  # noqa: BLE001 — unencodable values still need a bound
        return sum(len(str(r)) for r in rows) + 64, None


def _digest(*parts) -> str:
    return hashlib.sha256(
        json.dumps(parts, default=str, sort_keys=True).encode()
    ).hexdigest()


# ------------------------------------------------------------------ plan tier


class PlanCache:
    """Optimized plans by statement text + session state. LRU over
    ``plan_cache_size`` entries (0 = disabled)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Tuple[Any, PlanProfile]]" = OrderedDict()
        self.stats = TierStats()

    def _key(self, sql: str, session, registry: str) -> Tuple:
        # the registry nonce rides EVERY plan key: a plan embeds handles
        # and types resolved against one runner's catalogs, and two
        # runners may mount same-named catalogs over different schemas —
        # plans are process-local, so nothing is lost by scoping them
        return (sql, session.user, registry, session_props_key(session))

    def lookup(self, sql: str, session, registry: str = ""):
        """-> (plan, PlanProfile) or None. The caller gates on txn/size."""
        size = int(session.get("plan_cache_size") or 0)
        if size <= 0 or sql_mentions_nondeterminism(sql):
            return None
        if bool(session.get("history_based_stats")):
            return None  # replanning on fresh history is the point
        key = self._key(sql, session, registry)
        with _span("cache_lookup", "plan") as sp:
            with self._lock:
                hit = self._entries.get(key)
                if hit is not None:
                    self._entries.move_to_end(key)
                    self.stats.hits += 1
                else:
                    self.stats.misses += 1
            sp["outcome"] = "hit" if hit is not None else "miss"
        _counter(
            "trino_tpu_cache_hits_total" if hit is not None
            else "trino_tpu_cache_misses_total", "plan"
        ).inc()
        return hit

    def store(self, sql: str, session, plan, profile: PlanProfile,
              registry: str = "") -> None:
        size = int(session.get("plan_cache_size") or 0)
        if size <= 0 or sql_mentions_nondeterminism(sql):
            return
        if bool(session.get("history_based_stats")):
            return
        key = self._key(sql, session, registry)
        with _span("cache_store", "plan") as sp:
            with self._lock:
                self._entries[key] = (plan, profile)
                self._entries.move_to_end(key)
                evicted = 0
                while len(self._entries) > size:
                    self._entries.popitem(last=False)
                    evicted += 1
                self.stats.evictions += evicted
            sp["outcome"] = "stored"
        if evicted:
            _counter("trino_tpu_cache_evictions_total", "plan").inc(evicted)

    def invalidate_all(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.stats.invalidations += n
        if n:
            _counter("trino_tpu_cache_invalidations_total", "plan").inc(n)
        return n

    def snapshot(self) -> Tuple[int, int, TierStats]:
        with self._lock:
            return len(self._entries), 0, TierStats(**vars(self.stats))


# ---------------------------------------------------------------- result tier


@dataclass
class ResultEntry:
    names: List[str]
    types: Optional[List[Any]]
    rows: List[tuple]
    nbytes: int
    created: float
    tables: Tuple  # PlanProfile.tables
    versions: Tuple[Optional[str], ...]
    query_id: str = ""
    unversioned: bool = False
    # memoized persistence payload: entries are immutable once stored, so
    # the O(rows) plancodec encode happens at most once per entry, not once
    # per full-file rewrite ("skip" = known-unencodable, stays memory-only)
    encoded: Any = field(default=None, repr=False, compare=False)
    # rows pre-encoded by encode_result_rows at store time (shared with the
    # byte estimate); None = encode lazily on first persist
    rows_encoded: Any = field(default=None, repr=False, compare=False)

    @property
    def provenance(self) -> str:
        return versions_provenance(self.tables, self.versions)


class ResultCache:
    """Full result sets keyed on plan fingerprint + table versions +
    session state; byte-bounded LRU; optionally persisted (capstore-style
    single JSON file + atomic rename) under ``$TRINO_TPU_RESULT_CACHE``."""

    def __init__(self):
        self._lock = threading.Lock()
        # serializes file writes WITHOUT blocking lookups: persistence is
        # O(total cache bytes) and must never sit inside _lock on the warm
        # path (concurrent hit paths would queue behind the encode+write)
        self._io_lock = threading.Lock()
        self._entries: "OrderedDict[str, ResultEntry]" = OrderedDict()
        self.stats = TierStats()
        self._loaded_path: Optional[str] = None

    # ------------------------------------------------------------------ keys

    def key_for(self, profile: PlanProfile, versions, session,
                registry: str = "") -> Optional[str]:
        """Cache key, or None when this plan must bypass the tier: plans
        with nondeterministic expressions, any cache_bypass catalog
        (system.runtime.* — volatile engine state must never serve stale),
        plans over an unversioned table when the TTL fallback is disabled,
        and fingerprint failures. Unversioned plans additionally carry the
        registry nonce: their data identity is unknowable, so a TTL entry
        must stay scoped to the runner that recorded it."""
        if not profile.fingerprint or not profile.cache_safe:
            return None
        if BYPASS in versions:
            return None
        ttl = float(session.get("result_cache_ttl") or 0)
        unversioned = any(v is None for v in versions)
        if unversioned and ttl <= 0:
            return None
        return _digest(
            profile.fingerprint, list(versions), session_props_key(session),
            registry if unversioned else "",
        )

    # ----------------------------------------------------------- persistence

    @staticmethod
    def _store_path() -> Optional[str]:
        return knobs.env_path(ENV_RESULT)

    def _maybe_load(self) -> None:
        """Lazy one-shot merge of the persisted file (called under _lock)."""
        path = self._store_path()
        if path is None or path == self._loaded_path:
            return
        self._loaded_path = path
        from .objectstore import is_object_uri

        try:
            if is_object_uri(path):
                from ..fs import Location
                from .objectstore import backend_for_root

                base, _, name = path.rstrip("/").rpartition("/")
                fs, _ = backend_for_root(base)
                data = json.loads(fs.read(Location("object", name)).decode())
            else:
                with open(path, "r") as f:
                    data = json.load(f)
        except (OSError, ValueError):
            return
        for key, raw in (data or {}).items():
            if key in self._entries:
                continue
            entry = self._entry_from_raw(raw)
            if entry is not None:  # a corrupt entry is skipped, never fatal
                self._entries[key] = entry

    @staticmethod
    def _entry_from_raw(raw) -> Optional[ResultEntry]:
        """On-disk/shared-tier JSON payload -> ResultEntry (None on any
        decode failure — the warm path degrades to cold)."""
        from . import plancodec

        try:
            return ResultEntry(
                names=list(raw["names"]),
                types=plancodec.decode(raw["types"]),
                rows=[tuple(r) for r in plancodec.decode(raw["rows"])],
                nbytes=int(raw["nbytes"]),
                created=float(raw["created"]),
                tables=tuple(tuple(t) for t in raw["tables"]),
                versions=tuple(raw["versions"]),
                query_id=raw.get("query_id", ""),
                unversioned=bool(raw.get("unversioned")),
                encoded=raw,  # already on-disk form: never re-encode
            )
        except Exception:  # noqa: BLE001 — corrupt payloads degrade to cold
            return None

    @staticmethod
    def _ensure_encoded(e: ResultEntry):
        """Memoized persistence payload for ``e`` ("skip" = unencodable,
        stays memory-only) — shared by file persistence and the
        cross-process shared tier."""
        from . import plancodec

        if e.encoded is None:
            try:
                rows_enc = e.rows_encoded
                if rows_enc is None:
                    rows_enc = plancodec.encode([tuple(r) for r in e.rows])
                e.encoded = {
                    "names": e.names,
                    "types": plancodec.encode(e.types),
                    "rows": rows_enc,
                    "nbytes": e.nbytes,
                    "created": e.created,
                    "tables": [list(t) for t in e.tables],
                    "versions": list(e.versions),
                    "query_id": e.query_id,
                    "unversioned": e.unversioned,
                }
            except Exception:  # noqa: BLE001 — unencodable rows stay
                e.encoded = "skip"  # memory-only; don't retry per write
            e.rows_encoded = None  # folded into .encoded (or dead)
        return e.encoded

    def _snapshot_for_persist(self):
        """Under _lock: the (path, entries) pair a caller hands to
        :meth:`_write_file` AFTER releasing the lock, or None when
        persistence is off. Entries are immutable once stored, so sharing
        references outside the lock is safe."""
        path = self._store_path()
        if path is None:
            return None
        return path, list(self._entries.items())

    def _write_file(self, path: str, items) -> None:
        """Serialize + atomically replace the store file, OUTSIDE _lock
        (serialized against other writers by _io_lock only — a lost update
        between two racing writers costs a re-execute later, never
        corruption, the capstore contract). Entries whose rows the schema'd
        codec cannot encode stay memory-only."""
        data = {}
        for key, e in items:
            if self._ensure_encoded(e) != "skip":
                data[key] = e.encoded
        from .objectstore import is_object_uri

        if is_object_uri(path):
            # whole-object put is atomic per-key on the object backend —
            # the same lost-update-never-corruption contract as the local
            # rename, with no rename needed
            from ..fs import Location
            from .objectstore import backend_for_root

            base, _, name = path.rstrip("/").rpartition("/")
            with self._io_lock:
                try:
                    fs, _ = backend_for_root(base)
                    fs.write(
                        Location("object", name), json.dumps(data).encode()
                    )
                except OSError:
                    pass
            return
        d = os.path.dirname(os.path.abspath(path)) or "."
        with self._io_lock:
            try:
                os.makedirs(d, exist_ok=True)
                fd, tmp = tempfile.mkstemp(dir=d, prefix=".cachestore-")
                with os.fdopen(fd, "w") as f:
                    json.dump(data, f)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except (OSError, UnboundLocalError):
                    pass

    # ------------------------------------------------------------ operations

    def _shared_lookup(self, shared, key: str, ttl: float,
                       now: float) -> Optional[ResultEntry]:
        """Cross-process warm tier (runtime/ha.SharedCacheTier): serve
        another coordinator's published entry, or claim the single-flight
        LEASE for this key — exactly one coordinator in the fleet
        materializes it; a loser waits briefly for the winner's publish
        before falling back to self-execution. Runs OUTSIDE _lock (file
        I/O)."""
        from .ha import SHARED_FLIGHT_WAIT_SECS

        raw = shared.get(key)
        if raw is None and not shared.try_flight(key):
            # another coordinator is materializing this key right now
            raw = shared.wait_for(key, SHARED_FLIGHT_WAIT_SECS)
        if raw is None:
            return None  # we hold the flight (if claimed); store() releases
        e = self._entry_from_raw(raw)
        if e is None or (e.unversioned and ttl > 0 and now - e.created > ttl):
            return None
        with self._lock:
            if key not in self._entries:
                self._entries[key] = e
            self._entries.move_to_end(key)
            e = self._entries[key]
        shared.end_flight(key)  # value exists; a raced claim is moot
        return e

    def lookup(self, key: str, session) -> Optional[ResultEntry]:
        ttl = float(session.get("result_cache_ttl") or 0)
        now = time.time()
        with _span("cache_lookup", "result", key=key[:16]) as sp:
            with self._lock:
                self._maybe_load()
                e = self._entries.get(key)
                if e is not None and e.unversioned and ttl > 0 \
                        and now - e.created > ttl:
                    # TTL fallback expiry for unversioned catalogs
                    self._entries.pop(key)
                    self.stats.invalidations += 1
                    e = None
                    expired = True
                else:
                    expired = False
                if e is not None:
                    self._entries.move_to_end(key)
            if e is None:
                from .ha import shared_tier

                shared = shared_tier(session)
                if shared is not None:
                    e = self._shared_lookup(shared, key, ttl, now)
            with self._lock:
                if e is not None:
                    self.stats.hits += 1
                else:
                    self.stats.misses += 1
            sp["outcome"] = "hit" if e is not None else "miss"
        if expired:
            _counter("trino_tpu_cache_invalidations_total", "result").inc()
        _counter(
            "trino_tpu_cache_hits_total" if e is not None
            else "trino_tpu_cache_misses_total", "result"
        ).inc()
        return e

    def peek(self, key: Optional[str],
             session=None) -> Optional[ResultEntry]:
        """EXPLAIN provenance probe — no counters, no LRU touch. With a
        session, a local miss additionally probes the shared warm tier
        READ-ONLY: no single-flight claim, no local insert — the fleet's
        follower reads serve another coordinator's published entry without
        ever wedging the key for the owner (atomic puts make torn reads
        impossible)."""
        if key is None:
            return None
        with self._lock:
            self._maybe_load()
            e = self._entries.get(key)
        if e is not None or session is None:
            return e
        from .ha import shared_tier

        shared = shared_tier(session)
        if shared is None:
            return None
        raw = shared.get(key)
        if raw is None:
            return None
        return self._entry_from_raw(raw)

    def release_flight(self, key: str, session) -> None:
        """Free a shared-tier single-flight lease claimed at lookup time
        when the materialization will never publish (failed/canceled query,
        mixed-snapshot store skip, oversized entry) — without this the
        fleet's lookups for the key stall until the flight TTL lapses."""
        from .ha import shared_tier

        shared = shared_tier(session)
        if shared is not None:
            shared.end_flight(key)

    def store(self, key: str, entry: ResultEntry, session) -> None:
        max_bytes = int(session.get("result_cache_max_bytes") or 0)
        if max_bytes and entry.nbytes > max_bytes:
            # one oversized result must not wipe the whole tier — but a
            # flight claimed at lookup time must still be freed
            self.release_flight(key, session)
            return
        with _span("cache_store", "result", key=key[:16]) as sp:
            with self._lock:
                if self._store_path() is None:
                    # no persistence: the pre-encoded payload would only
                    # double the entry's real memory footprint
                    entry.rows_encoded = None
                self._entries[key] = entry
                self._entries.move_to_end(key)
                evicted = 0
                if max_bytes:
                    total = sum(e.nbytes for e in self._entries.values())
                    while total > max_bytes and len(self._entries) > 1:
                        _, old = self._entries.popitem(last=False)
                        total -= old.nbytes
                        evicted += 1
                self.stats.evictions += evicted
                snap = self._snapshot_for_persist()
            if snap is not None:
                self._write_file(*snap)
            from .ha import shared_tier

            shared = shared_tier(session)
            if shared is not None:
                # publish into the fleet's warm tier; this also releases a
                # single-flight lease claimed at lookup time. Unencodable
                # entries stay process-local (same contract as persistence).
                payload = self._ensure_encoded(entry)
                if payload != "skip":
                    shared.publish(key, payload)
                else:
                    shared.end_flight(key)
            sp["outcome"] = "stored"
        if evicted:
            _counter("trino_tpu_cache_evictions_total", "result").inc(evicted)

    def invalidate_table(self, catalog: str, schema: str, table: str) -> int:
        target = (catalog, schema, table)
        snap = None
        with self._lock:
            doomed = [
                k for k, e in self._entries.items()
                if any((c, s, t) == target for c, s, t, _ in e.tables)
            ]
            for k in doomed:
                self._entries.pop(k)
            self.stats.invalidations += len(doomed)
            if doomed:
                snap = self._snapshot_for_persist()
        if snap is not None:
            self._write_file(*snap)
        if doomed:
            _counter(
                "trino_tpu_cache_invalidations_total", "result"
            ).inc(len(doomed))
        return len(doomed)

    def invalidate_all(self) -> int:
        snap = None
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.stats.invalidations += n
            if n:
                snap = self._snapshot_for_persist()
        if snap is not None:
            self._write_file(*snap)
        if n:
            _counter("trino_tpu_cache_invalidations_total", "result").inc(n)
        return n

    def snapshot(self) -> Tuple[int, int, TierStats]:
        with self._lock:
            return (
                len(self._entries),
                sum(e.nbytes for e in self._entries.values()),
                TierStats(**vars(self.stats)),
            )


# -------------------------------------------------------------- fragment tier


@dataclass
class FragmentEntry:
    exchange: Any  # exchange_spi.Exchange holding the committed attempt
    symbols: Tuple[str, ...]
    sorted_by: Tuple[str, ...]
    nbytes: int
    created: float
    tables: Tuple
    versions: Tuple[Optional[str], ...]
    query_id: str = ""


class _Flight:
    """Single-flight ticket: losers block on ``done`` until the winner
    commits (or dies — then they execute themselves)."""

    def __init__(self):
        self.done = threading.Event()


class FragmentCache:
    """Common-subplan tier: scan->filter->project->(partial-)agg subtrees
    materialized once into the durable exchange store, consumed by every
    later (or concurrently blocked) query with the same subtree fingerprint
    and table versions."""

    #: plan node class names a cacheable prefix may contain — the shared
    #: dashboard shape; joins/windows stay out (their build sides make
    #: byte-bounding and reuse-detection far murkier)
    SAFE_NODES = frozenset(
        {"TableScanNode", "FilterNode", "ProjectNode", "AggregationNode"}
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, FragmentEntry]" = OrderedDict()
        self._flights: Dict[str, _Flight] = {}
        self.stats = TierStats()
        self._manager = None  # lazy ExchangeManager over a managed temp dir
        self._seq = 0

    # --------------------------------------------------------------- plumbing

    def _exchange_for(self, key: str):
        from .exchange_spi import ExchangeManager

        with self._lock:
            if self._manager is None:
                self._manager = ExchangeManager()
            self._seq += 1
            return self._manager.create_exchange(f"cache-{key[:24]}", self._seq)

    # ------------------------------------------------------------ cacheability

    def subtree_cacheable(self, node, executor) -> bool:
        """Memoized per executor: every node in the subtree is a safe shape,
        at least one table scan, every expression cache-safe."""
        memo = getattr(executor, "_frag_cacheable_memo", None)
        if memo is None:
            memo = executor._frag_cacheable_memo = {}
        cached = memo.get(id(node))
        if cached is not None:
            return cached
        has_scan = False
        ok = True

        def walk(n):
            nonlocal has_scan, ok
            if not ok:
                return
            if type(n).__name__ not in self.SAFE_NODES:
                ok = False
                return
            if type(n).__name__ == "TableScanNode":
                has_scan = True
            exprs: List = []
            _walk_exprs(n, exprs)
            for e in exprs:
                if not _expr_cache_safe(e):
                    ok = False
                    return
            for s in n.sources:
                walk(s)

        walk(node)
        memo[id(node)] = verdict = ok and has_scan
        return verdict

    def _key(self, node, binding) -> Optional[Tuple[str, Any, Tuple]]:
        """-> (key, profile-ish tables, versions) or None to bypass."""
        profile = profile_plan(node)
        if not profile.fingerprint or not profile.cache_safe:
            return None
        versions = resolve_versions(binding.metadata, profile.tables)
        if BYPASS in versions:
            return None
        ttl = float(binding.session.get("result_cache_ttl") or 0)
        unversioned = any(v is None for v in versions)
        if unversioned and ttl <= 0:
            return None
        key = _digest(
            profile.fingerprint, list(versions),
            session_props_key(binding.session), binding.scope,
            binding.registry if unversioned else "",
        )
        return key, profile.tables, versions

    # ------------------------------------------------------------- operations

    def fetch_or_execute(self, binding, executor, node):
        """The executor's entry: serve the committed materialization, or
        single-flight execute-and-commit, or fall through to plain
        execution when the subtree is not cacheable here."""
        keyed = self._key(node, binding)
        if keyed is None:
            return executor._eval_node(node)
        key, tables, versions = keyed
        # counting contract: each fetch records exactly ONE hit or ONE miss
        # — a single-flight loser that probes, waits, then gets served must
        # not read as both (the hit rate would collapse toward 50%)
        entry = self._lookup(key, binding.session)
        if entry is not None:
            rel = self._materialize(entry, executor, node)
            if rel is not None:
                self._count("hit")
                return rel
            self._drop_dead(key, entry)
            entry = None  # entry vanished under us: fall through and execute
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                # committed between lookup and flight registration
                self._entries.move_to_end(key)
                entry = e
                winner = False
                flight = None
            elif key not in self._flights:
                self._flights[key] = _Flight()
                winner = True
                flight = None
            else:
                flight = self._flights[key]
                winner = False
        if entry is not None:
            rel = self._materialize(entry, executor, node)
            if rel is not None:
                self._count("hit")
                return rel
            self._drop_dead(key, entry)
            self._count("miss")
            return executor._eval_node(node)
        if winner:
            self._count("miss")
            return self._execute_and_store(
                key, tables, versions, binding, executor, node
            )
        # loser: block on the winner's commit (single-flight dedup). A zero
        # wait (FTE attempts) skips straight to self-execution — a
        # speculative sibling exists to RACE a stalled attempt, never to
        # queue behind its flight.
        wait = binding.wait_secs
        if wait <= 0 or not flight.done.wait(wait):
            self._count("miss")
            return executor._eval_node(node)  # hung winner: self-serve
        entry = self._lookup(key, binding.session)
        if entry is not None:
            rel = self._materialize(entry, executor, node)
            if rel is not None:
                self._count("hit")
                return rel
            self._drop_dead(key, entry)
        # the winner failed or was poisoned (or the entry was invalidated
        # under us): execute ourselves rather than stampede a fresh flight
        self._count("miss")
        return executor._eval_node(node)

    def _drop_dead(self, key: str, entry) -> None:
        """An entry whose committed blob can no longer be read (a /tmp
        sweeper took the exchange dir) must leave the map — otherwise the
        key would sit at 100% miss forever: the dead entry blocks any new
        flight from ever re-materializing it."""
        dropped = False
        with self._lock:
            if self._entries.get(key) is entry:
                self._remove_locked(key)
                self.stats.invalidations += 1
                dropped = True
        if dropped:
            _counter("trino_tpu_cache_invalidations_total", "fragment").inc()

    def _count(self, kind: str) -> None:
        """The ONE hit-or-miss tick for a fetch_or_execute call (the probe
        itself never counts — see the counting contract above)."""
        with self._lock:
            if kind == "hit":
                self.stats.hits += 1
            else:
                self.stats.misses += 1
        _counter(
            "trino_tpu_cache_hits_total" if kind == "hit"
            else "trino_tpu_cache_misses_total", "fragment"
        ).inc()

    def _lookup(self, key: str, session):
        """Probe (spanned, TTL-expiring, LRU-touching) — does NOT tick the
        hit/miss stats; the caller does, once per logical fetch."""
        ttl = float(session.get("result_cache_ttl") or 0)
        now = time.time()
        expired = False
        with _span("cache_lookup", "fragment", key=key[:16]) as sp:
            with self._lock:
                e = self._entries.get(key)
                if e is not None and any(v is None for v in e.versions) \
                        and ttl > 0 and now - e.created > ttl:
                    self._remove_locked(key)
                    self.stats.invalidations += 1
                    e = None
                    expired = True
                if e is not None:
                    self._entries.move_to_end(key)
            sp["outcome"] = "hit" if e is not None else "miss"
        if expired:
            _counter("trino_tpu_cache_invalidations_total", "fragment").inc()
        return e

    def _execute_and_store(self, key, tables, versions, binding, executor, node):
        from .failure import chaos_fire

        flight_entry_stored = False
        try:
            rel = executor._eval_node(node)
            with _span("cache_store", "fragment", key=key[:16]) as sp:
                try:
                    blob = self._serialize(rel)
                except Exception:  # noqa: BLE001 — unserializable page shapes
                    sp["outcome"] = "skipped"  # (nested/lambda cols) skip
                    return rel
                max_entry = int(
                    binding.session.get("result_cache_max_bytes") or 0
                )
                if max_entry and len(blob) > max_entry:
                    # one oversized prefix must not wipe the whole tier
                    # (same guard as ResultCache.store)
                    sp["outcome"] = "skipped"
                    return rel
                exch = self._exchange_for(key)
                sink = exch.sink(partition=0, attempt=0)
                sink.add(blob)
                poison = chaos_fire("cache_poison", text=key)
                if poison is not None:
                    # simulated crash mid-materialization: abort the attempt
                    # — nothing commits, no entry appears, losers self-serve
                    sink.abort()
                    shutil.rmtree(
                        os.path.dirname(exch.root), ignore_errors=True
                    )
                    sp["outcome"] = "poisoned"
                    return rel
                sink.commit()
                # re-resolve versions AFTER materialization: a DML that
                # landed mid-execution must not record a mixed snapshot —
                # and the now-unreferenced committed blob must not orphan
                # a temp dir until process exit
                v_after = resolve_versions(binding.metadata, tables)
                if v_after != versions:
                    shutil.rmtree(
                        os.path.dirname(exch.root), ignore_errors=True
                    )
                    sp["outcome"] = "skipped"
                    return rel
                entry = FragmentEntry(
                    exchange=exch,
                    symbols=tuple(rel.symbols),
                    sorted_by=tuple(rel.sorted_by),
                    nbytes=len(blob),
                    created=time.time(),
                    tables=tables,
                    versions=versions,
                    query_id=binding.query_id,
                )
                max_bytes = int(
                    binding.session.get("result_cache_max_bytes") or 0
                )
                with self._lock:
                    self._entries[key] = entry
                    self._entries.move_to_end(key)
                    evicted = 0
                    if max_bytes:
                        total = sum(
                            e.nbytes for e in self._entries.values()
                        )
                        while total > max_bytes and len(self._entries) > 1:
                            old_key = next(iter(self._entries))
                            total -= self._entries[old_key].nbytes
                            self._remove_locked(old_key)
                            evicted += 1
                    self.stats.evictions += evicted
                flight_entry_stored = True
                sp["outcome"] = "stored"
                if evicted:
                    _counter(
                        "trino_tpu_cache_evictions_total", "fragment"
                    ).inc(evicted)
            return rel
        finally:
            with self._lock:
                flight = self._flights.pop(key, None)
            if flight is not None:
                flight.done.set()
            if not flight_entry_stored:
                pass  # losers observe no entry and execute themselves

    @staticmethod
    def _serialize(rel) -> bytes:
        import numpy as np

        from .serde import serialize_page

        _ = np  # serde pulls arrays to host internally
        return serialize_page(rel.page)

    def _materialize(self, entry: FragmentEntry, executor, node):
        """-> Relation, or None when the committed materialization vanished
        between lookup and read (invalidate_table / LRU eviction rmtree'd
        the exchange dir) — the caller falls back to executing the subtree,
        never failing the query on a cache race."""
        from .executor import Relation
        from .serde import deserialize_page

        try:
            blobs = entry.exchange.source(0)
            page = deserialize_page(blobs[0])
        except Exception:  # noqa: BLE001 — a cache race must not fail a query
            return None
        rel = Relation(
            page=page, symbols=entry.symbols, sorted_by=entry.sorted_by
        )
        prov = getattr(executor, "cache_provenance", None)
        if prov is not None:
            who = entry.query_id or "an earlier query"
            prov[id(node)] = f"fragment reused from query {who}"
        executor.fragment_cache_hits = (
            getattr(executor, "fragment_cache_hits", 0) + 1
        )
        return rel

    # ----------------------------------------------------------- maintenance

    def _remove_locked(self, key: str) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            try:
                # the parent is the per-key cache-<fp> dir: dropping it
                # reclaims every attempt generation for this key
                shutil.rmtree(
                    os.path.dirname(e.exchange.root), ignore_errors=True
                )
            except Exception:  # noqa: BLE001
                pass

    def peek(self, node, binding) -> Optional[FragmentEntry]:
        keyed = self._key(node, binding)
        if keyed is None:
            return None
        with self._lock:
            return self._entries.get(keyed[0])

    def invalidate_table(self, catalog: str, schema: str, table: str) -> int:
        target = (catalog, schema, table)
        with self._lock:
            doomed = [
                k for k, e in self._entries.items()
                if any((c, s, t) == target for c, s, t, _ in e.tables)
            ]
            for k in doomed:
                self._remove_locked(k)
            self.stats.invalidations += len(doomed)
        if doomed:
            _counter(
                "trino_tpu_cache_invalidations_total", "fragment"
            ).inc(len(doomed))
        return len(doomed)

    def invalidate_all(self) -> int:
        with self._lock:
            n = len(self._entries)
            for k in list(self._entries):
                self._remove_locked(k)
            self.stats.invalidations += n
        if n:
            _counter("trino_tpu_cache_invalidations_total", "fragment").inc(n)
        return n

    def snapshot(self) -> Tuple[int, int, TierStats]:
        with self._lock:
            return (
                len(self._entries),
                sum(e.nbytes for e in self._entries.values()),
                TierStats(**vars(self.stats)),
            )


@dataclass
class FragmentBinding:
    """What a PlanExecutor needs to consult the fragment tier: resolution
    context plus a scope disambiguating partitioned fragment executions
    (partition p of n reads DIFFERENT splits than partition p' — their
    materializations must never alias)."""

    cache: FragmentCache
    metadata: Any
    session: Any
    scope: str = ""
    query_id: str = ""
    # CatalogManager.cache_nonce of the owning runner — scopes entries over
    # UNVERSIONED tables to the registry that recorded them (same-named
    # catalogs in two runners may hold different data)
    registry: str = ""
    # how long a single-flight loser blocks on the winner. 0 = never block
    # (FTE task attempts: a speculative sibling must race a stalled peer,
    # not wait behind its flight)
    wait_secs: float = SINGLE_FLIGHT_WAIT_SECS

    def fetch_or_execute(self, executor, node):
        if not self.cache.subtree_cacheable(node, executor):
            return executor._eval_node(node)
        return self.cache.fetch_or_execute(self, executor, node)


# -------------------------------------------------------------------- facade


class CacheStore:
    """The process-wide three-tier warm path. One instance (``CACHES``)
    serves every runner in the process — sharing across concurrent queries
    is the point."""

    def __init__(self):
        self.plan = PlanCache()
        self.result = ResultCache()
        self.fragment = FragmentCache()

    # ------------------------------------------------------------ enablement

    @staticmethod
    def result_enabled(session) -> bool:
        """Session property wins when explicitly set; otherwise a deployed
        ``$TRINO_TPU_RESULT_CACHE`` path opts the process in (the same
        env-as-deployment-default idiom as TRINO_TPU_QUERY_MAX_MEMORY)."""
        if "result_cache" in session.properties:
            return bool(session.properties["result_cache"])
        if knobs.env_path(ENV_RESULT):
            return True
        return bool(session.DEFAULTS.get("result_cache"))

    @staticmethod
    def fragment_enabled(session) -> bool:
        return bool(session.get("fragment_cache"))

    @staticmethod
    def plan_enabled(session) -> bool:
        return int(session.get("plan_cache_size") or 0) > 0

    # ---------------------------------------------------------- invalidation

    def invalidate_table(self, catalog: str, schema: str, table: str) -> int:
        """Exact invalidation on a DML commit (an iceberg snapshot bump, a
        memory-table append): every result/fragment entry whose key touches
        the table is dropped. Version-keyed entries would already miss —
        this reclaims their bytes and makes the bump visible in the
        invalidation counters."""
        with _span("cache_invalidate", "all",
                   table=f"{catalog}.{schema}.{table}") as sp:
            n = self.result.invalidate_table(catalog, schema, table)
            n += self.fragment.invalidate_table(catalog, schema, table)
            sp["outcome"] = "invalidated"
            sp["entries"] = n
        return n

    def on_ddl(self) -> None:
        """Schema-changing statements (CREATE/DROP table/view/function/
        catalog) clear everything: a cached plan may embed dropped handles
        or stale view/routine bodies, and name reuse could alias entries."""
        with _span("cache_invalidate", "all", reason="ddl") as sp:
            n = self.plan.invalidate_all()
            n += self.result.invalidate_all()
            n += self.fragment.invalidate_all()
            sp["outcome"] = "invalidated"
            sp["entries"] = n

    def clear(self) -> None:
        """Test hook: drop all entries WITHOUT counting invalidations."""
        self.plan._entries.clear()
        with self.result._lock:
            self.result._entries.clear()
            self.result._loaded_path = None
        with self.fragment._lock:
            for k in list(self.fragment._entries):
                self.fragment._remove_locked(k)
            self.fragment._flights.clear()
        self.plan.stats = TierStats()
        self.result.stats = TierStats()
        self.fragment.stats = TierStats()

    # -------------------------------------------------------------- snapshot

    def stats_rows(self) -> List[tuple]:
        """system.runtime.caches rows: (tier, entries, bytes, hits, misses,
        evictions, invalidations)."""
        rows = []
        for tier, cache in (
            ("plan", self.plan), ("result", self.result),
            ("fragment", self.fragment),
        ):
            entries, nbytes, st = cache.snapshot()
            rows.append(
                (tier, entries, nbytes, st.hits, st.misses, st.evictions,
                 st.invalidations)
            )
        return rows


CACHES = CacheStore()
