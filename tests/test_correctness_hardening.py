"""Adversarial correctness tests for round-2 hardening fixes.

Targets the silent-wrong-answer risks called out in round-1 review:
- NOT IN / IN three-valued NULL semantics (ref: SemiJoinNode nullable output)
- multi-column join key packing overflow (ref: PagesHash equality confirmation)
- repartition hashing of NULL / float keys (host and device tiers must agree)
- all_to_all bucket overflow must be detected, never silently dropped
- dictionary divergence across exchange producer chunks
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trino_tpu.spi.page import Column, Dictionary, Page
from trino_tpu.spi.types import BIGINT, DOUBLE, VarcharType


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.0005)


class TestInNullSemantics:
    def test_not_in_with_null_in_subquery_is_empty(self, runner):
        # 1 NOT IN (2, NULL) is NULL, not TRUE -> every row drops
        res = runner.execute(
            "SELECT x FROM (VALUES (1), (5)) t(x) "
            "WHERE x NOT IN (SELECT y FROM (VALUES (2), (NULL)) s(y))"
        )
        assert res.rows == []

    def test_not_in_null_probe_dropped(self, runner):
        # NULL NOT IN (1, 2) is NULL -> dropped; 5 NOT IN (1, 2) is TRUE
        res = runner.execute(
            "SELECT x FROM (VALUES (NULL), (5)) t(x) "
            "WHERE x NOT IN (SELECT y FROM (VALUES (1), (2)) s(y))"
        )
        assert res.rows == [(5,)]

    def test_in_unmatched_with_null_filter_dropped(self, runner):
        # 5 IN (1, NULL) is NULL -> dropped; 1 IN (1, NULL) is TRUE
        res = runner.execute(
            "SELECT x FROM (VALUES (1), (5)) t(x) "
            "WHERE x IN (SELECT y FROM (VALUES (1), (NULL)) s(y))"
        )
        assert res.rows == [(1,)]

    def test_in_empty_subquery_is_false_even_for_null(self, runner):
        res = runner.execute(
            "SELECT x FROM (VALUES (NULL), (5)) t(x) "
            "WHERE x NOT IN (SELECT y FROM (VALUES (1)) s(y) WHERE y > 10)"
        )
        assert res.rows == [(None,), (5,)]

    def test_in_matched_stays_true_with_null_filter(self, runner):
        res = runner.execute(
            "SELECT count(*) FROM (VALUES (1), (2), (3)) t(x) "
            "WHERE x IN (SELECT y FROM (VALUES (1), (2), (NULL)) s(y))"
        )
        assert res.rows == [(2,)]


class TestKeyPackOverflow:
    def test_three_wide_range_join_keys(self, runner):
        # span product of three +/-1e18 ranges wraps 2^63 under range packing;
        # dense-rank packing must keep distinct keys distinct
        big = 10**18
        rows = [(1, big, -big), (2, -big, big), (3, big, big)]
        values_t = ", ".join(f"({a}, {b}, {c})" for a, b, c in rows)
        # build side: same keys, one extra non-matching row
        values_s = ", ".join(
            f"({a}, {b}, {c}, {a * 10})" for a, b, c in rows
        ) + f", (1, {big}, {big - 1}, 999)"
        res = runner.execute(
            f"SELECT t.a, s.v FROM (VALUES {values_t}) t(a, b, c) "
            f"JOIN (VALUES {values_s}) s(a, b, c, v) "
            "ON t.a = s.a AND t.b = s.b AND t.c = s.c ORDER BY t.a"
        )
        assert res.rows == [(1, 10), (2, 20), (3, 30)]

    def test_pack_key_pair_distinctness_adversarial(self):
        from trino_tpu.ops import kernels as K

        rng = np.random.default_rng(0)
        n = 256
        # keys spanning the whole int64 range across 3 columns
        cols = [
            rng.integers(-(2**62), 2**62, size=n, dtype=np.int64) for _ in range(3)
        ]
        # plant two rows equal in the first two columns, differing in the third
        cols[0][10] = cols[0][20]
        cols[1][10] = cols[1][20]
        cols[2][10] = cols[2][20] + 1
        valid = np.ones(n, dtype=bool)
        pairs = [(jnp.asarray(c), jnp.asarray(valid)) for c in cols]
        p, pv, b, bv = K.pack_key_pair(pairs, pairs)
        p = np.asarray(p)
        tuples = list(zip(*[c.tolist() for c in cols]))
        for i in range(n):
            for j in range(i + 1, n):
                if tuples[i] == tuples[j]:
                    assert p[i] == p[j]
                else:
                    assert p[i] != p[j], f"rows {i},{j} alias: {tuples[i]} {tuples[j]}"
        np.testing.assert_array_equal(np.asarray(b), p)


class TestRepartitionNullFloatKeys:
    def test_host_device_partition_agreement(self):
        from trino_tpu.parallel.exchange import partition_ids
        from trino_tpu.spi.host_pages import hash_partition_host as _hash_partition_host

        rng = np.random.default_rng(1)
        n = 512
        fdata = rng.normal(size=n) * 1e6
        fdata[::7] = -0.0  # sign-sensitive encodings would diverge here
        fvalid = rng.random(n) > 0.2
        idata = rng.integers(-(2**40), 2**40, size=n)
        ivalid = rng.random(n) > 0.2
        host = _hash_partition_host([(fdata, fvalid), (idata, ivalid)], 8)
        dev = partition_ids(
            [
                (jnp.asarray(fdata), jnp.asarray(fvalid)),
                (jnp.asarray(idata), jnp.asarray(ivalid)),
            ],
            8,
        )
        np.testing.assert_array_equal(host, np.asarray(dev))

    def test_null_keys_single_group_distributed(self):
        from trino_tpu.parallel.runner import DistributedQueryRunner

        runner = DistributedQueryRunner.tpch(scale=0.0005, n_workers=4)
        res = runner.execute(
            "SELECT x, count(*) FROM (VALUES (1), (NULL), (NULL), (2), (NULL)) t(x) "
            "GROUP BY x ORDER BY x"
        )
        # exactly ONE null group (split NULL groups would emit duplicates)
        assert sorted(res.rows, key=lambda r: (r[0] is None, r[0])) == [
            (1, 1),
            (2, 1),
            (None, 3),
        ]


class TestAllToAllOverflow:
    def test_skewed_overflow_detected(self):
        from functools import partial

        from jax.sharding import PartitionSpec as P

        from trino_tpu.parallel import exchange, make_mesh

        if len(jax.devices()) < 8:
            pytest.skip("need 8 devices")
        mesh = make_mesh(8)
        n = 8 * 64
        keys = np.zeros(n, dtype=np.int64)  # 100% skew: all rows -> one shard
        vals = np.arange(n)
        page = Page.from_arrays([BIGINT, BIGINT], [keys, vals], capacity=n)
        from trino_tpu.parallel.distributed import shard_pages

        sharded = shard_pages([page], mesh)

        @partial(
            jax.shard_map, mesh=mesh, in_specs=(P("workers"),), out_specs=(P("workers"), P())
        )
        def shuffle(p):
            return exchange.repartition_by_keys(p, [0], 8, "workers", bucket_cap=8)

        out, overflow = shuffle(sharded)
        # per shard: 64 rows to one destination, bucket_cap 8 -> 56 dropped x 8
        assert int(overflow) == 8 * (64 - 8)
        active = np.asarray(out.active)
        assert int(active.sum()) == 8 * 8

    def test_safe_cap_no_overflow(self):
        from functools import partial

        from jax.sharding import PartitionSpec as P

        from trino_tpu.parallel import exchange, make_mesh
        from trino_tpu.parallel.distributed import shard_pages

        if len(jax.devices()) < 8:
            pytest.skip("need 8 devices")
        mesh = make_mesh(8)
        n = 8 * 64
        keys = np.zeros(n, dtype=np.int64)
        vals = np.arange(n)
        page = Page.from_arrays([BIGINT, BIGINT], [keys, vals], capacity=n)
        sharded = shard_pages([page], mesh)

        @partial(
            jax.shard_map, mesh=mesh, in_specs=(P("workers"),), out_specs=(P("workers"), P())
        )
        def shuffle(p):
            return exchange.repartition_by_keys(p, [0], 8, "workers")

        out, overflow = shuffle(sharded)
        assert int(overflow) == 0
        active = np.asarray(out.active)
        got = sorted(np.asarray(out.columns[1].data)[active].tolist())
        assert got == list(range(n))


class TestDictKeyRepartition:
    def test_same_string_same_partition_across_dictionaries(self):
        # producers carrying different dictionaries must route the same string
        # to the same consumer partition (codes are dictionary-local)
        d1 = Dictionary.from_strings(["apple", "cherry"])
        d2 = Dictionary.from_strings(["banana", "cherry"])
        k1 = d1.value_keys()[np.array([1])]  # "cherry" under d1
        k2 = d2.value_keys()[np.array([1])]  # "cherry" under d2
        assert k1[0] == k2[0]
        assert d1.value_keys()[0] != d2.value_keys()[0]  # apple != banana

    def test_fingerprint_equal_content(self):
        d1 = Dictionary.from_strings(["x", "y"])
        d2 = Dictionary.from_strings(["y", "x"])
        assert d1.fingerprint() == d2.fingerprint()
        assert d1.fingerprint() != Dictionary.from_strings(["x"]).fingerprint()


class TestExchangeDictionaryMerge:
    def test_divergent_chunk_dictionaries_reencode(self):
        from trino_tpu.parallel.runner import _page_from_host_chunks

        d1 = Dictionary.from_strings(["apple", "cherry"])
        d2 = Dictionary.from_strings(["banana", "cherry"])
        vt = VarcharType()
        # chunk 1: ["cherry", "apple"] under d1; chunk 2: ["banana"] under d2
        c1 = [(vt, np.array([1, 0]), np.array([True, True]), d1)]
        c2 = [(vt, np.array([0]), np.array([True]), d2)]
        page = _page_from_host_chunks([c1, c2])
        col = page.columns[0]
        decoded = col.dictionary.decode(np.asarray(col.data))
        assert list(decoded[:3]) == ["cherry", "apple", "banana"]
