"""Lambda expressions and higher-order array/map functions.

Model: the reference's TestArrayTransformFunction / TestArrayFilterFunction /
TestArrayAnyMatchFunction / TestZipWithFunction / TestArrayReduceFunction /
TestMapTransformValuesFunction / TestMapFilterFunction
(operator/scalar/, sql/tree/LambdaExpression.java). The TPU lowering compiles
each lambda body as one vectorized program over the flattened [cap*W] lane
grid (ops/compiler._compile_higher_order).
"""

import pytest


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.0005)


def one(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


class TestTransform:
    def test_basic(self, runner):
        assert one(runner, "SELECT transform(ARRAY[1,2,3], x -> x * 2)") == ([2, 4, 6],)

    def test_null_elements_flow_through(self, runner):
        assert one(runner, "SELECT transform(ARRAY[1,NULL,3], x -> x + 1)") == (
            [2, None, 4],
        )

    def test_outer_column_capture(self, runner):
        assert one(
            runner,
            "SELECT transform(arr, x -> x + y) FROM (SELECT ARRAY[1,2] AS arr, 10 AS y) t",
        ) == ([11, 12],)

    def test_string_result(self, runner):
        assert one(
            runner,
            "SELECT transform(ARRAY[1,2], x -> CASE WHEN x > 1 THEN 'big' ELSE 'small' END)",
        ) == (["small", "big"],)

    def test_string_input(self, runner):
        assert one(runner, "SELECT transform(ARRAY['a','bb'], x -> length(x))") == ([1, 2],)
        assert one(runner, "SELECT transform(ARRAY['a','b'], x -> upper(x))") == (["A", "B"],)

    def test_null_array(self, runner):
        assert one(
            runner,
            "SELECT transform(CAST(NULL AS array(bigint)), x -> x + 1)",
        ) == (None,)


class TestFilter:
    def test_basic(self, runner):
        assert one(runner, "SELECT filter(ARRAY[5,-6,NULL,7], x -> x > 0)") == ([5, 7],)

    def test_per_row(self, runner):
        got = runner.execute(
            "SELECT filter(arr, x -> x > y) FROM "
            "(SELECT ARRAY[1,5,9] AS arr, 4 AS y UNION ALL SELECT ARRAY[2,3], 1) t "
            "ORDER BY y"
        ).rows
        assert got == [([2, 3],), ([5, 9],)]

    def test_empty_result(self, runner):
        assert one(runner, "SELECT filter(ARRAY[1,2], x -> x > 99)") == ([],)


class TestMatch:
    def test_any_all_none(self, runner):
        assert one(
            runner,
            "SELECT any_match(ARRAY[1,2], x -> x > 1), "
            "all_match(ARRAY[1,2], x -> x > 0), "
            "none_match(ARRAY[1,2], x -> x > 5)",
        ) == (True, True, True)

    def test_three_valued_null(self, runner):
        # no true, a null verdict -> NULL (ArrayAnyMatchFunction semantics)
        assert one(runner, "SELECT any_match(ARRAY[1,NULL], x -> x > 5)") == (None,)
        assert one(runner, "SELECT any_match(ARRAY[9,NULL], x -> x > 5)") == (True,)
        assert one(runner, "SELECT all_match(ARRAY[9,NULL], x -> x > 5)") == (None,)
        assert one(runner, "SELECT all_match(ARRAY[1,NULL], x -> x > 5)") == (False,)


class TestZipWith:
    def test_equal_lengths(self, runner):
        assert one(
            runner, "SELECT zip_with(ARRAY[1,2], ARRAY[10,20], (a,b) -> a + b)"
        ) == ([11, 22],)

    def test_shorter_extends_with_null(self, runner):
        assert one(
            runner, "SELECT zip_with(ARRAY[1,2], ARRAY[10,20,30], (a,b) -> a + b)"
        ) == ([11, 22, None],)


class TestReduce:
    def test_sum(self, runner):
        assert one(
            runner, "SELECT reduce(ARRAY[5,20,50], 0, (s,x) -> s + x, s -> s)"
        ) == (75,)

    def test_final_transform(self, runner):
        assert one(
            runner,
            "SELECT reduce(ARRAY[5,20,50], CAST(0 AS double), (s,x) -> s + x, s -> s / 3.0)",
        ) == (25.0,)

    def test_per_row(self, runner):
        got = runner.execute(
            "SELECT reduce(arr, 0, (s,x) -> s + x * x, s -> s) FROM "
            "(SELECT ARRAY[1,2,3] AS arr UNION ALL SELECT ARRAY[4]) t"
        ).rows
        assert sorted(got) == [(14,), (16,)]

    def test_three_arg_defaults_to_identity_output(self, runner):
        assert one(
            runner, "SELECT reduce(ARRAY[1,2,3], 100, (s,x) -> s + x)"
        ) == (106,)


class TestMapHigherOrder:
    def test_transform_values(self, runner):
        assert one(
            runner,
            "SELECT transform_values(MAP(ARRAY['k1','k2'], ARRAY[1,2]), (k,v) -> v * 10)",
        ) == ({"k1": 10, "k2": 20},)

    def test_map_filter(self, runner):
        assert one(
            runner,
            "SELECT map_filter(MAP(ARRAY['k1','k2'], ARRAY[1,2]), (k,v) -> v > 1)",
        ) == ({"k2": 2},)


class TestStringCase:
    """Regression: string-typed CASE must merge branch dictionaries."""

    def test_constant_branches(self, runner):
        assert one(runner, "SELECT CASE WHEN 1 > 0 THEN 'big' ELSE 'small' END") == ("big",)

    def test_no_default_yields_null(self, runner):
        got = runner.execute(
            "SELECT CASE WHEN x > 1 THEN 'big' WHEN x > 0 THEN 'mid' END FROM "
            "(SELECT 1 AS x UNION ALL SELECT 2 UNION ALL SELECT 0) t ORDER BY x"
        ).rows
        assert got == [(None,), ("mid",), ("big",)]

    def test_mixing_column_and_constant(self, runner):
        got = runner.execute(
            "SELECT DISTINCT CASE WHEN l_quantity > 25 THEN 'hi' ELSE l_shipmode END "
            "FROM lineitem WHERE l_shipmode = 'AIR' ORDER BY 1"
        ).rows
        assert got == [("AIR",), ("hi",)]


class TestLambdaErrors:
    def test_lambda_outside_higher_order(self, runner):
        with pytest.raises(Exception):
            runner.execute("SELECT x -> x + 1")

    def test_wrong_arity(self, runner):
        with pytest.raises(Exception, match="parameters"):
            runner.execute("SELECT transform(ARRAY[1], (x, y) -> x)")

    def test_filter_requires_boolean(self, runner):
        with pytest.raises(Exception, match="boolean"):
            runner.execute("SELECT filter(ARRAY[1], x -> x + 1)")


class TestLambdaParamNames:
    def test_non_reserved_keyword_params(self, runner):
        # 'day'/'position' are keywords usable as identifiers; multi-param
        # lambda lookahead must accept them like the single-param path
        assert one(runner, "SELECT transform(ARRAY[1], day -> day + 1)") == ([2],)
        assert one(
            runner,
            "SELECT zip_with(ARRAY[1], ARRAY[2], (x, day) -> x + day)",
        ) == ([3],)
