"""Serving fabric plane: coordinator HA, dispatch handoff, shared warm
tiers, and worker elasticity.

Reference blueprint: Trino's fault-tolerant execution lets TASKS outlive
their workers (EventDrivenFaultTolerantQueryScheduler over the durable
exchange); this module extends the same disaggregation one level up so a
QUERY outlives its COORDINATOR — "Near Data Processing in Taurus Database"
(PAPERS.md) motivates the move: push the shared state down to the storage
substrate and any compute node can pick the work back up. Every durable
plane needed already exists (query-history JSONL, statstore, capstore,
result cache, the FTE durable exchange); what this module adds is the
coordination layer over them:

- :class:`LeaderLease` — a leader election primitive on the ``fs.py``
  object-store substrate: an atomic-rename lease file carrying a FENCED
  EPOCH, TTL renewal, and standby takeover through an O_EXCL epoch-claim
  object (``write_if_absent``), so two standbys racing an expired lease
  can never both win the same epoch. A paused old leader discovers the
  advanced epoch on its next renew/fence check and steps down — at no
  observable point do two holders believe the same epoch.
- :class:`DispatchJournal` — the per-query dispatch handoff record,
  persisted NEXT TO the durable exchange (``<exchange>/<query_id>/
  journal.jsonl``): begin (sql + the planning-relevant session props),
  stage_start / winner (keyed like the FTE scheduler's attempt ring) /
  stage_done / finished. On failover :func:`resume_fte_query` replays it:
  completed stages are skipped outright, committed exchange attempts of
  the in-flight stage are RE-ADOPTED, and scheduling resumes from the
  last completed stage instead of failing the query. Readers skip a
  truncated trailing record (kill-mid-append) and count it instead of
  crashing (``trino_tpu_recovery_torn_records_total``).
- :class:`SharedCacheTier` — the cross-process warm tier over the fs.py
  object-store layer (the round-11 follow-up): a fleet of coordinators
  shares one warm result cache, and single-flight is extended with a
  leased flight object so two coordinators never double-materialize the
  same entry (``write_if_absent`` again; an abandoned flight expires by
  TTL so a crashed materializer never wedges the key).
- :class:`ScaleController` — worker elasticity driven by the signals
  ``system.metrics`` already exports (resource-group queue depth,
  memory-pool pressure, blacklist churn): scale-up admits a late-joining
  worker into RUNNING FTE queries (``EventDrivenFteScheduler.
  admit_worker``), scale-down drains gracefully (no new dispatch, live
  attempts finish) before retiring the node.

Everything is gated off by default (``ha_plane`` / ``shared_cache_tier``
/ ``elastic_workers`` session properties): with the gates off the
execution path is byte-identical to the pre-HA engine.

Chaos sites: ``coordinator_crash`` (the stage loop raises
:class:`CoordinatorCrashError` mid-query, leaving journal + committed
exchange attempts on disk exactly as a dead process would) and
``lease_expire`` (the leader's renewal forfeits, modelling a GC pause /
partition long enough for the lease to lapse).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .. import knobs
from ..fs import Location
from .failure import chaos_fire
from .objectstore import (
    ObjectJournal,
    backend_for_root,
    is_object_uri,
    object_journal_queries,
)
from .observability import RECORDER

# one shared HELP string per counter: the metric HELP lint requires every
# call site of a name to agree
TORN_RECORDS_HELP = (
    "truncated trailing JSONL records skipped during restart recovery"
)
FAILOVERS_HELP = "coordinator failovers (standby lease takeovers)"
RENEWALS_HELP = "leader lease renewals"
SHARED_HITS_HELP = "shared warm-tier cache hits served across processes"
SHARED_MISSES_HELP = "shared warm-tier cache lookups that found no entry"
SHARED_PUBLISH_HELP = "entries published into the shared warm tier"
ADMIT_HELP = "workers admitted by the elastic scale controller"
DRAIN_HELP = "workers drained by the elastic scale controller"

# how long a shared-tier single-flight loser waits for the winner's publish
# before falling back to executing itself (mirrors the fragment cache's
# hung-winner fallback)
SHARED_FLIGHT_WAIT_SECS = 10.0
# flight-lease TTL: a crashed materializer's abandoned flight frees itself
SHARED_FLIGHT_TTL_SECS = 30.0


def _counter(name: str, help_: str):
    from .metrics import REGISTRY

    return REGISTRY.counter(name, help=help_)


def note_torn_record(n: int = 1) -> None:
    """Count a torn trailing JSONL record skipped during recovery — the
    QueryHistoryStore, statstore, and dispatch-journal readers all report
    through this one hook instead of crashing on a kill-mid-append tail."""
    if n > 0:
        _counter("trino_tpu_recovery_torn_records_total", TORN_RECORDS_HELP).inc(n)


# --------------------------------------------------------------------------- #
# leader lease
# --------------------------------------------------------------------------- #


class LeadershipLost(RuntimeError):
    """The caller believed it was the leader but the lease says otherwise."""


class FencedWriteError(RuntimeError):
    """A write carrying a superseded epoch was rejected — the fencing rule:
    once a standby takes over at epoch N+1, every epoch-N writer is dead to
    the substrate even if its process is still running."""

    def __init__(self, held: int, current: int):
        super().__init__(
            f"fenced write rejected: holder epoch {held} superseded by "
            f"epoch {current}"
        )
        self.held = held
        self.current = current


class LeaderLease:
    """Fenced leader lease on the fs.py substrate.

    State is one lease object (``lease.json``: holder / epoch /
    expires_at) plus conditional-put epoch-claim objects
    (``claims/epoch-N``). Takeover protocol: read the lease; if expired,
    CAS-create the claim for ``epoch+1`` — ``write_if_absent``
    (If-None-Match on the object backend, tmp+link locally) guarantees
    exactly one winner per epoch — then publish the new lease via an
    etag-fenced ``write_if_match`` CAS, so a paused OLD leader's late
    renewal can never clobber a newer epoch's lease even on a rename-free
    substrate. Renewal re-reads and FAILS if the stored epoch moved on
    (the paused-leader case). ``check_fenced`` is the write-side fencing
    hook journal appends go through.

    The root may be an ``object://`` URI: the lease then runs on the
    retrying object backend with identical exactly-one-winner semantics.
    """

    LEASE = Location("local", "lease.json")

    def __init__(self, root: str, node_id: str, ttl: float = 10.0):
        self.fs, self.root = backend_for_root(root)
        self.node_id = node_id
        self.ttl = float(ttl)
        self.epoch = 0  # the epoch THIS holder owns; 0 = not leader
        self._lease_etag: Optional[str] = None  # etag of the last lease read

    # ------------------------------------------------------------------ state

    def _read(self) -> Optional[dict]:
        try:
            raw, etag = self.fs.read_with_etag(self.LEASE)
            data = json.loads(raw.decode())
        except (OSError, ValueError):
            self._lease_etag = None
            return None
        self._lease_etag = etag
        return data if isinstance(data, dict) else None

    def _publish(self, now: float) -> bool:
        """Etag-fenced lease publication. Returns False when the lease
        advanced past our epoch mid-publish (we are superseded); retries
        through lower-epoch interference (an old leader's concurrent late
        renewal) because our epoch is the newer claim."""
        body = json.dumps({
            "holder": self.node_id,
            "epoch": self.epoch,
            "expires_at": now + self.ttl,
        }).encode()
        for _ in range(16):
            if self._lease_etag is None:
                if self.fs.write_if_absent(self.LEASE, body):
                    self._lease_etag = hashlib.md5(body).hexdigest()
                    return True
            else:
                new = self.fs.write_if_match(self.LEASE, body, self._lease_etag)
                if new is not None:
                    self._lease_etag = new
                    return True
            cur = self._read()  # refreshes the etag for the next round
            if cur is not None and int(cur.get("epoch", 0)) > self.epoch:
                return False  # superseded while publishing: step down
        return False

    def current_epoch(self) -> int:
        cur = self._read()
        return int(cur.get("epoch", 0)) if cur else 0

    def holder(self) -> Optional[str]:
        cur = self._read()
        if cur is None or time.time() >= float(cur.get("expires_at", 0)):
            return None
        return cur.get("holder")

    # -------------------------------------------------------------- lifecycle

    def acquire(self) -> bool:
        """Become leader if the lease is free/expired (or already ours).
        Returns True when this node holds the lease afterwards."""
        with RECORDER.span("leader_lease", "ha", node=self.node_id) as end:
            now = time.time()
            cur = self._read()
            if (
                cur is not None
                and cur.get("holder") == self.node_id
                and int(cur.get("epoch", 0)) == self.epoch
                and self.epoch > 0
            ):
                end["outcome"] = "renewed"
                return self.renew()
            if cur is not None and now < float(cur.get("expires_at", 0)):
                end["outcome"] = "held"
                end["holder"] = cur.get("holder")
                return False
            next_epoch = (int(cur.get("epoch", 0)) if cur else 0) + 1
            claim = Location("local", f"claims/epoch-{next_epoch}")
            if not self.fs.write_if_absent(
                claim,
                json.dumps({"holder": self.node_id, "ts": now}).encode(),
            ):
                # another standby won this epoch's CAS first
                end["outcome"] = "lost_claim"
                return False
            self.epoch = next_epoch
            if not self._publish(now):
                # a newer epoch published mid-claim (shouldn't happen: the
                # claim CAS serializes epochs) — don't pretend to lead
                self.epoch = 0
                end["outcome"] = "lost_publish"
                return False
            end["outcome"] = "acquired"
            end["epoch"] = next_epoch
            if next_epoch > 1:
                _counter("trino_tpu_failovers_total", FAILOVERS_HELP).inc()
            return True

    def renew(self) -> bool:
        """Extend the lease; False (and step down) when leadership is gone.
        The ``lease_expire`` chaos site models a GC pause: the renewal is
        skipped and the holder forfeits locally, so the on-disk lease
        lapses and a standby takes over — is_leader() goes False HERE
        first, which is what makes "never two leaders" hold."""
        if self.epoch <= 0:
            return False
        act = chaos_fire("lease_expire", text=self.node_id)
        if act is not None:
            self.epoch = 0
            return False
        cur = self._read()
        if (
            cur is None
            or cur.get("holder") != self.node_id
            or int(cur.get("epoch", 0)) != self.epoch
        ):
            self.epoch = 0  # superseded while we slept
            return False
        if not self._publish(time.time()):
            self.epoch = 0  # CAS lost to a newer epoch: step down
            return False
        _counter("trino_tpu_lease_renewals_total", RENEWALS_HELP).inc()
        return True

    def release(self) -> None:
        """Voluntary step-down: expire the lease immediately (same epoch) so
        a standby can claim the next one without waiting out the TTL."""
        if self.epoch <= 0:
            return
        cur = self._read()
        if cur is not None and cur.get("holder") == self.node_id \
                and int(cur.get("epoch", 0)) == self.epoch \
                and self._lease_etag is not None:
            cur["expires_at"] = 0.0
            # best-effort CAS: losing means someone already superseded us,
            # which achieves the same end (we no longer hold the lease)
            self.fs.write_if_match(
                self.LEASE, json.dumps(cur).encode(), self._lease_etag
            )
        self.epoch = 0

    def is_leader(self) -> bool:
        if self.epoch <= 0:
            return False
        cur = self._read()
        return bool(
            cur is not None
            and cur.get("holder") == self.node_id
            and int(cur.get("epoch", 0)) == self.epoch
            and time.time() < float(cur.get("expires_at", 0))
        )

    def check_fenced(self, epoch: int) -> None:
        """Write-side fencing: raise when ``epoch`` has been superseded.
        (The check-then-write window is inherent to a filesystem substrate;
        it is safe here because journal records are ADVISORY over the
        idempotent first-commit-wins exchange — a late stale record can
        never change which attempt a resumed consumer reads.)"""
        current = self.current_epoch()
        if current > epoch:
            raise FencedWriteError(epoch, current)

    def snapshot(self) -> dict:
        cur = self._read() or {}
        return {
            "node": self.node_id,
            "leader": self.is_leader(),
            "epoch": self.epoch,
            "currentEpoch": int(cur.get("epoch", 0) or 0),
            "holder": cur.get("holder"),
            "expiresAt": float(cur.get("expires_at", 0) or 0),
        }


# --------------------------------------------------------------------------- #
# dispatch journal + resume
# --------------------------------------------------------------------------- #


class CoordinatorCrashError(RuntimeError):
    """The ``coordinator_crash`` chaos site fired: the query aborts exactly
    the way a dead coordinator process would leave it — dispatch journal
    and committed exchange attempts intact on the shared substrate, no
    cleanup — so a standby can adopt and resume it."""

    def __init__(self, query_id: str, journal_path: Optional[str] = None):
        super().__init__(f"injected coordinator crash during {query_id}")
        self.query_id = query_id
        self.journal_path = journal_path


# session properties whose values shape the distributed plan: the resuming
# coordinator must re-plan with the SAME values or fragment/partition
# topology would not line up with the committed exchange attempts
PLAN_SESSION_PROPS = (
    "retry_policy", "join_distribution_type", "join_reordering_strategy",
    "hash_partition_count", "target_partition_rows",
    "push_partial_aggregation", "broadcast_join_threshold_rows",
    "distributed_sort", "enable_dynamic_filtering", "task_retry_attempts",
    "fte_exchange_dir", "ha_plane",
)


def repair_jsonl_tail(path: str) -> bool:
    """Terminate a torn trailing line (kill-mid-append) with a newline so
    the NEXT append starts a fresh record instead of concatenating onto the
    unterminated fragment — without this, one torn tail silently corrupts
    the first post-recovery record too. Returns True when a repair was
    needed."""
    try:
        with open(path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            if f.tell() == 0:
                return False
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return False
            f.write(b"\n")
            return True
    except OSError:
        return False


def read_jsonl_tolerant(path: str) -> Tuple[List[dict], int]:
    """All decodable JSON records in ``path`` plus how many torn/corrupt
    lines were skipped (counted via :func:`note_torn_record`). A file
    truncated mid-append (coordinator killed between write and newline)
    yields every complete record instead of crashing the reader."""
    records: List[dict] = []
    torn = 0
    try:
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn += 1
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
                else:
                    torn += 1
    except OSError:
        return [], 0
    note_torn_record(torn)
    return records, torn


class DispatchJournal:
    """Per-query dispatch handoff journal, JSONL next to the durable
    exchange. Appends are epoch-fenced when a lease is attached: a paused
    old leader's late write raises :class:`FencedWriteError` instead of
    landing. Record kinds::

        {"kind": "begin", "query_id", "sql", "session", "n_workers"}
        {"kind": "stage_start", "fid", "n_parts"}
        {"kind": "winner", "fid", "p", "attempt"}   # the attempt ring key
        {"kind": "stage_done", "fid"}
        {"kind": "finished"}
    """

    FILENAME = "journal.jsonl"

    def __init__(self, path: str, lease: Optional[LeaderLease] = None,
                 epoch: Optional[int] = None):
        self.path = path
        self.lease = lease
        self.epoch = int(
            epoch if epoch is not None
            else (lease.epoch if lease is not None else 0)
        )
        # dedicated I/O serializer (lint blocking-call-under-lock: appends
        # are its only job, no shared state hides behind it)
        self._io_lock = threading.Lock()
        self._tail_checked = False
        # object substrate: appends become sequenced record objects with a
        # CAS'd tail pointer (no JSONL append primitive on a rename-free
        # store); the record schema and fencing are identical
        self._obj = ObjectJournal(path) if is_object_uri(path) else None

    @staticmethod
    def path_for(exchange_base: str, query_id: str) -> str:
        if is_object_uri(exchange_base):
            # no .jsonl on the object substrate: the journal is a PREFIX
            # of sequenced record objects (<prefix>/00000001.json + TAIL)
            return f"{str(exchange_base).rstrip('/')}/{query_id}/journal"
        return os.path.join(exchange_base, query_id, DispatchJournal.FILENAME)

    # ---------------------------------------------------------------- writes

    def append(self, record: dict) -> None:
        if self.lease is not None:
            self.lease.check_fenced(self.epoch)
        record = dict(record)
        record["epoch"] = self.epoch
        record["ts"] = time.time()
        if self._obj is not None:
            with self._io_lock:
                self._obj.append(record)
            return
        line = json.dumps(record)
        with self._io_lock:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            if not self._tail_checked:
                # a takeover leader appends to the DEAD leader's journal:
                # terminate any torn trailing line first
                self._tail_checked = True
                repair_jsonl_tail(self.path)
            with open(self.path, "a") as f:
                f.write(line + "\n")

    def begin(self, query_id: str, sql: str, session, n_workers: int,
              exchange_dir: str = "") -> None:
        props = {}
        for name in PLAN_SESSION_PROPS:
            try:
                props[name] = session.get(name)
            except KeyError:
                continue
        if exchange_dir:
            # the RESOLVED substrate location, not the session default — a
            # temp-managed exchange dir must still be findable on takeover
            props["fte_exchange_dir"] = exchange_dir
        self.append({
            "kind": "begin", "query_id": query_id, "sql": sql,
            "session": props, "n_workers": int(n_workers),
        })

    def stage_start(self, fid: int, n_parts: int) -> None:
        self.append({"kind": "stage_start", "fid": fid, "n_parts": n_parts})

    def winner(self, fid: int, p: int, attempt: int) -> None:
        self.append({"kind": "winner", "fid": fid, "p": p, "attempt": attempt})

    def stage_done(self, fid: int) -> None:
        self.append({"kind": "stage_done", "fid": fid})

    def finished(self) -> None:
        self.append({"kind": "finished"})

    # ----------------------------------------------------------------- reads

    @staticmethod
    def read(path: str) -> Tuple[List[dict], int]:
        if is_object_uri(path):
            records, torn = ObjectJournal(path).read()
            note_torn_record(torn)
            return records, torn
        return read_jsonl_tolerant(path)


class ResumeState:
    """Parsed dispatch journal: what a takeover leader adopts."""

    def __init__(self):
        self.query_id: str = ""
        self.sql: str = ""
        self.session_props: Dict[str, Any] = {}
        self.n_workers: int = 0
        self.stages_done: Set[int] = set()
        self.winners: Dict[Tuple[int, int], int] = {}
        self.finished: bool = False

    @staticmethod
    def from_records(records: List[dict]) -> "ResumeState":
        st = ResumeState()
        for rec in records:
            kind = rec.get("kind")
            if kind == "begin":
                st.query_id = str(rec.get("query_id", ""))
                st.sql = str(rec.get("sql", ""))
                props = rec.get("session")
                if isinstance(props, dict):
                    st.session_props = props
                st.n_workers = int(rec.get("n_workers", 0) or 0)
            elif kind == "stage_done":
                st.stages_done.add(int(rec["fid"]))
            elif kind == "winner":
                st.winners[(int(rec["fid"]), int(rec["p"]))] = int(
                    rec["attempt"]
                )
            elif kind == "finished":
                st.finished = True
        return st

    @staticmethod
    def load(path: str) -> "ResumeState":
        records, _ = DispatchJournal.read(path)
        return ResumeState.from_records(records)


def orphaned_journals(exchange_base: str) -> List[str]:
    """Journal paths of queries that began but never journaled
    ``finished`` — the takeover leader's adoption worklist."""
    out: List[str] = []
    if is_object_uri(exchange_base):
        for _qid, journal_uri in object_journal_queries(exchange_base):
            st = ResumeState.load(journal_uri)
            if st.sql and not st.finished:
                out.append(journal_uri)
        return out
    try:
        names = sorted(os.listdir(exchange_base))
    except OSError:
        return out
    for name in names:
        path = os.path.join(exchange_base, name, DispatchJournal.FILENAME)
        if not os.path.isfile(path):
            continue
        st = ResumeState.load(path)
        if st.sql and not st.finished:
            out.append(path)
    return out


def resume_fte_query(runner, journal_path: str):
    """Failover dispatch handoff: replay ``journal_path`` on ``runner``
    (the NEW leader's runner, mounted over the same catalogs and exchange
    substrate), re-adopt committed exchange attempts, and resume from the
    last completed stage. Returns the finished QueryResult — bit-identical
    to the uninterrupted run because every adopted stage's committed
    attempts are exactly what an uninterrupted consumer would have read."""
    from .clusterobs import session_enabled as _obs_enabled

    # profile breakdown contract: everything from handoff entry to the
    # stage loop counts as the resumed query's planning phase
    obs_t0 = time.monotonic() if _obs_enabled(runner.session) else None
    state = ResumeState.load(journal_path)
    if not state.sql:
        raise ValueError(f"journal {journal_path!r} has no begin record")
    if state.finished:
        raise ValueError(f"query {state.query_id} already finished")
    with RECORDER.span(
        "dispatch_replay", "ha",
        query_id=state.query_id, stages_done=len(state.stages_done),
        winners=len(state.winners),
    ) as end:
        for name, value in state.session_props.items():
            try:
                runner.session.set(name, value)
            except (KeyError, ValueError):
                continue
        if state.n_workers:
            runner.n_workers = state.n_workers
        # per-query observability normally reset by _execute_once — the
        # handoff enters the FTE tier directly
        runner.last_partition_counts = {}
        runner.last_tier, runner.last_tier_reason = "fte", None
        subplan = runner.plan_distributed(state.sql)
        if obs_t0 is not None:
            runner._obs_planning_secs = time.monotonic() - obs_t0
        result = runner._execute_fte(subplan, sql=state.sql, resume=state)
        end["outcome"] = "resumed"
        end["adopted"] = getattr(runner, "last_fte_adopted", 0)
    return result


# --------------------------------------------------------------------------- #
# shared warm tier (cross-process result cache over the object store)
# --------------------------------------------------------------------------- #


class SharedCacheTier:
    """Cross-process warm tier on the fs.py object-store layer: one value
    object per cache key plus a leased single-flight object so a FLEET of
    coordinators materializes each entry exactly once.

    Layout under the tier root::

        result/<key>.json     the published entry (atomic put)
        flight/<key>.json     the materialization lease (O_EXCL create,
                              expires after SHARED_FLIGHT_TTL_SECS)
    """

    def __init__(self, root: str):
        # an object:// root mounts the retrying object backend; the value
        # objects (atomic whole-object puts) and flight leases
        # (write_if_absent) already speak pure contract, so the tier runs
        # unchanged on either substrate
        self.fs, self.root = backend_for_root(root)
        self._held: Set[str] = set()
        self._lock = threading.Lock()

    @staticmethod
    def _value_loc(key: str) -> Location:
        return Location("local", f"result/{key}.json")

    @staticmethod
    def _flight_loc(key: str) -> Location:
        return Location("local", f"flight/{key}.json")

    # ----------------------------------------------------------------- value

    def get(self, key: str) -> Optional[dict]:
        try:
            raw = json.loads(self.fs.read(self._value_loc(key)).decode())
        except (OSError, ValueError):
            _counter(
                "trino_tpu_shared_cache_misses_total", SHARED_MISSES_HELP
            ).inc()
            return None
        _counter("trino_tpu_shared_cache_hits_total", SHARED_HITS_HELP).inc()
        return raw if isinstance(raw, dict) else None

    def publish(self, key: str, payload: dict) -> None:
        """Atomic put of the materialized entry; releases a held flight."""
        try:
            self.fs.write(self._value_loc(key), json.dumps(payload).encode())
            _counter(
                "trino_tpu_shared_cache_publishes_total", SHARED_PUBLISH_HELP
            ).inc()
        finally:
            self.end_flight(key)

    def invalidate(self, key: str) -> None:
        """Remove a published entry. Atomic unlink: a concurrent reader
        sees either the old FULL object or a miss — never a torn value
        (the cross-process race test in tests/test_ha_plane.py hammers
        this against concurrent lookup/publish)."""
        self.fs.delete(self._value_loc(key))

    # ---------------------------------------------------------------- flight

    def try_flight(self, key: str) -> bool:
        """Claim the materialization flight for ``key``. True = this caller
        is the winner and must publish (or let the lease expire). An
        expired flight (crashed materializer) is reclaimed."""
        loc = self._flight_loc(key)
        body = json.dumps(
            {"pid": os.getpid(), "expires_at": time.time()
             + SHARED_FLIGHT_TTL_SECS}
        ).encode()
        if self.fs.write_if_absent(loc, body):
            with self._lock:
                self._held.add(key)
            return True
        try:
            cur = json.loads(self.fs.read(loc).decode())
            expired = time.time() >= float(cur.get("expires_at", 0))
        except (OSError, ValueError):
            expired = True  # vanished/corrupt between exists and read
        if not expired:
            return False
        # stale flight: reclaim (delete + CAS again; two reclaimers race the
        # CAS, exactly one wins)
        self.fs.delete(loc)
        if self.fs.write_if_absent(loc, body):
            with self._lock:
                self._held.add(key)
            return True
        return False

    def end_flight(self, key: str) -> None:
        with self._lock:
            held = key in self._held
            self._held.discard(key)
        if held:
            self.fs.delete(self._flight_loc(key))

    def flight_active(self, key: str) -> bool:
        try:
            cur = json.loads(self.fs.read(self._flight_loc(key)).decode())
        except (OSError, ValueError):
            return False
        return time.time() < float(cur.get("expires_at", 0))

    def wait_for(self, key: str, timeout: float) -> Optional[dict]:
        """Single-flight loser path: poll for the winner's publish; give up
        at ``timeout`` or as soon as the flight lease vanished without a
        value (winner died — the caller self-executes)."""
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            try:
                raw = json.loads(
                    self.fs.read(self._value_loc(key)).decode()
                )
                if isinstance(raw, dict):
                    _counter(
                        "trino_tpu_shared_cache_hits_total", SHARED_HITS_HELP
                    ).inc()
                    return raw
            except (OSError, ValueError):
                pass
            if time.monotonic() >= deadline or not self.flight_active(key):
                return None
            time.sleep(0.01)


_SHARED_TIERS: Dict[str, SharedCacheTier] = {}
_SHARED_TIERS_LOCK = threading.Lock()


def shared_tier(session) -> Optional[SharedCacheTier]:
    """The process's shared warm tier, or None when the gate is off. Opt-in
    is BOTH the ``shared_cache_tier`` session property and a configured
    ``$TRINO_TPU_SHARED_CACHE_DIR`` (matching the result tier's deployment
    opt-in contract) — with either missing the lookup path is untouched."""
    try:
        if not bool(session.get("shared_cache_tier")):
            return None
    except KeyError:
        return None
    root = knobs.env_path("TRINO_TPU_SHARED_CACHE_DIR")
    if not root:
        return None
    with _SHARED_TIERS_LOCK:
        tier = _SHARED_TIERS.get(root)
        if tier is None:
            tier = SharedCacheTier(root)
            _SHARED_TIERS[root] = tier
        return tier


# --------------------------------------------------------------------------- #
# elastic workers
# --------------------------------------------------------------------------- #


class ScaleController:
    """Worker elasticity driven by the signals the metrics plane already
    exports: resource-group queue depth, memory-pool pressure, and
    blacklist churn. ``spawn()`` must return the new worker's url;
    ``retire(url)`` stops it after a graceful drain. Scale-up admits the
    worker into every RUNNING FTE query's scheduler (late join); scale-down
    drains first — no new dispatch, in-flight attempts finish — before
    retiring."""

    def __init__(
        self,
        node_manager=None,
        resource_groups=None,
        memory_pool=None,
        spawn: Optional[Callable[[], str]] = None,
        retire: Optional[Callable[[str], None]] = None,
        min_workers: int = 1,
        max_workers: int = 8,
        queue_high: int = 4,
        pressure_high: float = 0.85,
    ):
        self.node_manager = node_manager
        self.resource_groups = resource_groups
        self.memory_pool = memory_pool
        self.spawn = spawn
        self.retire = retire
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.queue_high = int(queue_high)
        self.pressure_high = float(pressure_high)
        self.workers: List[str] = []  # urls this controller manages
        self._last_blacklisted: Optional[float] = None
        self.decisions: List[dict] = []

    # --------------------------------------------------------------- signals

    def signals(self) -> dict:
        queue_depth = 0
        if self.resource_groups is not None:
            try:
                queue_depth = sum(
                    int(row.get("queued", 0))
                    for row in self.resource_groups.flat_info()
                )
            except Exception:  # noqa: BLE001 — signals are advisory
                queue_depth = 0
        pressure = 0.0
        if self.memory_pool is not None:
            try:
                snap = self.memory_pool.snapshot()
                if snap.get("maxBytes"):
                    pressure = (
                        float(snap.get("reservedBytes", 0))
                        / float(snap["maxBytes"])
                    )
            except Exception:  # noqa: BLE001 — signals are advisory
                pressure = 0.0
        from .metrics import REGISTRY

        blacklisted = REGISTRY.counter(
            "trino_tpu_workers_blacklisted_total",
            help="workers blacklisted by the FTE scheduler",
        ).value
        churn = 0.0
        if self._last_blacklisted is not None:
            churn = max(0.0, blacklisted - self._last_blacklisted)
        self._last_blacklisted = blacklisted
        return {
            "queue_depth": queue_depth,
            "memory_pressure": pressure,
            "blacklist_churn": churn,
            "workers": len(self.workers),
        }

    # --------------------------------------------------------------- actions

    def scale_up(self) -> Optional[str]:
        if self.spawn is None or len(self.workers) >= self.max_workers:
            return None
        url = (self.spawn() or "").rstrip("/")
        if not url:
            return None
        self.workers.append(url)
        self.admit_into_running(url)
        _counter("trino_tpu_worker_admissions_total", ADMIT_HELP).inc()
        return url

    @staticmethod
    def admit_into_running(url: str) -> int:
        """Late-join: hand the new worker to every live FTE scheduler that
        dispatches remotely (a local in-process scheduler must never grow a
        remote worker mid-query). Returns how many queries admitted it."""
        from .fte_scheduler import active_schedulers

        n = 0
        for sched in active_schedulers():
            if sched.workers and sched.admit_worker(url):
                n += 1
        return n

    def drain(self, url: str, node_id: Optional[str] = None,
              wait_secs: float = 10.0) -> bool:
        """Graceful scale-down: mark the node DRAINING (no new dispatch),
        tell every live scheduler to steer away, wait for in-flight
        attempts to finish, then retire. Returns True when the worker
        drained clean inside ``wait_secs`` (it is retired either way —
        remaining attempts fail over through the normal FTE retry path)."""
        url = url.rstrip("/")
        from .fte_scheduler import active_schedulers

        with RECORDER.span("worker_drain", "ha", worker=url) as end:
            if self.node_manager is not None and node_id is not None:
                try:
                    self.node_manager.drain(node_id)
                except Exception:  # noqa: BLE001 — registry drain is advisory
                    pass
            for sched in active_schedulers():
                sched.drain_worker(url)
            deadline = time.monotonic() + max(0.0, wait_secs)
            clean = False
            while True:
                busy = sum(
                    sched.worker_inflight(url)
                    for sched in active_schedulers()
                )
                if busy == 0:
                    clean = True
                    break
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.02)
            end["outcome"] = "drained" if clean else "timeout"
        if url in self.workers:
            self.workers.remove(url)
        if self.retire is not None:
            self.retire(url)
        _counter("trino_tpu_worker_drains_total", DRAIN_HELP).inc()
        return clean

    def tick(self) -> dict:
        """One control-loop step: read the signals, maybe act."""
        sig = self.signals()
        decision = {"action": "hold", **sig}
        overloaded = (
            sig["queue_depth"] >= self.queue_high
            or sig["memory_pressure"] >= self.pressure_high
            or sig["blacklist_churn"] > 0
        )
        if overloaded and len(self.workers) < self.max_workers:
            url = self.scale_up()
            if url:
                decision["action"] = "scale_up"
                decision["worker"] = url
        elif (
            sig["queue_depth"] == 0
            and sig["memory_pressure"] < 0.5 * self.pressure_high
            and len(self.workers) > self.min_workers
        ):
            url = self.workers[-1]
            decision["action"] = "scale_down"
            decision["worker"] = url
            decision["clean"] = self.drain(url)
        self.decisions.append(decision)
        return decision
