"""Planner IR — the typed expression language the optimizer and compiler consume.

Reference blueprint: core/trino-main/src/main/java/io/trino/sql/ir/ (Expression,
Call, Case, Cast, Constant, Reference, Logical...; SURVEY.md §2.2 "IR — planner
expression language (distinct from AST)"). Every node carries its resolved SQL type.
The expression compiler (trino_tpu.ops.compiler) lowers this IR to XLA, playing the
role of io.trino.sql.gen.PageFunctionCompiler (SURVEY.md §2.4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

from ..spi.types import BOOLEAN, Type


class IrExpr:
    """Base IR expression; every node has a .type."""

    __slots__ = ()

    @property
    def type(self) -> Type:
        raise NotImplementedError


@dataclass(frozen=True)
class Reference(IrExpr):
    """Reference to a plan symbol (ref: sql/ir/Reference.java)."""

    symbol: str
    _type: Type = None

    @property
    def type(self) -> Type:
        return self._type

    def __str__(self):
        return self.symbol


@dataclass(frozen=True)
class Constant(IrExpr):
    """Typed literal; value is a host Python value in *storage* representation
    (e.g. decimal -> scaled int, varchar -> the string itself — the compiler maps
    strings to dictionary codes per input column). ref: sql/ir/Constant.java."""

    _type: Type = None
    value: Any = None

    @property
    def type(self) -> Type:
        return self._type

    def __str__(self):
        return f"{self.value!r}"


@dataclass(frozen=True)
class Call(IrExpr):
    """Function invocation; operators are functions ($add, $eq, ...) exactly as in
    Trino IR. ref: sql/ir/Call.java."""

    name: str = ""
    args: Tuple[IrExpr, ...] = ()
    _type: Type = None

    @property
    def type(self) -> Type:
        return self._type

    def __str__(self):
        return f"{self.name}({', '.join(map(str, self.args))})"


@dataclass(frozen=True)
class Lambda(IrExpr):
    """Typed lambda for higher-order functions (ref: sql/ir/Lambda.java).
    ``params`` are fresh plan symbols (never colliding with columns);
    ``type`` is the body's result type."""

    params: Tuple[str, ...] = ()
    param_types: Tuple[Type, ...] = ()
    body: "IrExpr" = None

    @property
    def type(self) -> Type:
        return self.body.type

    def __str__(self):
        return f"({', '.join(self.params)}) -> {self.body}"


@dataclass(frozen=True)
class Case(IrExpr):
    """Searched CASE (simple CASE is lowered to searched at analysis).
    ref: sql/ir/Case.java."""

    whens: Tuple[Tuple[IrExpr, IrExpr], ...] = ()
    default: Optional[IrExpr] = None
    _type: Type = None

    @property
    def type(self) -> Type:
        return self._type

    def __str__(self):
        parts = " ".join(f"WHEN {c} THEN {r}" for c, r in self.whens)
        return f"CASE {parts} ELSE {self.default} END"


@dataclass(frozen=True)
class CastExpr(IrExpr):
    value: IrExpr = None
    _type: Type = None
    safe: bool = False

    @property
    def type(self) -> Type:
        return self._type

    def __str__(self):
        return f"CAST({self.value} AS {self._type.display()})"


@dataclass(frozen=True)
class InLut(IrExpr):
    """Dictionary-LUT predicate: value's dict code indexes a host-computed boolean
    table (used for LIKE / IN over VARCHAR; see SURVEY.md §7 strings strategy)."""

    value: IrExpr = None
    lut: Tuple[bool, ...] = ()  # indexed by dictionary code
    description: str = ""

    @property
    def type(self) -> Type:
        return BOOLEAN

    def __str__(self):
        return f"in_lut({self.value}, {self.description})"


def references(expr: IrExpr) -> set:
    """All symbols referenced by an IR expression."""
    out: set = set()

    def walk(e: IrExpr):
        if isinstance(e, Reference):
            out.add(e.symbol)
        elif isinstance(e, Call):
            for a in e.args:
                walk(a)
        elif isinstance(e, Case):
            for c, r in e.whens:
                walk(c)
                walk(r)
            if e.default is not None:
                walk(e.default)
        elif isinstance(e, CastExpr):
            walk(e.value)
        elif isinstance(e, InLut):
            walk(e.value)
        elif isinstance(e, Lambda):
            inner = references(e.body)
            out.update(inner - set(e.params))

    walk(expr)
    return out


# per-row nondeterministic functions (ref: io.trino.metadata.FunctionManager
# isDeterministic; current_timestamp et al are constant-per-query and thus
# deterministic for plan rewrites)
_NONDETERMINISTIC = frozenset({"random", "rand", "uuid", "shuffle"})


def is_deterministic(expr: IrExpr) -> bool:
    """True when the expression yields the same value for the same inputs —
    rewrites that duplicate or re-site an expression (equality inference,
    predicate mirroring) must skip nondeterministic ones."""
    ok = True

    def walk(e: IrExpr):
        nonlocal ok
        if isinstance(e, Call):
            if e.name in _NONDETERMINISTIC:
                ok = False
            for a in e.args:
                walk(a)
        elif isinstance(e, Case):
            for c, r in e.whens:
                walk(c)
                walk(r)
            if e.default is not None:
                walk(e.default)
        elif isinstance(e, CastExpr):
            walk(e.value)
        elif isinstance(e, InLut):
            walk(e.value)
        elif isinstance(e, Lambda):
            walk(e.body)

    walk(expr)
    return ok


def substitute(expr: IrExpr, mapping: dict) -> IrExpr:
    """Replace Reference(symbol) per ``mapping`` (symbol -> IrExpr)."""
    if isinstance(expr, Reference):
        return mapping.get(expr.symbol, expr)
    if isinstance(expr, Call):
        return Call(expr.name, tuple(substitute(a, mapping) for a in expr.args), expr._type)
    if isinstance(expr, Case):
        return Case(
            tuple((substitute(c, mapping), substitute(r, mapping)) for c, r in expr.whens),
            substitute(expr.default, mapping) if expr.default is not None else None,
            expr._type,
        )
    if isinstance(expr, CastExpr):
        return CastExpr(substitute(expr.value, mapping), expr._type, expr.safe)
    if isinstance(expr, InLut):
        return InLut(substitute(expr.value, mapping), expr.lut, expr.description)
    if isinstance(expr, Lambda):
        # params shadow outer symbols
        inner = {k: v for k, v in mapping.items() if k not in expr.params}
        return Lambda(expr.params, expr.param_types, substitute(expr.body, inner))
    return expr
