"""Serving fabric plane (runtime/ha.py): coordinator HA with leased
dispatch handoff, cross-process warm tiers, and elastic workers.

Acceptance contracts (ISSUE 14):
- an in-flight FTE query killed at its coordinator resumes on a standby
  BIT-IDENTICAL to the uninterrupted run (journal replay + re-adoption of
  committed durable-exchange attempts);
- standby takeover respects the fencing epoch — a paused old leader's late
  writes are rejected;
- lease expiry under the chaos site never yields two leaders;
- a result-cache hit is served BEFORE the resource-group queue gate;
- torn-tail JSONL records are skipped+counted on restart, never a crash;
- one missed heartbeat is SUSPECT (no new dispatch, no strike) before GONE;
- everything is gated off by default with a byte-identical off path.
"""

import json
import os
import threading
import time

import pytest

from trino_tpu.metadata import Session
from trino_tpu.parallel.runner import DistributedQueryRunner
from trino_tpu.runtime.failure import ChaosInjector
from trino_tpu.runtime.ha import (
    TORN_RECORDS_HELP,
    CoordinatorCrashError,
    DispatchJournal,
    FencedWriteError,
    LeaderLease,
    ResumeState,
    ScaleController,
    SharedCacheTier,
    orphaned_journals,
    read_jsonl_tolerant,
    resume_fte_query,
)
from trino_tpu.runtime.metrics import REGISTRY

SCALE = 0.0005

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

Q13 = """
SELECT c_count, count(*) AS custdist
FROM (
  SELECT c_custkey, count(o_orderkey) AS c_count
  FROM customer LEFT JOIN orders ON c_custkey = o_custkey
    AND o_comment NOT LIKE '%special%requests%'
  GROUP BY c_custkey
) AS c_orders
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC
"""


def _runner(exdir, ha: bool = True) -> DistributedQueryRunner:
    r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=4)
    r.session.set("retry_policy", "TASK")
    # force fan-out so stages really run at width
    r.session.set("join_distribution_type", "PARTITIONED")
    r.session.set("target_partition_rows", 200)
    r.session.set("fte_exchange_dir", str(exdir))
    if ha:
        r.session.set("ha_plane", True)
    return r


def _torn_counter():
    return REGISTRY.counter(
        "trino_tpu_recovery_torn_records_total", help=TORN_RECORDS_HELP
    )


@pytest.fixture(params=["local", "object"])
def plane_root(request, tmp_path):
    """Durable-plane root on BOTH substrates: a plain directory and the
    rename-free object backend (``object://`` routes through
    runtime/objectstore's RetryingFileSystem). Every backend-agnostic
    lease/journal/shared-tier contract below must hold on each."""
    root = tmp_path / "plane"
    if request.param == "object":
        return "object://" + str(root)
    return str(root)


@pytest.fixture(scope="module")
def oracle(tmp_path_factory):
    """Uninterrupted FTE runs every failover result must be bit-identical
    to (also warms the XLA compile caches)."""
    r = _runner(tmp_path_factory.mktemp("oracle_ex"), ha=False)
    return {Q3: r.execute(Q3).rows, Q13: r.execute(Q13).rows}


# --------------------------------------------------------------------------- #
# leader lease
# --------------------------------------------------------------------------- #


class TestLeaderLease:
    def test_acquire_renew_and_exclusion(self, plane_root):
        a = LeaderLease(plane_root, "a", ttl=5.0)
        b = LeaderLease(plane_root, "b", ttl=5.0)
        assert a.acquire() and a.is_leader() and a.epoch == 1
        assert not b.acquire() and not b.is_leader()
        assert a.renew()
        assert a.holder() == "a"

    def test_expired_lease_takeover_bumps_epoch(self, plane_root):
        a = LeaderLease(plane_root, "a", ttl=0.1)
        b = LeaderLease(plane_root, "b", ttl=5.0)
        assert a.acquire()
        time.sleep(0.15)
        assert b.acquire() and b.epoch == 2
        # the superseded holder discovers it on its next renew
        assert not a.renew()
        assert not a.is_leader()

    def test_epoch_claim_is_exclusive(self, plane_root):
        """Two standbys racing one expired lease: write_if_absent on the
        epoch-claim object lets exactly ONE win that epoch."""
        a = LeaderLease(plane_root, "a", ttl=0.05)
        assert a.acquire()
        time.sleep(0.1)
        b = LeaderLease(plane_root, "b", ttl=5.0)
        c = LeaderLease(plane_root, "c", ttl=5.0)
        results = {}
        barrier = threading.Barrier(2)

        def race(lease, name):
            barrier.wait()
            results[name] = lease.acquire()

        ts = [
            threading.Thread(target=race, args=(lease, name))
            for lease, name in ((b, "b"), (c, "c"))
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(results.values()) == [False, True]
        assert (b.is_leader(), c.is_leader()).count(True) == 1

    def test_lease_expire_chaos_never_two_leaders(self, plane_root):
        """The lease_expire chaos site (a GC pause long enough for the
        lease to lapse): the holder forfeits BEFORE the standby can take
        over, so at no sampled instant do two leases both believe."""
        a = LeaderLease(plane_root, "a", ttl=0.2)
        b = LeaderLease(plane_root, "b", ttl=0.2)
        assert a.acquire()
        with ChaosInjector() as chaos:
            chaos.arm("lease_expire", times=1)
            assert not a.renew()
        assert not a.is_leader()  # forfeited immediately
        deadline = time.monotonic() + 5
        while not b.acquire():
            assert not (a.is_leader() and b.is_leader())
            assert time.monotonic() < deadline, "standby never took over"
            time.sleep(0.02)
        assert b.is_leader() and not a.is_leader()
        assert b.epoch == 2

    def test_release_frees_immediately(self, plane_root):
        a = LeaderLease(plane_root, "a", ttl=30.0)
        b = LeaderLease(plane_root, "b", ttl=30.0)
        assert a.acquire()
        a.release()
        assert not a.is_leader()
        assert b.acquire() and b.epoch == 2


# --------------------------------------------------------------------------- #
# dispatch journal
# --------------------------------------------------------------------------- #


class TestDispatchJournal:
    def test_round_trip(self, plane_root):
        path = DispatchJournal.path_for(plane_root, "q1")
        j = DispatchJournal(path)
        j.begin("q1", "SELECT 1", Session(catalog="tpch", schema="sf1"), 4)
        j.stage_start(0, 2)
        j.winner(0, 0, 0)
        j.winner(0, 1, 2)
        j.stage_done(0)
        st = ResumeState.load(path)
        assert st.query_id == "q1" and st.sql == "SELECT 1"
        assert st.n_workers == 4
        assert st.stages_done == {0}
        assert st.winners == {(0, 0): 0, (0, 1): 2}
        assert not st.finished
        j.finished()
        assert ResumeState.load(path).finished

    def test_torn_tail_skipped_and_counted(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        j = DispatchJournal(path)
        j.begin("q1", "SELECT 1", Session(catalog="tpch", schema="sf1"), 1)
        j.stage_done(0)
        with open(path, "a") as f:
            f.write('{"kind": "winner", "fid"')  # killed mid-append
        before = _torn_counter().value
        records, torn = read_jsonl_tolerant(path)
        assert torn == 1
        assert [r["kind"] for r in records] == ["begin", "stage_done"]
        assert _torn_counter().value == before + 1

    def test_fenced_append_rejected(self, tmp_path):
        lease_dir = str(tmp_path / "ha")
        old = LeaderLease(lease_dir, "old", ttl=0.05)
        assert old.acquire()
        j = DispatchJournal(str(tmp_path / "journal.jsonl"), lease=old)
        j.append({"kind": "stage_start", "fid": 0, "n_parts": 1})
        time.sleep(0.1)
        new = LeaderLease(lease_dir, "new", ttl=5.0)
        assert new.acquire()
        with pytest.raises(FencedWriteError):
            j.append({"kind": "winner", "fid": 0, "p": 0, "attempt": 0})
        # the new leader's journal writes fine
        j2 = DispatchJournal(str(tmp_path / "journal.jsonl"), lease=new)
        j2.append({"kind": "winner", "fid": 0, "p": 0, "attempt": 0})


class TestTornTailRecovery:
    def test_history_store_kill_mid_append(self, tmp_path):
        from trino_tpu.runtime.events import QueryHistoryStore

        path = str(tmp_path / "history.jsonl")
        store = QueryHistoryStore(path)
        store.query_completed({"queryId": "q1", "state": "FINISHED"})
        store.query_completed({"queryId": "q2", "state": "FINISHED"})
        with open(path, "a") as f:
            f.write('{"queryId": "q3", "sta')  # the kill-mid-append tail
        before = _torn_counter().value
        replayed = QueryHistoryStore(path)
        assert [r["queryId"] for r in replayed.records()] == ["q1", "q2"]
        assert _torn_counter().value == before + 1
        # the recovered store keeps appending past the torn line
        replayed.query_completed({"queryId": "q4", "state": "FINISHED"})
        again = QueryHistoryStore(path)
        assert [r["queryId"] for r in again.records()] == ["q1", "q2", "q4"]

    def test_statstore_truncated_file_recovers_cold(self, tmp_path, monkeypatch):
        from trino_tpu.runtime import statstore

        path = str(tmp_path / "stats.json")
        with open(path, "w") as f:
            f.write('{"s:abc": {"rows": 4')  # truncated mid-write
        monkeypatch.setenv("TRINO_TPU_STATS_HISTORY", path)
        before = _torn_counter().value
        assert statstore.load_history() == {}
        assert _torn_counter().value == before + 1


# --------------------------------------------------------------------------- #
# failover: killed-coordinator resume
# --------------------------------------------------------------------------- #


class TestFailover:
    def _crash(self, runner, sql, match):
        with ChaosInjector() as chaos:
            chaos.arm("coordinator_crash", times=1, match=match)
            with pytest.raises(CoordinatorCrashError) as ei:
                runner.execute(sql)
        return ei.value

    @pytest.mark.parametrize("backend", ["local", "object"])
    def test_post_stage_crash_resume_bit_identical_q3(self, tmp_path, oracle,
                                                      backend):
        """The r16 acceptance run on BOTH substrates: killed-coordinator
        resume over the object exchange must match the local-fs oracle."""
        exdir = str(tmp_path / "ex")
        if backend == "object":
            exdir = "object://" + exdir
        self._crash(_runner(exdir), Q3, "_post")
        orphans = orphaned_journals(exdir)
        assert len(orphans) == 1
        standby = _runner(exdir)
        result = resume_fte_query(standby, orphans[0])
        assert result.rows == oracle[Q3]
        # completed stages were adopted, not re-run
        assert standby.last_fte_scheduler.stats["dispatched"] > 0
        # the journal (and the whole query dir) is gone after completion
        assert orphaned_journals(exdir) == []

    def test_post_stage_crash_resume_bit_identical_q13(self, tmp_path, oracle):
        exdir = tmp_path / "ex"
        self._crash(_runner(exdir), Q13, "_post")
        orphans = orphaned_journals(str(exdir))
        assert len(orphans) == 1
        result = resume_fte_query(_runner(exdir), orphans[0])
        assert result.rows == oracle[Q13]

    def test_pre_stage_crash_resume(self, tmp_path, oracle):
        """Crash before ANYTHING committed: the journal has only begin —
        resume runs the whole query and still matches the oracle."""
        exdir = tmp_path / "ex"
        self._crash(_runner(exdir), Q3, "_f0_pre")
        orphans = orphaned_journals(str(exdir))
        assert len(orphans) == 1
        standby = _runner(exdir)
        result = resume_fte_query(standby, orphans[0])
        assert result.rows == oracle[Q3]
        assert standby.last_fte_adopted == 0

    def test_mid_stage_commits_are_adopted(self, tmp_path, oracle):
        """A coordinator dead BETWEEN a task's durable commit and the
        stage_done record: the resume re-adopts the committed attempts
        (first-commit-wins) instead of re-running those tasks."""
        exdir = tmp_path / "ex"
        primary = _runner(exdir)
        # crash at the LAST fragment's pre-site: every earlier stage done
        last_fid = primary.plan_distributed(Q3).root_fragment.fragment_id
        self._crash(primary, Q3, f"_f{last_fid}_pre")
        full_dispatched = None
        orphan = orphaned_journals(str(exdir))[0]
        # simulate the mid-stage death: drop the trailing stage_done record
        lines = [
            line for line in open(orphan).read().splitlines() if line.strip()
        ]
        dropped = False
        kept = []
        for line in reversed(lines):
            if not dropped and json.loads(line).get("kind") == "stage_done":
                dropped = True
                continue
            kept.append(line)
        assert dropped
        with open(orphan, "w") as f:
            f.write("\n".join(reversed(kept)) + "\n")
        standby = _runner(exdir)
        result = resume_fte_query(standby, orphan)
        assert result.rows == oracle[Q3]
        assert standby.last_fte_adopted >= 1
        full_dispatched = 20  # the uninterrupted Q3 run's task count floor
        assert standby.last_fte_scheduler.stats["dispatched"] < full_dispatched

    def test_fenced_old_leader_cannot_start_a_query(self, tmp_path, oracle):
        """An old leader paused past its lease: its next journaled query
        raises FencedWriteError (late writes rejected) and the new leader
        serves the same query correctly."""
        exdir = tmp_path / "ex"
        hadir = str(tmp_path / "ha")
        lease_old = LeaderLease(hadir, "old", ttl=0.1)
        assert lease_old.acquire()
        old_leader = _runner(exdir)
        old_leader.ha_lease = lease_old
        time.sleep(0.15)  # the "pause": lease lapses un-renewed
        lease_new = LeaderLease(hadir, "new", ttl=10.0)
        assert lease_new.acquire()
        with pytest.raises(FencedWriteError) as ei:
            old_leader.execute(Q3)
        assert getattr(ei.value, "query_id", "")
        new_leader = _runner(exdir)
        new_leader.ha_lease = lease_new
        assert new_leader.execute(Q3).rows == oracle[Q3]

    def test_off_path_is_untouched(self, tmp_path, oracle):
        """ha_plane off (the default): no journal is ever written and the
        FTE result is byte-identical to the oracle run."""
        exdir = tmp_path / "ex"
        runner = _runner(exdir, ha=False)
        assert runner.execute(Q3).rows == oracle[Q3]
        journals = [
            f for _, _, files in os.walk(str(exdir)) for f in files
            if f == DispatchJournal.FILENAME
        ]
        assert journals == []
        # the chaos site is dormant on the off path
        with ChaosInjector() as chaos:
            chaos.arm("coordinator_crash", times=1, match="_post")
            assert runner.execute(Q3).rows == oracle[Q3]
            assert chaos.fired.get("coordinator_crash") is None


# --------------------------------------------------------------------------- #
# heartbeat-loss grace window
# --------------------------------------------------------------------------- #


class TestSuspectGrace:
    def test_one_missed_announcement_is_suspect_not_gone(self):
        from trino_tpu.runtime.nodes import (
            InternalNodeManager,
            NodeBlacklist,
            NodeState,
            suspect_uris,
        )

        mgr = InternalNodeManager(heartbeat_timeout=0.4, suspect_timeout=0.1)
        mgr.announce("w1", "http://w1")
        mgr.announce("w2", "http://w2")
        time.sleep(0.15)
        mgr.announce("w2", "http://w2")  # w2 keeps beating
        mgr.refresh()
        states = {n.node_id: n.state for n in mgr.all_nodes()}
        assert states["w1"] is NodeState.SUSPECT
        assert states["w2"] is NodeState.ACTIVE
        assert suspect_uris(mgr) == ["http://w1"]
        # SUSPECT never burns a blacklist strike
        bl = NodeBlacklist()
        assert bl.sync_nodes(mgr) == 0
        assert not bl.is_blacklisted("http://w1")
        # ...and is excluded from the dispatchable active set
        assert [n.node_id for n in mgr.active_nodes()] == ["w2"]
        # the full timeout is the hard strike
        time.sleep(0.45)
        mgr.announce("w2", "http://w2")  # w2 is still alive and beating
        mgr.refresh()
        assert {n.node_id: n.state for n in mgr.all_nodes()}["w1"] \
            is NodeState.GONE
        assert bl.sync_nodes(mgr) == 1
        assert bl.is_blacklisted("http://w1")
        # a fresh announcement is the SUSPECT/GONE recovery path
        mgr.announce("w1", "http://w1")
        assert {n.node_id: n.state for n in mgr.all_nodes()}["w1"] \
            is NodeState.ACTIVE

    def test_scheduler_steers_around_suspects(self):
        from trino_tpu.runtime.fte_scheduler import EventDrivenFteScheduler

        sched = EventDrivenFteScheduler(
            workers=["http://w1", "http://w2"],
            session=Session(catalog="tpch", schema="sf0_0005"),
        )
        sched.set_suspects(["http://w1"])
        for _ in range(4):
            assert sched._pick_worker(()) == "http://w2"
        # survival beats purity: every worker suspect -> still dispatchable
        sched.set_suspects(["http://w1", "http://w2"])
        assert sched._pick_worker(()) in ("http://w1", "http://w2")

    def test_suspect_knob_declared(self):
        from trino_tpu import knobs

        assert knobs.env_float("TRINO_TPU_HEARTBEAT_SUSPECT_SECS", 7.5) == 7.5


# --------------------------------------------------------------------------- #
# shared warm tier
# --------------------------------------------------------------------------- #


class TestSharedCacheTier:
    def _session(self, shared: bool = True):
        s = Session(catalog="tpch", schema="sf0_001")
        s.set("result_cache", True)
        if shared:
            s.set("shared_cache_tier", True)
        return s

    def _entry(self):
        from trino_tpu.runtime.cachestore import ResultEntry

        return ResultEntry(
            names=["x"], types=None, rows=[(1,), (2,)], nbytes=64,
            created=time.time(),
            tables=(("tpch", "sf0_001", "nation", ""),), versions=("v1",),
        )

    def test_fleet_shares_one_warm_cache(self, plane_root, monkeypatch):
        """Two coordinators (two ResultCache instances — per-process state)
        over one shared dir: B serves A's entry without executing."""
        from trino_tpu.runtime.cachestore import ResultCache

        monkeypatch.setenv("TRINO_TPU_SHARED_CACHE_DIR", plane_root)
        sess = self._session()
        a, b = ResultCache(), ResultCache()
        a.store("k1", self._entry(), sess)
        got = b.lookup("k1", sess)
        assert got is not None
        assert got.rows == [(1,), (2,)]
        assert got.names == ["x"]

    def test_single_flight_lease_no_double_materialize(self, plane_root,
                                                       monkeypatch):
        """A miss claims the leased flight; a concurrent second coordinator
        WAITS for the publish instead of materializing again."""
        from trino_tpu.runtime.cachestore import ResultCache

        monkeypatch.setenv("TRINO_TPU_SHARED_CACHE_DIR", plane_root)
        sess = self._session()
        a, b = ResultCache(), ResultCache()
        tier = SharedCacheTier(plane_root)
        assert a.lookup("k2", sess) is None  # miss claims the flight
        assert tier.flight_active("k2")
        got = {}

        def loser():
            got["v"] = b.lookup("k2", sess)

        t = threading.Thread(target=loser)
        t.start()
        time.sleep(0.05)
        a.store("k2", self._entry(), sess)  # publish releases the flight
        t.join(timeout=10)
        assert got["v"] is not None and got["v"].rows == [(1,), (2,)]
        assert not tier.flight_active("k2")

    def test_crashed_materializer_lease_expires(self, plane_root):
        import trino_tpu.runtime.ha as ha_mod

        tier = SharedCacheTier(plane_root)
        assert tier.try_flight("k")
        # a second process sees the active flight and cannot claim it
        other = SharedCacheTier(plane_root)
        assert not other.try_flight("k")
        # ...until the TTL lapses (the holder "crashed")
        old_ttl = ha_mod.SHARED_FLIGHT_TTL_SECS
        ha_mod.SHARED_FLIGHT_TTL_SECS = 0.0
        try:
            loc = tier._flight_loc("k")
            tier.fs.write(loc, json.dumps({"expires_at": 0.0}).encode())
            assert other.try_flight("k")
        finally:
            ha_mod.SHARED_FLIGHT_TTL_SECS = old_ttl

    def test_oversized_store_releases_flight(self, tmp_path, monkeypatch):
        """A result too big for the tier never publishes — but the flight
        claimed at lookup time must be freed, not leaked until TTL."""
        from trino_tpu.runtime.cachestore import ResultCache

        monkeypatch.setenv("TRINO_TPU_SHARED_CACHE_DIR", str(tmp_path / "w"))
        sess = self._session()
        sess.set("result_cache_max_bytes", 16)  # entry nbytes=64 won't fit
        cache = ResultCache()
        tier = SharedCacheTier(str(tmp_path / "w"))
        assert cache.lookup("big", sess) is None  # miss claims the flight
        assert tier.flight_active("big")
        cache.store("big", self._entry(), sess)
        assert not tier.flight_active("big")
        assert tier.get("big") is None  # nothing published either

    def test_failed_run_releases_flight(self, tmp_path, monkeypatch):
        """release_flight (the failed/canceled-query path in local.py):
        peers stop waiting immediately instead of riding out the TTL."""
        from trino_tpu.runtime.cachestore import ResultCache

        monkeypatch.setenv("TRINO_TPU_SHARED_CACHE_DIR", str(tmp_path / "w"))
        sess = self._session()
        cache = ResultCache()
        tier = SharedCacheTier(str(tmp_path / "w"))
        assert cache.lookup("doomed", sess) is None
        assert tier.flight_active("doomed")
        cache.release_flight("doomed", sess)
        assert not tier.flight_active("doomed")

    def test_gated_off_by_default(self, tmp_path, monkeypatch):
        """Without the session gate, the env dir alone changes nothing (and
        vice versa) — the off path never touches the shared dir."""
        from trino_tpu.runtime.cachestore import ResultCache
        from trino_tpu.runtime.ha import shared_tier

        monkeypatch.setenv("TRINO_TPU_SHARED_CACHE_DIR", str(tmp_path / "w"))
        assert shared_tier(self._session(shared=False)) is None
        monkeypatch.delenv("TRINO_TPU_SHARED_CACHE_DIR")
        assert shared_tier(self._session(shared=True)) is None
        sess = self._session(shared=False)
        monkeypatch.setenv("TRINO_TPU_SHARED_CACHE_DIR", str(tmp_path / "w"))
        a, b = ResultCache(), ResultCache()
        a.store("k1", self._entry(), sess)
        assert b.lookup("k1", sess) is None
        assert not (tmp_path / "w" / "result").exists()


# the cross-process race worker: REAL processes (not threads — the GIL
# serializes same-process access and would hide torn reads / double claims)
# hammering one shared dir with concurrent get/publish/invalidate. Payload
# rows embed a checksum so any torn read is detected at the reader. Imports
# only runtime.ha (no jax) so worker startup stays cheap.
_RACE_WORKER = r"""
import hashlib, json, sys
from trino_tpu.runtime.ha import SharedCacheTier

tier_dir, worker_id, rounds = sys.argv[1], sys.argv[2], int(sys.argv[3])
tier = SharedCacheTier(tier_dir)
wins, torn = 0, 0
for i in range(rounds):
    key = f"k{i % 7}"
    raw = tier.get(key)
    if raw is not None:
        body = json.dumps(raw["rows"], sort_keys=True)
        if hashlib.sha256(body.encode()).hexdigest() != raw["checksum"]:
            torn += 1
    if tier.try_flight(key):
        rows = [[worker_id, i, n] for n in range(50)]
        body = json.dumps(rows, sort_keys=True)
        tier.publish(key, {
            "rows": rows,
            "checksum": hashlib.sha256(body.encode()).hexdigest(),
        })
        wins += 1
    elif i % 11 == 0:
        tier.invalidate(key)
print(json.dumps({"wins": wins, "torn": torn}))
"""


class TestSharedTierCrossProcessRaces:
    def test_concurrent_lookup_publish_invalidate(self, tmp_path):
        """Two real processes race lookup/publish/invalidate on one dir:
        every observed value passes its embedded checksum (no torn reads
        — fs.py's temp+rename publish and atomic unlink invalidate), and
        single-flight claims stay exclusive (O_EXCL CAS)."""
        import subprocess
        import sys

        tier_dir = str(tmp_path / "w")
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _RACE_WORKER, tier_dir, wid, "120"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                cwd=os.path.dirname(os.path.dirname(__file__)),
            )
            for wid in ("w1", "w2")
        ]
        results = []
        for p in procs:
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, err.decode()
            results.append(json.loads(out.decode().strip().splitlines()[-1]))
        assert sum(r["torn"] for r in results) == 0
        assert sum(r["wins"] for r in results) > 0
        # steady state: whatever survived the races still reads clean
        import hashlib

        tier = SharedCacheTier(tier_dir)
        for i in range(7):
            raw = tier.get(f"k{i}")
            if raw is None:
                continue
            body = json.dumps(raw["rows"], sort_keys=True)
            assert hashlib.sha256(body.encode()).hexdigest() == \
                raw["checksum"]

    def test_invalidate_is_atomic_unlink(self, tmp_path):
        tier = SharedCacheTier(str(tmp_path / "w"))
        tier.publish("k", {"rows": [[1]]})
        assert tier.get("k") is not None
        tier.invalidate("k")
        assert tier.get("k") is None
        tier.invalidate("k")  # idempotent on a missing key


# --------------------------------------------------------------------------- #
# coordinator lease maintenance
# --------------------------------------------------------------------------- #


class TestCoordinatorLeaseMaintenance:
    def test_renewal_and_standby_takeover(self, tmp_path):
        """The production failover loop: the primary's maintenance thread
        renews past the TTL; killing the primary stops renewals and the
        STANDBY's own loop takes the lease at the next epoch."""
        from trino_tpu.runtime.local import LocalQueryRunner
        from trino_tpu.server.coordinator import CoordinatorServer

        hadir = str(tmp_path / "ha")
        primary_lease = LeaderLease(hadir, "primary", ttl=0.3)
        standby_lease = LeaderLease(hadir, "standby", ttl=0.3)
        primary = CoordinatorServer(
            LocalQueryRunner.tpch(scale=0.001), ha_lease=primary_lease
        ).start()
        standby = CoordinatorServer(
            LocalQueryRunner.tpch(scale=0.001), ha_lease=standby_lease
        ).start()
        try:
            assert primary_lease.is_leader()
            assert not standby_lease.is_leader()
            time.sleep(0.7)  # > 2x ttl: only live renewal keeps the lease
            assert primary_lease.is_leader(), "renewal loop not running"
            assert not standby_lease.is_leader()
            primary.stop()  # the "crash": renewals cease
            deadline = time.monotonic() + 10
            while not standby_lease.is_leader():
                assert time.monotonic() < deadline, "standby never took over"
                time.sleep(0.05)
            assert standby_lease.epoch == 2
            assert not primary_lease.is_leader()
        finally:
            for server in (primary, standby):
                try:
                    server.stop()
                except Exception:  # noqa: BLE001 — teardown
                    pass


# --------------------------------------------------------------------------- #
# elastic workers
# --------------------------------------------------------------------------- #


class TestElasticWorkers:
    def _sched(self, workers):
        from trino_tpu.runtime.fte_scheduler import EventDrivenFteScheduler

        return EventDrivenFteScheduler(
            workers=workers,
            session=Session(catalog="tpch", schema="sf0_0005"),
        )

    def test_admit_worker_becomes_pickable(self):
        sched = self._sched(["http://a"])
        assert sched.admit_worker("http://b/")
        assert "http://b" in sched.workers
        assert not sched.admit_worker("http://b")  # idempotent
        # least-loaded pick can now land on the late joiner
        sched._inflight["http://a"] = 3
        assert sched._pick_worker(()) == "http://b"

    def test_drain_holds_out_new_dispatch(self):
        sched = self._sched(["http://a", "http://b"])
        sched.drain_worker("http://a")
        for _ in range(4):
            assert sched._pick_worker(()) == "http://b"
        # survival beats purity when EVERYTHING is draining
        sched.drain_worker("http://b")
        assert sched._pick_worker(()) in ("http://a", "http://b")

    def test_controller_scales_up_on_queue_depth(self):
        class Groups:
            def flat_info(self):
                return [{"queued": 6}, {"queued": 2}]

        sched = self._sched(["http://a"])
        spawned = []
        ctl = ScaleController(
            resource_groups=Groups(),
            spawn=lambda: spawned.append("http://new") or "http://new",
            queue_high=4, max_workers=2,
        )
        ctl.workers = ["http://a"]
        decision = ctl.tick()
        assert decision["action"] == "scale_up"
        assert decision["queue_depth"] == 8
        assert spawned == ["http://new"]
        # the late joiner was admitted into the RUNNING query's scheduler
        assert "http://new" in sched.workers

    def test_controller_drains_idle_fleet(self):
        retired = []
        ctl = ScaleController(
            retire=retired.append, min_workers=1, max_workers=4,
        )
        ctl.workers = ["http://a", "http://b"]
        decision = ctl.tick()
        assert decision["action"] == "scale_down"
        assert decision["clean"] is True
        assert retired == ["http://b"]
        assert ctl.workers == ["http://a"]
        # never below the floor
        assert ctl.tick()["action"] == "hold"

    def test_drain_waits_for_inflight(self):
        sched = self._sched(["http://a", "http://b"])
        sched._inflight["http://a"] = 1
        retired = []
        ctl = ScaleController(retire=retired.append, min_workers=0)
        ctl.workers = ["http://a"]

        def finish():
            time.sleep(0.1)
            sched._inflight["http://a"] = 0

        t = threading.Thread(target=finish)
        t.start()
        assert ctl.drain("http://a", wait_secs=5.0)
        t.join()
        assert retired == ["http://a"]
        assert "http://a" in sched._draining


# --------------------------------------------------------------------------- #
# cache-aware admission
# --------------------------------------------------------------------------- #


class TestCacheAwareAdmission:
    def _setup(self):
        from trino_tpu.runtime.local import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.001)
        runner.session.set("result_cache", True)
        sql = "SELECT count(*) FROM nation"
        warm = runner.execute(sql)  # populates the result tier
        assert runner.peek_cached_result(sql) is not None
        block = threading.Event()
        started = threading.Event()

        def exec_fn(q_sql, user=None):
            if q_sql == "SLOW":
                started.set()
                block.wait(30)
                return runner.execute("SELECT 1")
            return runner.execute(q_sql)

        exec_fn.peek_cached_result = runner.peek_cached_result
        return runner, sql, warm, exec_fn, block, started

    def test_warm_hit_served_before_saturated_queue(self):
        """ROADMAP item 5's explicit callout: a result-cache hit must NOT
        wait behind the resource-group gate — a warm hit returns in ~ms
        while the group's one slot is saturated."""
        from trino_tpu.runtime.query_manager import QueryManager, QueryState

        runner, sql, warm, exec_fn, block, started = self._setup()
        mgr = QueryManager(exec_fn, max_concurrent=1)
        try:
            slow = mgr.submit("SLOW")
            assert started.wait(30)  # the only slot is now occupied
            filler = mgr.submit("SELECT 2")  # control: queues behind
            t0 = time.perf_counter()
            hit = mgr.submit(sql)
            assert hit.wait_done(10)
            elapsed = time.perf_counter() - t0
            assert hit.state is QueryState.FINISHED
            assert hit.rows == warm.rows
            assert elapsed < 1.0, f"warm hit waited {elapsed:.2f}s in queue"
            assert not filler.state.is_done  # the cold query still queues
        finally:
            block.set()
            slow.wait_done(30)
            filler.wait_done(30)

    def test_gate_respects_cache_aware_admission_knob(self):
        from trino_tpu.runtime.query_manager import QueryManager

        runner, sql, _, exec_fn, block, started = self._setup()
        runner.session.set("cache_aware_admission", False)
        mgr = QueryManager(exec_fn, max_concurrent=1)
        try:
            slow = mgr.submit("SLOW")
            assert started.wait(30)
            hit = mgr.submit(sql)
            assert not hit.wait_done(0.5)  # waits its queue turn like HEAD
        finally:
            block.set()
            slow.wait_done(30)
            hit.wait_done(30)

    def test_peek_never_executes_or_misfires(self):
        """peek is a pure probe: cold key -> None; non-query text -> None;
        disabled tier -> None."""
        from trino_tpu.runtime.cachestore import CACHES
        from trino_tpu.runtime.local import LocalQueryRunner

        CACHES.clear()  # the tiers are process-wide; start cold
        runner = LocalQueryRunner.tpch(scale=0.001)
        assert runner.peek_cached_result("SELECT count(*) FROM region") is None
        runner.session.set("result_cache", True)
        # still cold: nothing executed, nothing stored
        _, _, before = CACHES.result.snapshot()
        assert runner.peek_cached_result("SELECT count(*) FROM region") is None
        assert runner.peek_cached_result("SHOW CATALOGS") is None
        # the probe is PURE: no hit/miss counters ticked, no LRU touched
        _, _, after = CACHES.result.snapshot()
        assert (after.hits, after.misses) == (before.hits, before.misses)
        # executing under the enabled tier is what makes peek hit
        want = runner.execute("SELECT count(*) FROM region")
        hit = runner.peek_cached_result("SELECT count(*) FROM region")
        assert hit is not None and hit.rows == want.rows
