"""System catalog + full event-listener lifecycle + query history (ISSUE 3).

Covers: system.runtime.{queries,tasks,nodes,flight_events,query_history} and
system.metrics.{counters,histograms} as live SQL tables, CALL
system.runtime.kill_query, the bounded completed-query ring, distinguished
cancel outcomes, lifecycle dispatch ordering + exception isolation, the
persistent query-history store, and the metric HELP lint.
"""

import json
import os
import threading
import time

import pytest

from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.events import (
    LIFECYCLE_EVENTS,
    CollectingEventListener,
    FileEventListener,
    QueryHistoryStore,
)
from trino_tpu.runtime.query_manager import (
    CancelResult,
    QueryManager,
    QueryNotFound,
    QueryState,
)

SCALE = 0.0005


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():  # single success is enough: transient states (e.g. a
            return  # file mid-rotation) must not fail a later re-check
        time.sleep(0.02)
    assert cond()


class _Blocking:
    """Executor fn whose 'slow' queries block until released."""

    def __init__(self):
        self.release = threading.Event()
        self.started = threading.Event()

    def __call__(self, sql):
        if sql.startswith("slow"):
            self.started.set()
            self.release.wait(timeout=20)

        class R:
            column_names = ["x"]
            rows = [(1,)]

        return R()


class TestSystemRuntimeQueries:
    def test_queries_table_sees_itself_and_history(self, runner):
        """Acceptance: the submitting query appears RUNNING alongside at
        least one completed historical query, with device_busy_ms."""
        mgr = QueryManager(runner.execute)
        warm = mgr.submit("SELECT count(*) FROM nation")
        assert warm.wait_done(60)
        q = mgr.submit(
            "SELECT query_id, state, device_busy_ms "
            "FROM system.runtime.queries"
        )
        assert q.wait_done(60)
        assert q.state == QueryState.FINISHED, q.error
        by_id = {r[0]: r for r in q.rows}
        assert warm.query_id in by_id
        assert by_id[warm.query_id][1] == "FINISHED"
        # the scan ran while its own query was RUNNING
        assert by_id[q.query_id][1] == "RUNNING"
        assert all(isinstance(r[2], int) for r in q.rows)

    def test_group_by_state(self, runner):
        mgr = QueryManager(runner.execute)
        mgr.submit("SELECT 1").wait_done(60)
        q = mgr.submit(
            "SELECT state, count(*) FROM system.runtime.queries GROUP BY 1"
        )
        assert q.wait_done(60)
        states = dict(q.rows)
        assert states.get("RUNNING", 0) >= 1
        assert states.get("FINISHED", 0) >= 1

    def test_auto_wiring_last_manager_wins(self, runner):
        mgr = QueryManager(runner.execute)
        assert runner.metadata.system_context.query_manager is mgr

    def test_empty_without_manager(self):
        solo = LocalQueryRunner.tpch(scale=SCALE)
        res = solo.execute("SELECT query_id FROM system.runtime.queries")
        assert res.rows == []


class TestHistoryRing:
    def test_terminal_queries_retained_up_to_cap(self):
        blocking = _Blocking()
        mgr = QueryManager(blocking, max_history=3)
        done = [mgr.submit(f"q{i}") for i in range(5)]
        for q in done:
            assert q.wait_done(30)
        _wait(lambda: len(mgr.list_queries()) == 3)
        kept = {q.query_id for q in mgr.list_queries()}
        # the OLDEST completed queries were evicted
        assert all(q.state.is_done for q in mgr.list_queries())
        assert len(kept) == 3

    def test_env_var_overrides_default(self, monkeypatch):
        monkeypatch.setenv("TRINO_TPU_QUERY_HISTORY", "7")
        mgr = QueryManager(_Blocking())
        assert mgr._max_history == 7

    def test_running_queries_never_evicted(self):
        blocking = _Blocking()
        mgr = QueryManager(blocking, max_history=1, max_workers=2)
        slow = mgr.submit("slow")
        assert blocking.started.wait(10)
        for i in range(3):
            mgr.submit(f"q{i}").wait_done(30)
        assert mgr.get(slow.query_id) is not None  # still tracked
        blocking.release.set()
        assert slow.wait_done(30)


class TestCancelSemantics:
    def test_unknown_raises(self):
        mgr = QueryManager(_Blocking())
        with pytest.raises(QueryNotFound):
            mgr.cancel("q_does_not_exist")
        with pytest.raises(QueryNotFound):
            mgr.kill("q_does_not_exist")

    def test_terminal_marker(self):
        mgr = QueryManager(_Blocking())
        q = mgr.submit("fast")
        assert q.wait_done(30)
        assert mgr.cancel(q.query_id) is CancelResult.TERMINAL
        assert mgr.kill(q.query_id) is CancelResult.TERMINAL
        assert q.state == QueryState.FINISHED  # kill never rewrites history
        assert q.error is None

    def test_live_cancel(self):
        blocking = _Blocking()
        mgr = QueryManager(blocking)
        q = mgr.submit("slow")
        assert blocking.started.wait(10)
        assert mgr.cancel(q.query_id) is CancelResult.CANCELED
        assert q.state == QueryState.CANCELED
        blocking.release.set()


class TestKillQueryProcedure:
    def test_call_kills_running_query(self, runner):
        """Acceptance: CALL system.runtime.kill_query cancels a concurrently
        running query, verified via the lifecycle events."""
        blocking = _Blocking()
        mgr = QueryManager(blocking)
        listener = CollectingEventListener()
        mgr.add_listener(listener)
        runner.metadata.system_context.query_manager = mgr
        try:
            victim = mgr.submit("slow victim")
            assert blocking.started.wait(10)
            res = runner.execute(
                f"CALL system.runtime.kill_query("
                f"'{victim.query_id}', 'killed by test')"
            )
            assert res.rows == [(True,)]
            assert victim.wait_done(10)
            assert victim.state == QueryState.FAILED
            assert victim.error == "killed by test"
            assert victim.error_type == "AdministrativelyKilled"
            _wait(
                lambda: any(
                    e["eventType"] == "QueryCompleted"
                    and e["queryId"] == victim.query_id
                    and e["errorType"] == "AdministrativelyKilled"
                    for e in listener.events
                )
            )
        finally:
            blocking.release.set()
            QueryManager(runner.execute)  # restore auto-wiring for others

    def test_call_unknown_query_raises(self, runner):
        QueryManager(runner.execute)
        with pytest.raises(QueryNotFound):
            runner.execute("CALL system.runtime.kill_query('q_nope')")

    def test_call_terminal_query_raises(self, runner):
        mgr = QueryManager(runner.execute)
        q = mgr.submit("SELECT 1")
        assert q.wait_done(60)
        with pytest.raises(ValueError, match="not running"):
            runner.execute(
                f"CALL system.runtime.kill_query('{q.query_id}')"
            )

    def test_unknown_procedure(self, runner):
        with pytest.raises(ValueError, match="procedure not found"):
            runner.execute("CALL system.runtime.no_such_proc(1)")

    def test_kill_consults_access_control_for_foreign_query(self):
        """checkCanKillQueryOwnedBy analogue: an access control providing the
        hook can deny killing another user's query; your own query never
        consults it."""
        from trino_tpu.spi.security import AllowAllAccessControl

        class StrictKill(AllowAllAccessControl):
            def check_can_kill_query_owned_by(self, user, owner):
                raise PermissionError(
                    f"{user} cannot kill query owned by {owner}"
                )

        runner = LocalQueryRunner.tpch(scale=SCALE)
        runner.access_control = StrictKill()
        blocking = _Blocking()
        mgr = QueryManager(blocking)
        runner.metadata.system_context.query_manager = mgr
        victim = mgr.submit("slow", user="bob")
        assert blocking.started.wait(10)
        try:
            with pytest.raises(PermissionError, match="cannot kill"):
                runner.execute(
                    f"CALL system.runtime.kill_query('{victim.query_id}')",
                    user="alice",
                )
            assert not victim.state.is_done
            # bob may kill bob's query: the hook is not consulted
            res = runner.execute(
                f"CALL system.runtime.kill_query('{victim.query_id}')",
                user="bob",
            )
            assert res.rows == [(True,)]
            assert victim.wait_done(10)
        finally:
            blocking.release.set()


class TestListenerLifecycle:
    def test_dispatch_order_success(self, runner):
        mgr = QueryManager(runner.execute)
        listener = CollectingEventListener()
        mgr.add_listener(listener)
        q = mgr.submit("SELECT count(*) FROM region")
        assert q.wait_done(60)
        _wait(
            lambda: any(
                e["eventType"] == "QueryCompleted"
                and e["queryId"] == q.query_id
                for e in listener.events
            )
        )
        kinds = [
            e["eventType"]
            for e in listener.events
            if e["queryId"] == q.query_id
            and e["eventType"] != "SplitCompleted"
        ]
        assert kinds[0] == "QueryCreated"
        assert kinds[-1] == "QueryCompleted"
        assert kinds[1:-1] and all(
            k == "QueryStateChange" for k in kinds[1:-1]
        )
        # state-machine order: QUEUED seen at creation, terminal at the end
        states = [
            e["state"]
            for e in listener.events
            if e["queryId"] == q.query_id
            and e["eventType"] != "SplitCompleted"
        ]
        assert states[0] == "QUEUED"
        assert states[-1] == "FINISHED"

    def test_order_for_parse_failure(self, runner):
        mgr = QueryManager(runner.execute)
        listener = CollectingEventListener()
        mgr.add_listener(listener)
        q = mgr.submit("SELECT FROM WHERE nonsense !!")
        assert q.wait_done(60)
        assert q.state == QueryState.FAILED
        _wait(
            lambda: any(
                e["eventType"] == "QueryCompleted"
                and e["queryId"] == q.query_id
                for e in listener.events
            )
        )
        kinds = [
            e["eventType"]
            for e in listener.events
            if e["queryId"] == q.query_id
        ]
        assert kinds[0] == "QueryCreated"
        assert kinds[-1] == "QueryCompleted"
        completed = [
            e for e in listener.events
            if e["queryId"] == q.query_id
            and e["eventType"] == "QueryCompleted"
        ]
        assert completed[0]["state"] == "FAILED"
        assert completed[0]["errorType"]

    def test_raising_listener_is_isolated(self, runner):
        """A listener that raises must not wedge transition() nor starve the
        listeners registered after it."""
        mgr = QueryManager(runner.execute)

        class Bomb:
            def query_created(self, event):
                raise RuntimeError("created boom")

            def query_state_change(self, event):
                raise RuntimeError("state boom")

            def query_completed(self, event):
                raise RuntimeError("completed boom")

        survivor = CollectingEventListener()
        mgr.add_listener(Bomb())
        mgr.add_listener(survivor)
        q = mgr.submit("SELECT 1")
        assert q.wait_done(60)
        assert q.state == QueryState.FINISHED
        _wait(
            lambda: any(
                e["eventType"] == "QueryCompleted"
                and e["queryId"] == q.query_id
                for e in survivor.events
            )
        )
        kinds = [
            e["eventType"] for e in survivor.events
            if e["queryId"] == q.query_id
        ]
        assert "QueryCreated" in kinds and "QueryCompleted" in kinds

    def test_split_completed_events(self, runner):
        mgr = QueryManager(runner.execute)
        listener = CollectingEventListener()
        mgr.add_listener(listener)
        q = mgr.submit("SELECT count(*) FROM nation")
        assert q.wait_done(60)
        _wait(lambda: listener.of_type("SplitCompleted"))
        ev = listener.of_type("SplitCompleted")[0]
        assert ev["queryId"] == q.query_id
        assert ev["table"].endswith("nation")
        assert ev["rows"] == 25

    def test_base_class_noop_does_not_enable_split_path(self, runner, tmp_path):
        """An EventListener subclass that only overrides query_completed
        (e.g. the history store) must not switch on per-split dispatch."""
        mgr = QueryManager(runner.execute)
        mgr.add_listener(QueryHistoryStore(str(tmp_path / "h.jsonl")))
        assert not mgr._wants("split_completed")
        assert mgr._wants("query_completed")
        mgr.add_listener(CollectingEventListener())  # overrides all hooks
        assert mgr._wants("split_completed")

    def test_legacy_callable_listener_still_completion_only(self, runner):
        mgr = QueryManager(runner.execute)
        seen = []
        mgr.add_listener(lambda q: seen.append(q.state))
        q = mgr.submit("SELECT 1")
        assert q.wait_done(60)
        _wait(lambda: seen)
        assert seen == [QueryState.FINISHED]


class TestFileListenerRotation:
    def test_rotates_by_size(self, tmp_path, runner):
        path = str(tmp_path / "events.jsonl")
        listener = FileEventListener(
            path, events=LIFECYCLE_EVENTS, max_bytes=600
        )
        mgr = QueryManager(runner.execute)
        mgr.add_listener(listener)
        for _ in range(4):
            mgr.submit("SELECT 1").wait_done(60)
        _wait(lambda: os.path.exists(path + ".1"))
        # wait for dispatch to quiesce, then both generations must exist
        # (mid-rotation there is an instant with no base file)
        time.sleep(0.3)
        _wait(
            lambda: os.path.exists(path + ".1") and os.path.exists(path)
        )
        # both generations hold valid JSONL
        for p in (path, path + ".1"):
            with open(p) as f:
                for line in f:
                    json.loads(line)


class TestQueryHistoryStore:
    def test_survives_restart_and_backs_table(self, tmp_path, runner):
        path = str(tmp_path / "history.jsonl")
        mgr = QueryManager(runner.execute)
        store = QueryHistoryStore(path)
        mgr.add_listener(store)
        q = mgr.submit("SELECT count(*) FROM region")
        assert q.wait_done(60)
        _wait(lambda: store.records())
        # simulate a coordinator restart: a fresh store over the same file
        reloaded = QueryHistoryStore(path)
        recs = reloaded.records()
        assert [r["queryId"] for r in recs] == [q.query_id]
        assert recs[0]["state"] == "FINISHED"
        runner.metadata.system_context.history_store = reloaded
        try:
            res = runner.execute(
                "SELECT query_id, state, rows "
                "FROM system.runtime.query_history"
            )
            assert (q.query_id, "FINISHED", 1) in res.rows
        finally:
            runner.metadata.system_context.history_store = None

    def test_compaction_bounds_file(self, tmp_path):
        path = str(tmp_path / "history.jsonl")
        store = QueryHistoryStore(path, max_records=5)
        for i in range(25):
            store.query_completed({"queryId": f"q{i}", "state": "FINISHED"})
        with open(path) as f:
            lines = [l for l in f if l.strip()]
        assert len(lines) <= 10  # 2 * max_records
        assert [r["queryId"] for r in store.records()] == [
            f"q{i}" for i in range(20, 25)
        ]


class TestSystemNodesAndTasks:
    def test_local_nodes_row(self):
        solo = LocalQueryRunner.tpch(scale=SCALE)
        res = solo.execute(
            "SELECT node_id, coordinator, state, device "
            "FROM system.runtime.nodes"
        )
        assert len(res.rows) == 1
        node_id, coordinator, state, device = res.rows[0]
        assert node_id == "local" and coordinator is True
        assert state == "ACTIVE" and device

    def test_tasks_table_reads_worker_registry(self, runner):
        from trino_tpu.connectors.tpch import TpchConnector
        from trino_tpu.metadata import CatalogManager
        from trino_tpu.server.worker import (
            TaskDescriptor,
            WorkerServer,
            encode_task,
            sign,
        )
        import urllib.request

        catalogs = CatalogManager()
        catalogs.register("tpch", TpchConnector(scale=SCALE))
        w = WorkerServer(catalogs, secret="sys-tasks").start()
        try:
            from trino_tpu.planner.plan import ValuesNode
            from trino_tpu.spi.types import BIGINT

            desc = TaskDescriptor(
                root=ValuesNode(symbols=("x",), rows=((1,),)),
                types={"x": BIGINT},
            )
            body = encode_task(desc)
            rel = "/v1/task/tq1_f0_p0"
            req = urllib.request.Request(
                f"http://{w.address}{rel}", data=body, method="POST"
            )
            req.add_header(
                "X-Trino-Tpu-Signature", sign("sys-tasks", "POST", rel, body)
            )
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
            deadline = time.time() + 10
            while time.time() < deadline:
                res = runner.execute(
                    "SELECT node_id, task_id, query_id, state "
                    "FROM system.runtime.tasks"
                )
                match = [r for r in res.rows if r[1] == "tq1_f0_p0"]
                if match and match[0][3] in ("FINISHED", "FAILED"):
                    break
                time.sleep(0.1)
            assert match, res.rows
            assert match[0][0] == w.address
            assert match[0][2] == "tq1"
        finally:
            w.stop()


class TestSystemMetricsAndFlightEvents:
    def test_counters_table(self, runner):
        runner.execute("SELECT 1")
        res = runner.execute(
            "SELECT name, kind, value, help FROM system.metrics.counters"
        )
        by_name = {r[0]: r for r in res.rows}
        assert "trino_tpu_queries_submitted_total" in by_name
        name, kind, value, help_ = by_name["trino_tpu_queries_submitted_total"]
        assert kind == "counter" and value >= 1 and help_

    def test_histograms_table(self, runner):
        res = runner.execute(
            "SELECT name, le, cumulative_count, count "
            "FROM system.metrics.histograms "
            "WHERE name = 'trino_tpu_query_duration_secs'"
        )
        assert res.rows
        # cumulative within a series is monotone, +Inf bucket == count
        inf_rows = [r for r in res.rows if r[1] == float("inf")]
        assert inf_rows and all(r[2] == r[3] for r in inf_rows)

    def test_flight_events_table(self, runner):
        from trino_tpu.runtime.observability import RECORDER

        RECORDER.enable()
        try:
            runner.execute("SELECT count(*) FROM nation")
        finally:
            RECORDER.disable()
        res = runner.execute(
            "SELECT kind, dur FROM system.runtime.flight_events "
            "WHERE kind = 'xla_compile' ORDER BY dur DESC"
        )
        # compiles may be cache-warm in-suite; the execution span always lands
        res2 = runner.execute(
            "SELECT kind, cat FROM system.runtime.flight_events"
        )
        kinds = {r[0] for r in res2.rows}
        assert "execution" in kinds
        assert all(r[1] >= 0 for r in res.rows)

    def test_every_registered_metric_has_help(self):
        """Lint: every series in the process registry carries HELP text
        (delegates to the shared engine-lint rule the per-plane copies
        collapsed into — tools/lint/rules.py)."""
        from tools.lint.rules import registry_help_problems

        assert registry_help_problems() == []

    def test_metric_call_sites_pass_help(self):
        """Source lint: REGISTRY.counter/gauge/histogram call sites always
        pass non-empty help (the AST half of the shared HELP rule, run
        through the engine-lint framework)."""
        from tools.lint.engine import LintEngine
        from tools.lint.rules import metric_help_missing

        engine = LintEngine([metric_help_missing])
        result = engine.run("trino_tpu")
        offenders = [f"{f.file}:{f.line} {f.message}" for f in result.findings]
        assert not offenders, offenders


class TestSystemSmokeCheck:
    """The tier-1 system-catalog smoke check (satellite: CI/tooling)."""

    def test_system_smoke_passes(self):
        import importlib.util

        tools = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        )
        spec = importlib.util.spec_from_file_location(
            "obs_smoke_sys", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_system_smoke() == []


class TestSystemCatalogMetadata:
    def test_show_tables_and_schemas(self, runner):
        res = runner.execute("SHOW SCHEMAS FROM system")
        assert {("metrics",), ("runtime",)} <= set(res.rows)
        res = runner.execute("SHOW TABLES FROM system.runtime")
        assert {
            ("queries",), ("tasks",), ("nodes",), ("flight_events",),
            ("query_history",),
        } <= set(res.rows)

    def test_registered_catalog_wins(self, runner):
        from trino_tpu.connectors.memory import MemoryConnector

        runner.catalogs.register("system", MemoryConnector())
        try:
            conn = runner.metadata.connector_by_name("system")
            assert isinstance(conn, MemoryConnector)
        finally:
            runner.catalogs.deregister("system")

    def test_use_system_catalog(self):
        solo = LocalQueryRunner.tpch(scale=SCALE)
        solo.execute("USE system.runtime")
        res = solo.execute("SELECT node_id FROM nodes")  # unqualified
        assert res.rows == [("local",)]

    def test_information_schema_over_system_catalog(self, runner):
        """BI-tool discovery path: system.information_schema.tables must list
        the builtin runtime/metrics tables (the resolver, not the
        CatalogManager, knows the system catalog)."""
        res = runner.execute(
            "SELECT table_schema, table_name "
            "FROM system.information_schema.tables"
        )
        assert {
            ("runtime", "queries"), ("runtime", "tasks"),
            ("runtime", "nodes"), ("runtime", "flight_events"),
            ("runtime", "query_history"), ("metrics", "counters"),
            ("metrics", "histograms"),
        } <= set(res.rows)
        res = runner.execute(
            "SELECT schema_name FROM system.information_schema.schemata"
        )
        assert {("runtime",), ("metrics",)} <= set(res.rows)

    def test_unknown_system_table(self, runner):
        with pytest.raises(ValueError, match="table not found"):
            runner.execute("SELECT * FROM system.runtime.nope")
