"""Dynamic catalogs: CREATE/DROP CATALOG + connector factories.

ref: the reference's CREATE CATALOG task over CatalogStore +
ConnectorFactory resolution (connector/ConnectorServicesProvider,
StaticCatalogManager made runtime-registrable).
"""

import pytest

from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture()
def runner():
    return LocalQueryRunner()


class TestDynamicCatalogs:
    def test_create_query_drop(self, runner):
        runner.execute("CREATE CATALOG small USING tpch WITH (scale = 0.001)")
        assert runner.execute(
            "SELECT count(*) FROM small.sf0_001.nation"
        ).rows == [(25,)]
        assert ("small",) in runner.execute("SHOW CATALOGS").rows
        runner.execute("DROP CATALOG small")
        assert ("small",) not in runner.execute("SHOW CATALOGS").rows

    def test_if_not_exists_and_duplicates(self, runner):
        runner.execute("CREATE CATALOG c1 USING memory")
        with pytest.raises(Exception):
            runner.execute("CREATE CATALOG c1 USING memory")
        runner.execute("CREATE CATALOG IF NOT EXISTS c1 USING memory")
        runner.execute("DROP CATALOG c1")
        with pytest.raises(Exception):
            runner.execute("DROP CATALOG c1")
        runner.execute("DROP CATALOG IF EXISTS c1")

    def test_unknown_connector_lists_available(self, runner):
        with pytest.raises(Exception) as ei:
            runner.execute("CREATE CATALOG x USING nosuch")
        assert "available" in str(ei.value)

    def test_memory_catalog_end_to_end(self, runner):
        runner.execute("CREATE CATALOG m USING memory")
        runner.execute("CREATE TABLE m.default.t (x bigint)")
        runner.execute("INSERT INTO m.default.t VALUES (1), (2)")
        assert runner.execute("SELECT sum(x) FROM m.default.t").rows == [(3,)]

    def test_lake_catalog_via_sql(self, runner, tmp_path):
        runner.execute(
            f"CREATE CATALOG lk USING lake WITH "
            f"(warehouse = 'local://wh', local_root = '{tmp_path}')"
        )
        runner.execute(
            "CREATE TABLE lk.default.t AS SELECT 1 AS x UNION ALL SELECT 2"
        )
        assert runner.execute("SELECT sum(x) FROM lk.default.t").rows == [(3,)]

    def test_drop_catalog_keeps_others(self, runner):
        runner.execute("CREATE CATALOG a USING memory")
        runner.execute("CREATE CATALOG b USING memory")
        runner.execute("DROP CATALOG a")
        assert ("b",) in runner.execute("SHOW CATALOGS").rows
