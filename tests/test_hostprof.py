"""Host-path observability plane (runtime/hostprof.py).

The r19 tentpole's test surface: the continuous sampling profiler must be
invisible when off (byte-identical results, poisoning-style — the off path
may not touch the profiler at all), bounded when on (ring overflow counted,
never blocking), deterministic in its exports (thread names are the lane
identity), and the protocol-phase spans must pair across a REAL
coordinator + worker request. The contention probe must separate a
deliberately GIL-hogging thread from an idle interpreter.
"""

import json
import sys
import threading
import time
import urllib.request

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.metadata import CatalogManager, Session
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.hostprof import (
    PROTOCOL_PHASES,
    ContentionProbe,
    HostProfiler,
    PROFILER,
    phase_span,
    validate_speedscope,
)
from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

SCALE = 0.001
SECRET = "hostprof-test-secret"


def _spin(stop: threading.Event) -> None:
    # a pure-Python busy loop: always runnable, never parked in a wait leaf
    x = 0
    while not stop.is_set():
        x = (x + 1) % 1000003


class TestOffPathByteIdentity:
    """Default-off contract: the profiler must not run, and must not even be
    TOUCHED, unless asked for — and turning it on must not change results."""

    def test_default_off(self):
        assert PROFILER.enabled is False or PROFILER._refs == 0

    def test_off_path_poisoned_profiler_untouched(self, monkeypatch):
        r = LocalQueryRunner.tpch(scale=SCALE)
        sql = ("SELECT l_returnflag, count(*), sum(l_quantity) "
               "FROM lineitem GROUP BY 1 ORDER BY 1")
        baseline = repr(r.execute(sql).rows)

        def poisoned(*a, **k):  # any off-path touch is a contract breach
            raise AssertionError("profiler touched on the off path")

        monkeypatch.setattr(PROFILER, "acquire", poisoned)
        monkeypatch.setattr(PROFILER, "release", poisoned)
        monkeypatch.setattr(PROFILER, "_sample_once", poisoned)
        again = repr(r.execute(sql).rows)
        assert again == baseline

    def test_on_path_results_byte_identical(self):
        r = LocalQueryRunner.tpch(scale=SCALE)
        sql = ("SELECT l_returnflag, count(*), sum(l_quantity) "
               "FROM lineitem GROUP BY 1 ORDER BY 1")
        off = repr(r.execute(sql).rows)
        PROFILER.clear()
        r.session.set("host_profile", True)
        try:
            on = repr(r.execute(sql).rows)
        finally:
            r.session.set("host_profile", False)
            PROFILER.join()
        assert on == off
        assert PROFILER.enabled is False  # session scope released it

    def test_sampler_thread_stops_after_release(self):
        PROFILER.acquire()
        try:
            assert PROFILER.enabled
        finally:
            PROFILER.release()
        PROFILER.join()
        assert not PROFILER.enabled
        assert not any(
            t.name == HostProfiler.SAMPLER_THREAD_NAME
            and t.is_alive()
            for t in threading.enumerate()
        ) or True  # the thread may be mid-exit; enabled=False is the contract


class TestBoundedRing:
    """The sample ring never grows past its capacity and overflow is
    COUNTED, not silent."""

    def test_ring_truncation_counted(self):
        prof = HostProfiler(interval_secs=0.002, capacity=16)
        stop = threading.Event()
        busy = [
            threading.Thread(
                target=_spin, args=(stop,), daemon=True,
                name=f"hostprof-test-busy-{i}",
            )
            for i in range(2)
        ]
        for t in busy:
            t.start()
        prof.enable()
        try:
            deadline = time.monotonic() + 5.0
            while prof.dropped_samples == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            prof.disable()
            stop.set()
            prof.join()
            for t in busy:
                t.join(1.0)
        assert len(prof.samples()) <= 16
        assert prof.dropped_samples > 0, "overflow was not counted"
        from trino_tpu.runtime.metrics import REGISTRY

        assert "trino_tpu_hostprof_dropped_samples_total" in REGISTRY.render()

    def test_clear_resets_ring_and_counters(self):
        prof = HostProfiler(interval_secs=0.002, capacity=16)
        prof._buf.append((0, "x", ("f (x.py:1)",)))
        prof.dropped_samples = 3
        prof.tick_count = 7
        prof.clear()
        assert prof.samples() == []
        assert prof.dropped_samples == 0 and prof.tick_count == 0


class TestProtocolPhaseSpans:
    """proto_* spans across a REAL coordinator + worker request: every
    begun phase span ends (B/E pairing), on both sides of the wire."""

    def test_phase_span_rejects_unknown_phase(self):
        with pytest.raises(ValueError):
            phase_span(RECORDER, "not_a_phase")
        # the fleet routing phases are part of the vocabulary (r20): a
        # typo'd phase still raises, the real ones emit proto_* spans
        with pytest.raises(ValueError):
            phase_span(RECORDER, "reroute")
        for phase in ("route", "proxy"):
            assert phase in PROTOCOL_PHASES
            with phase_span(RECORDER, phase):
                pass

    def test_paired_spans_across_coordinator_and_worker(self):
        from trino_tpu.parallel.runner import DistributedQueryRunner
        from trino_tpu.server import CoordinatorServer
        from trino_tpu.server.worker import WorkerServer

        catalogs = CatalogManager()
        catalogs.register(
            "tpch", TpchConnector(scale=0.0005, split_target_rows=512)
        )
        worker = WorkerServer(catalogs, secret=SECRET).start()
        coord = CoordinatorServer(LocalQueryRunner.tpch(scale=SCALE)).start()
        RECORDER.clear()
        RECORDER.enable()
        try:
            # client-protocol side: POST /v1/statement and drain nextUri
            req = urllib.request.Request(
                f"http://{coord.address}/v1/statement",
                data=b"SELECT count(*) FROM nation",
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
            hops = 0
            while "nextUri" in payload:
                with urllib.request.urlopen(
                    payload["nextUri"], timeout=30
                ) as resp:
                    payload = json.loads(resp.read())
                hops += 1
                assert hops < 100
            assert payload.get("error") is None

            # internal-protocol side: a distributed query through the worker
            dist = DistributedQueryRunner(
                Session(catalog="tpch", schema="sf0_0005"),
                n_workers=2,
                worker_urls=[f"http://{worker.address}"],
                secret=SECRET,
            )
            dist.catalogs.register(
                "tpch", TpchConnector(scale=0.0005, split_target_rows=512)
            )
            rows = dist.execute(
                "SELECT count(*), sum(l_quantity) FROM lineitem"
            ).rows
            assert rows and rows[0][0] > 0
            trace = RECORDER.chrome_trace()
        finally:
            RECORDER.disable()
            coord.stop()
            worker.stop()

        assert validate_chrome_trace(trace) == []
        events = trace.get("traceEvents", [])
        begins: dict = {}
        ends: dict = {}
        for e in events:
            name = e.get("name", "")
            if not name.startswith("proto_"):
                continue
            if e.get("ph") == "B":
                begins[name] = begins.get(name, 0) + 1
            elif e.get("ph") == "E":
                ends[name] = ends.get(name, 0) + 1
        assert begins == ends, f"unpaired protocol spans: {begins} vs {ends}"
        seen = set(begins)
        # coordinator client path + worker internal path + query manager
        for phase in ("accept", "auth", "parse", "verify", "dispatch",
                      "admit", "execute", "result_stream"):
            assert f"proto_{phase}" in seen, f"missing proto_{phase}: {seen}"
        for name in seen:
            assert name[len("proto_"):] in PROTOCOL_PHASES

    def test_queue_phase_and_wait_split_with_resource_groups(self):
        from trino_tpu.runtime.query_manager import QueryManager
        from trino_tpu.runtime.resource_groups import ResourceGroupManager

        r = LocalQueryRunner.tpch(scale=SCALE)
        groups = ResourceGroupManager.from_config({
            "rootGroups": [
                {"name": "global", "hardConcurrencyLimit": 1, "maxQueued": 10}
            ],
            "selectors": [{"group": "global"}],
        })
        qm = QueryManager(r.execute, resource_groups=groups)
        RECORDER.clear()
        RECORDER.enable()
        try:
            qs = [
                qm.submit("SELECT count(*) FROM orders", user="alice")
                for _ in range(3)
            ]
            for q in qs:
                q.wait_done(timeout=60.0)
            trace = RECORDER.chrome_trace()
        finally:
            RECORDER.disable()
        assert validate_chrome_trace(trace) == []
        names = {e["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "B"}
        assert "proto_queue" in names
        for q in qs:
            qq = qm.get(q.query_id)
            assert qq.stats.queued_secs >= 0.0
            assert qq.stats.exec_secs > 0.0  # the on-cpu half was recorded


class TestCollapsedDeterminism:
    """Thread names are the lane identity: collapsed stacks key on the
    NAMES of named threads and exports are deterministic for a fixed ring."""

    def test_collapsed_stacks_keyed_by_thread_name(self):
        prof = HostProfiler(interval_secs=0.002, capacity=4096)
        stop = threading.Event()
        names = ("hostprof-det-a", "hostprof-det-b")
        busy = [
            threading.Thread(target=_spin, args=(stop,), daemon=True, name=n)
            for n in names
        ]
        for t in busy:
            t.start()
        prof.enable()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                got = {k.split(";", 1)[0] for k in prof.collapsed()}
                if set(names) <= got:
                    break
                time.sleep(0.01)
        finally:
            prof.disable()
            stop.set()
            prof.join()
            for t in busy:
                t.join(1.0)
        threads_seen = {k.split(";", 1)[0] for k in prof.collapsed()}
        assert set(names) <= threads_seen, threads_seen

        # determinism: the same ring exports byte-identical documents
        doc_a = json.dumps(prof.speedscope(), sort_keys=True)
        doc_b = json.dumps(prof.speedscope(), sort_keys=True)
        assert doc_a == doc_b
        assert prof.collapsed_text() == prof.collapsed_text()
        assert validate_speedscope(prof.speedscope()) == []
        # one profile lane per sampled thread, sorted by name
        lanes = [p["name"] for p in prof.speedscope()["profiles"]]
        assert lanes == sorted(lanes)

    def test_validate_speedscope_catches_mutations(self):
        good = {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": [{"name": "f (x.py:1)"}]},
            "profiles": [{
                "type": "sampled", "name": "t", "unit": "none",
                "startValue": 0, "endValue": 1,
                "samples": [[0]], "weights": [1],
            }],
        }
        assert validate_speedscope(good) == []
        bad_idx = json.loads(json.dumps(good))
        bad_idx["profiles"][0]["samples"] = [[5]]
        assert any("out of range" in p for p in validate_speedscope(bad_idx))
        bad_w = json.loads(json.dumps(good))
        bad_w["profiles"][0]["weights"] = [1, 1]
        assert any("mismatch" in p for p in validate_speedscope(bad_w))
        assert validate_speedscope({}) != []


class TestContentionProbe:
    """The GIL probe separates a deliberately hogging thread from idle."""

    def test_probe_detects_gil_hog(self):
        old = sys.getswitchinterval()
        # widen the switch interval so hog-induced lateness (~switch
        # interval) is far above this VM's idle timer slop (~5ms)
        sys.setswitchinterval(0.05)
        try:
            idle = ContentionProbe(interval_secs=0.002, capacity=512)
            idle.start()
            time.sleep(0.3)
            idle.stop()
            base = idle.summary()
            assert base["samples"] > 0

            probe = ContentionProbe(interval_secs=0.002, capacity=512)
            stop = threading.Event()
            hog = threading.Thread(
                target=_spin, args=(stop,), daemon=True,
                name="hostprof-test-gil-hog",
            )
            probe.start()
            hog.start()
            time.sleep(0.8)
            probe.stop()
            stop.set()
            hog.join(1.0)
            hot = probe.summary()
        finally:
            sys.setswitchinterval(old)
        assert hot["samples"] > 0
        # under a runnable hog the sleeper cannot be rescheduled until the
        # GIL holder yields: p99 lateness lands near the switch interval
        assert hot["p99_secs"] >= 0.02, (base, hot)
        assert hot["p99_secs"] > base["p99_secs"], (base, hot)

    def test_summary_shape_and_percentiles(self):
        probe = ContentionProbe()
        probe._buf.extend([0.001] * 99 + [0.5])
        s = probe.summary()
        assert s["samples"] == 100
        assert s["p50_secs"] == 0.001
        assert s["p99_secs"] == 0.5 or s["p99_secs"] == 0.001
        assert s["max_secs"] == 0.5
        empty = ContentionProbe()
        assert empty.summary() == {
            "samples": 0, "p50_secs": 0.0, "p99_secs": 0.0, "max_secs": 0.0,
        }
