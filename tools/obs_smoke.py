#!/usr/bin/env python
"""Observability smoke check (tier-1): one TPC-H query, flight recorder on.

Runs a small TPC-H join query with the pipeline flight recorder enabled,
exports the Chrome/Perfetto trace JSON via tools/query_trace.py, and
validates it against the minimal schema contract:

- monotonic timestamps per (pid, tid) track
- paired B/E duration events (no unclosed/unopened spans)
- every event's pid/tid declared by process_name/thread_name metadata
- the events the plane promises are actually present (operator or bucket
  spans, and an XLA compile on a cold cache)

Exit code 0 = pass. Wired into the tier-1 suite as a fast test
(tests/test_observability.py::TestSmokeCheck) and runnable standalone:

    JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

SMOKE_SQL = """
SELECT n.n_name, count(*) AS suppliers
FROM supplier s JOIN nation n ON s.s_nationkey = n.n_nationkey
GROUP BY n.n_name
ORDER BY suppliers DESC, n.n_name
LIMIT 5
"""


def _registry_help_problems(required=()):
    """Shared HELP lint (registry-contract half) from the engine lint suite
    (tools/lint/rules.py) — the single implementation the per-plane copies
    collapsed into."""
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from tools.lint.rules import registry_help_problems

    return registry_help_problems(required=required)


def run_smoke(scale: float = 0.001, ooc: bool = False) -> List[str]:
    """Returns a list of problems; [] means the smoke check passed."""
    import os

    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import query_trace  # sibling module

    trace, stats, rows = query_trace.run_query_trace(
        SMOKE_SQL, scale=scale, ooc=ooc
    )
    problems = query_trace.validate(trace)
    if rows == 0:
        problems.append("smoke query returned no rows")
    events = trace.get("traceEvents", [])
    cats = {e.get("cat") for e in events}
    if not ({"operator", "bucket"} & cats):
        problems.append(
            f"no operator/bucket spans recorded (cats={sorted(c for c in cats if c)})"
        )
    if ooc and "prefetch" not in cats and "transfer" not in cats:
        problems.append("ooc run recorded no prefetch/transfer events")
    return problems


def run_system_smoke(scale: float = 0.001) -> List[str]:
    """System-catalog smoke: the engine can query its own runtime state.

    Runs queries THROUGH a QueryManager (so system.runtime.queries has live
    + historical rows), then checks that

    - ``SELECT state, count(*) FROM system.runtime.queries GROUP BY 1``
      returns rows matching the declared schema (varchar state, bigint
      count) including the RUNNING scan itself and a FINISHED entry, and
    - a ``system.runtime.flight_events`` query under the recorder returns
      rows matching the declared schema (varchar kind, bigint dur).

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER
    from trino_tpu.runtime.query_manager import QueryManager, QueryState

    problems: List[str] = []
    runner = LocalQueryRunner.tpch(scale=scale)
    mgr = QueryManager(runner.execute)
    warm = mgr.submit("SELECT count(*) FROM nation")
    warm.wait_done(120)
    if warm.state is not QueryState.FINISHED:
        return [f"warm-up query did not finish: {warm.state} {warm.error}"]

    q = mgr.submit(
        "SELECT state, count(*) FROM system.runtime.queries GROUP BY 1"
    )
    q.wait_done(120)
    if q.state is not QueryState.FINISHED:
        problems.append(f"queries scan failed: {q.error}")
    else:
        if not q.rows:
            problems.append("system.runtime.queries returned no rows")
        bad = [
            r for r in q.rows
            if not isinstance(r[0], str) or not isinstance(r[1], int)
        ]
        if bad:
            problems.append(f"queries rows off-schema: {bad}")
        states = dict(q.rows)
        if not states.get("FINISHED"):
            problems.append("no FINISHED query visible in history")
        if not states.get("RUNNING"):
            problems.append("the scan did not see itself RUNNING")

    RECORDER.enable()
    try:
        mgr.submit("SELECT count(*) FROM supplier").wait_done(120)
    finally:
        RECORDER.disable()
    fq = mgr.submit(
        "SELECT kind, cat, dur FROM system.runtime.flight_events "
        "ORDER BY dur DESC"
    )
    fq.wait_done(120)
    if fq.state is not QueryState.FINISHED:
        problems.append(f"flight_events scan failed: {fq.error}")
    else:
        if not fq.rows:
            problems.append("flight_events returned no rows under recorder")
        bad = [
            r for r in fq.rows
            if not isinstance(r[0], str) or not isinstance(r[2], int)
        ]
        if bad:
            problems.append(f"flight_events rows off-schema: {bad[:3]}")
    return problems


def run_exchange_smoke(scale: float = 0.001) -> List[str]:
    """Exchange data-plane smoke: a repartitioned TPC-H join under the flight
    recorder must leave a valid Perfetto export in which the plane's three
    stages — ``repartition_kernel`` (device epilogue), ``serde_encode``
    (sliced v2 frames), ``exchange_flush`` (coalesced sink writes) — appear
    as PAIRED B/E spans on monotonic tracks, so the observability plane can
    attribute the exchange win end to end.

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    runner, sql = _fte_smoke_runner(scale)
    RECORDER.clear()
    RECORDER.enable()
    try:
        rows = runner.execute(sql).rows
    finally:
        RECORDER.disable()
    if not rows or not rows[0][0]:
        problems.append(f"exchange smoke join returned {rows!r}")
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    for name in ("repartition_kernel", "serde_encode", "exchange_flush"):
        b = sum(1 for e in events if e.get("name") == name and e.get("ph") == "B")
        e_ = sum(1 for e in events if e.get("name") == name and e.get("ph") == "E")
        if not b:
            problems.append(f"no {name} span in the exchange trace")
        elif b != e_:
            problems.append(f"{name} spans unpaired: {b} B vs {e_} E")
    return problems


def _fte_smoke_runner(scale: float):
    """Shared smoke shape for the FTE-tier checks: a 2-worker distributed
    runner under retry_policy=TASK, pinned to the repartitioned join shape
    (smoke data is tiny — AUTO would broadcast, and the stats-derived
    partition-count target would collapse the hash stage to one part)."""
    from trino_tpu.parallel.runner import DistributedQueryRunner

    runner = DistributedQueryRunner.tpch(scale=scale, n_workers=2)
    runner.session.set("retry_policy", "TASK")
    runner.session.set("join_distribution_type", "PARTITIONED")
    runner.session.set("target_partition_rows", 500)
    sql = "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey"
    return runner, sql


def run_fte_smoke(scale: float = 0.001) -> List[str]:
    """FTE control-plane smoke: a distributed query under an INJECTED task
    failure must recover via the event-driven scheduler, leaving a valid
    Perfetto export in which ``task_attempt`` spans are PAIRED/monotonic
    with outcome labels on their close events (a failed attempt followed by
    a higher-numbered ok attempt of the same task), and the retry counter
    (``trino_tpu_task_retries_total``) incremented.

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.runtime.failure import ChaosInjector
    from trino_tpu.runtime.metrics import REGISTRY
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    runner, sql = _fte_smoke_runner(scale)
    retries = REGISTRY.counter(
        "trino_tpu_task_retries_total",
        help="FTE task retries after classified retryable failures",
    )
    before = retries.value
    RECORDER.clear()
    RECORDER.enable()
    try:
        with ChaosInjector() as chaos:
            chaos.arm("task_crash_mid_execute", times=1)
            rows = runner.execute(sql).rows
    finally:
        RECORDER.disable()
    if not rows or not rows[0][0]:
        problems.append(f"fte smoke join returned {rows!r}")
    if chaos.fired.get("task_crash_mid_execute", 0) != 1:
        problems.append("chaos harness never fired the mid-execute crash")
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    begins = [
        e for e in events
        if e.get("name") == "task_attempt" and e.get("ph") == "B"
    ]
    ends = [
        e for e in events
        if e.get("name") == "task_attempt" and e.get("ph") == "E"
    ]
    if not begins:
        problems.append("no task_attempt span in the FTE trace")
    elif len(begins) != len(ends):
        problems.append(
            f"task_attempt spans unpaired: {len(begins)} B vs {len(ends)} E"
        )
    outcomes = [(e.get("args") or {}).get("outcome") for e in ends]
    if any(o not in ("ok", "failed") for o in outcomes):
        problems.append(f"task_attempt E events missing outcome labels: {outcomes}")
    # per-task attempt numbers must be monotonic, and the injected failure
    # must show as failed attempt N -> ok attempt > N for the SAME task.
    # Key by the task TEXT (query id + fragment + partition): a leftover
    # attempt thread from an earlier query in this process must not collide
    # with this run's (fragment, partition) numbering
    by_task = {}
    for e in begins:
        args = e.get("args") or {}
        task = str(args.get("task") or "")
        key = (task.rsplit("_a", 1)[0],
               args.get("fragment"), args.get("partition"))
        by_task.setdefault(key, []).append(int(args.get("attempt", -1)))
    if any(a != sorted(set(a)) for a in by_task.values()):
        problems.append(f"task attempt numbers not monotonic: {by_task}")
    if not any(len(a) > 1 for a in by_task.values()):
        problems.append("no task shows a retried attempt in the trace")
    if retries.value <= before:
        problems.append(
            "trino_tpu_task_retries_total did not increment under injected failure"
        )
    return problems


def run_memory_smoke() -> List[str]:
    """Memory-arbitration smoke: the three new flight events —
    ``memory_reserve_blocked`` (backpressure), ``memory_revoke`` (spill
    escalation), ``low_memory_kill`` (the killer) — must appear as PAIRED
    B/E spans on monotonic tracks in one deterministic exercise of the pool,
    and the new Prometheus counters (``trino_tpu_memory_blocked_queries``,
    ``trino_tpu_low_memory_kills_total``, ``trino_tpu_revoked_bytes_total``)
    must be registered with HELP text (the existing HELP lint contract).

    Single-threaded by design: blocked reservers drive the arbiter
    themselves (runtime/memory.py), so one thread exercises block -> revoke
    -> kill without races. Returns a list of problems; [] = pass.
    """
    from trino_tpu.runtime.memory import (
        AggregatedMemoryContext,
        ClusterMemoryManager,
        MemoryPool,
    )
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    RECORDER.clear()
    RECORDER.enable()
    try:
        pool = MemoryPool(1000, name="smoke", reserve_timeout=10)
        killed: List[str] = []
        ClusterMemoryManager(
            pool,
            kill_fn=lambda q, r: (killed.append(q), pool.free_owner(q)),
            spill_after=0.0, kill_after=0.05,
        )
        # qa parks 600 revocable bytes behind a revoker
        ctx_a = AggregatedMemoryContext(pool=pool, owner="qa")
        parked = ctx_a.new_local("parked", revocable=True)
        parked.set_bytes(600)

        class Revoker:
            def revoke(self, nbytes):
                freed = parked.get_bytes()
                parked.set_bytes(0)
                return freed

        revoker = Revoker()
        pool.add_revoker(revoker)
        # qb wants 700: blocks (600+700 > 1000) -> arbiter REVOKES qa -> fits
        AggregatedMemoryContext(pool=pool, owner="qb").new_local("op").set_bytes(700)
        # qc wants 700: blocks, nothing revocable left -> the KILLER sheds qb
        AggregatedMemoryContext(pool=pool, owner="qc").new_local("op").set_bytes(700)
        if killed != ["qb"]:
            problems.append(f"killer shed {killed!r}, expected ['qb']")
    finally:
        RECORDER.disable()
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    for name in ("memory_reserve_blocked", "memory_revoke", "low_memory_kill"):
        b = sum(1 for e in events if e.get("name") == name and e.get("ph") == "B")
        e_ = sum(1 for e in events if e.get("name") == name and e.get("ph") == "E")
        if not b:
            problems.append(f"no {name} span in the memory trace")
        elif b != e_:
            problems.append(f"{name} spans unpaired: {b} B vs {e_} E")
    outcomes = [
        (e.get("args") or {}).get("outcome")
        for e in events
        if e.get("name") == "memory_reserve_blocked" and e.get("ph") == "E"
    ]
    if "granted" not in outcomes:
        problems.append(
            f"no blocked reservation was granted (outcomes={outcomes})"
        )
    problems += _registry_help_problems(required=(
        "trino_tpu_memory_blocked_queries",
        "trino_tpu_low_memory_kills_total",
        "trino_tpu_revoked_bytes_total",
        "trino_tpu_memory_reserve_blocked_total",
    ))
    return problems


def run_stats_smoke(scale: float = 0.001) -> List[str]:
    """Statistics-feedback-plane smoke: a deliberately mis-estimated query
    under the flight recorder must leave a valid Perfetto export with a
    PAIRED ``stats_feedback`` span (monotonic per track, like every event)
    containing ``cardinality_misestimate`` instants; the per-node actuals
    must be queryable through a schema-checked
    ``system.runtime.operator_stats``; and the q-error metrics plus the
    ``system.metrics.histograms`` p50/p95/p99 interpolation columns must be
    registered with HELP text and ordered sanely.

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    runner = LocalQueryRunner.tpch(scale=scale)
    # any q-error > 1 counts as a mis-estimate: the LIKE filter below is a
    # guaranteed misestimate (unknown-selectivity coefficient vs near-zero
    # actual), so events fire deterministically
    runner.session.set("qerror_threshold", 1.0)
    RECORDER.clear()
    RECORDER.enable()
    try:
        rows = runner.execute(
            "SELECT count(*) FROM orders "
            "WHERE o_comment LIKE '%no such comment ever%'"
        ).rows
    finally:
        RECORDER.disable()
    if not rows:
        problems.append(f"stats smoke query returned {rows!r}")
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    b = sum(1 for e in events
            if e.get("name") == "stats_feedback" and e.get("ph") == "B")
    e_ = sum(1 for e in events
             if e.get("name") == "stats_feedback" and e.get("ph") == "E")
    if not b:
        problems.append("no stats_feedback span in the trace")
    elif b != e_:
        problems.append(f"stats_feedback spans unpaired: {b} B vs {e_} E")
    mis = [e for e in events if e.get("name") == "cardinality_misestimate"]
    if not mis:
        problems.append("no cardinality_misestimate event under a forced "
                        "misestimate")
    for ev in mis:
        args = ev.get("args") or {}
        if args.get("q") is None or args.get("actual") is None:
            problems.append(f"misestimate event missing q/actual: {args}")

    # per-node actuals are SQL-queryable and on-schema
    res = runner.execute(
        "SELECT plan_node, actual_rows, q_error "
        "FROM system.runtime.operator_stats"
    )
    if not res.rows:
        problems.append("system.runtime.operator_stats returned no rows")
    bad = [
        r for r in res.rows
        if not isinstance(r[0], str) or not isinstance(r[1], int)
        or not (r[2] is None or isinstance(r[2], float))
    ]
    if bad:
        problems.append(f"operator_stats rows off-schema: {bad[:3]}")
    if not any(r[2] is not None and r[2] > 1.0 for r in res.rows):
        problems.append("no operator_stats row carries the misestimate q-error")
    hist = runner.execute(
        "SELECT actual_rows FROM system.optimizer.stats_history"
    )
    if not hist.rows:
        problems.append("system.optimizer.stats_history returned no rows")

    # histogram quantile columns: monotone p50 <= p95 <= p99 on a populated
    # series (the q-error histogram the run above observed into)
    q = runner.execute(
        "SELECT p50, p95, p99 FROM system.metrics.histograms "
        "WHERE name = 'trino_tpu_cardinality_qerror' AND count > 0"
    )
    if not q.rows:
        problems.append("q-error histogram missing from system.metrics.histograms")
    for p50, p95, p99 in q.rows:
        if p50 is None or p95 is None or p99 is None:
            problems.append(f"NULL quantile on a populated histogram: "
                            f"{(p50, p95, p99)}")
            break
        if not (p50 <= p95 <= p99):
            problems.append(f"quantiles not monotone: {(p50, p95, p99)}")
            break

    # HELP lint (shared rule): trino_tpu_flight_dropped_events_total is NOT
    # required — it registers on first overflow and absence is healthy; when
    # present the shared rule covers its HELP text like every other series
    problems += _registry_help_problems(required=(
        "trino_tpu_cardinality_misestimates_total",
        "trino_tpu_cardinality_qerror",
    ))
    return problems


def run_cache_smoke(scale: float = 0.001) -> List[str]:
    """Warm-path cache plane smoke (runtime/cachestore.py): a warm-up /
    hit / invalidate cycle under the flight recorder must leave a valid
    Perfetto export with PAIRED ``cache_lookup``/``cache_store``/
    ``cache_invalidate`` spans (monotonic per track) carrying hit/miss
    outcomes on the E-event args; the tier counters must be registered
    with HELP text; and ``system.runtime.caches`` must be on-schema.

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.cachestore import CACHES
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    runner = LocalQueryRunner.tpch(scale=scale)
    runner.register_catalog("mem", MemoryConnector())
    runner.execute("CREATE TABLE mem.default.kv (x bigint)")
    runner.execute("INSERT INTO mem.default.kv VALUES (1), (2)")
    runner.session.set("result_cache", True)
    runner.session.set("plan_cache_size", 16)
    runner.session.set("fragment_cache", True)
    CACHES.clear()
    RECORDER.clear()
    RECORDER.enable()
    try:
        q = "SELECT count(*) FROM mem.default.kv"
        r1 = runner.execute(q)  # cold: misses, then stores
        r2 = runner.execute(q)  # warm: result-tier hit
        runner.execute("INSERT INTO mem.default.kv VALUES (3)")  # invalidate
        r3 = runner.execute(q)  # fresh data, never the stale entry
    finally:
        RECORDER.disable()
    if r1.rows != [(2,)] or r2.rows != [(2,)] or r3.rows != [(3,)]:
        problems.append(
            f"cache smoke rows wrong: {r1.rows} {r2.rows} {r3.rows}"
        )
    if (r2.query_stats or {}).get("cacheHitTier") != "result":
        problems.append("warm run not tagged cacheHitTier=result")
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    for name in ("cache_lookup", "cache_store", "cache_invalidate"):
        b = sum(1 for e in events
                if e.get("name") == name and e.get("ph") == "B")
        e_ = sum(1 for e in events
                 if e.get("name") == name and e.get("ph") == "E")
        if not b:
            problems.append(f"no {name} span in the trace")
        elif b != e_:
            problems.append(f"{name} spans unpaired: {b} B vs {e_} E")
    outcomes = {
        (e.get("args") or {}).get("outcome")
        for e in events
        if e.get("name") == "cache_lookup" and e.get("ph") == "E"
    }
    if not {"hit", "miss"} <= outcomes:
        problems.append(f"cache_lookup outcomes incomplete: {outcomes}")
    stored = [
        e for e in events
        if e.get("name") == "cache_store" and e.get("ph") == "E"
        and (e.get("args") or {}).get("outcome") == "stored"
    ]
    if not stored:
        problems.append("no cache_store span with outcome=stored")

    # the plane's snapshot table is on-schema and saw the traffic
    res = runner.execute(
        "SELECT tier, entries, bytes, hits, misses, evictions, invalidations "
        "FROM system.runtime.caches"
    )
    tiers = {r[0] for r in res.rows}
    if tiers != {"plan", "result", "fragment"}:
        problems.append(f"system.runtime.caches tiers off: {tiers}")
    bad = [
        r for r in res.rows
        if not isinstance(r[0], str)
        or not all(isinstance(v, int) for v in r[1:])
    ]
    if bad:
        problems.append(f"system.runtime.caches rows off-schema: {bad[:3]}")
    if not any(r[0] == "result" and r[3] >= 1 for r in res.rows):
        problems.append("result tier shows no hit after the warm run")

    # HELP lint (shared rule); trino_tpu_cache_evictions_total registers on
    # first eviction, so it is help-checked when present but not required
    problems += _registry_help_problems(required=(
        "trino_tpu_cache_hits_total",
        "trino_tpu_cache_misses_total",
        "trino_tpu_cache_invalidations_total",
    ))
    CACHES.clear()
    return problems


def run_batching_smoke(scale: float = 0.001) -> List[str]:
    """Device-batching-plane smoke (runtime/device_scheduler.py): a burst
    of concurrent identical queries with ``device_batching=on`` under the
    flight recorder must leave a valid Perfetto export with PAIRED
    ``batch_admit``/``batch_launch``/``batch_demux`` spans (lane count,
    packed rows, and the launch key on the E-args), results bit-identical
    to the serial run, the lane-occupancy/batched-fragments/program-launch
    metrics registered with HELP text, and at least one shared-scan hit.

    Returns a list of problems; [] means the smoke check passed.
    """
    import threading

    from trino_tpu.runtime.device_scheduler import SCHEDULER
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    sql = (
        "SELECT l_returnflag, sum(l_quantity), count(*) "
        "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
    )
    runner = LocalQueryRunner.tpch(scale=scale)
    serial = runner.execute(sql).rows
    runner.session.set("device_batching", True)
    runner.session.set("batch_admit_window_ms", 25.0)
    runner.execute(sql)  # warm compiles so the burst overlaps
    results: List[Optional[list]] = [None] * 4
    errors: List[BaseException] = []
    # a 1-core box can stagger the burst so badly nothing overlaps; the
    # smoke checks the PLANE's artifacts, not this host's scheduler, so
    # retry the burst until some dedup tier engaged (bounded attempts)
    for _ in range(3):
        SCHEDULER.reset_stats()
        RECORDER.clear()
        RECORDER.enable()
        try:
            results = [None] * 4
            errors = []

            def go(i: int) -> None:
                try:
                    results[i] = runner.execute(sql).rows
                except BaseException as e:  # noqa: BLE001 — reported below
                    errors.append(e)

            threads = [
                threading.Thread(
                    target=go, args=(i,), name=f"smoke-client-{i}"
                )
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            RECORDER.disable()
        if errors or SCHEDULER.subsumed >= 1 or SCHEDULER.batched_launches >= 1:
            break
    if errors:
        problems.append(f"batched burst raised: {errors[:2]}")
    if any(r != serial for r in results if r is not None):
        problems.append("batched results not bit-identical to serial run")
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    for name in ("batch_admit", "batch_launch", "batch_demux"):
        b = sum(1 for e in events
                if e.get("name") == name and e.get("ph") == "B")
        e_ = sum(1 for e in events
                 if e.get("name") == name and e.get("ph") == "E")
        if not b:
            problems.append(f"no {name} span in the trace")
        elif b != e_:
            problems.append(f"{name} spans unpaired: {b} B vs {e_} E")
    launches = [
        (e.get("args") or {})
        for e in events
        if e.get("name") == "batch_launch" and e.get("ph") == "E"
    ]
    if not any(
        a.get("lanes") and a.get("packed_rows") and a.get("key")
        for a in launches
    ):
        problems.append(
            f"batch_launch E-args missing lanes/packed_rows/key: {launches[:3]}"
        )
    multi_lane = any((a.get("lanes") or 0) >= 2 for a in launches)
    if not multi_lane and SCHEDULER.subsumed < 1:
        # identical concurrent queries normally SUBSUME (whole-subtree
        # single-flight) before they would pack; either dedup tier counts
        problems.append(
            "concurrent burst neither packed a multi-lane launch nor "
            "subsumed a fragment"
        )
    if SCHEDULER.scan_shares < 1:
        problems.append(
            f"no shared-scan elimination in the burst "
            f"(shares={SCHEDULER.scan_shares})"
        )
    problems += _registry_help_problems(required=(
        "trino_tpu_device_programs_total",
        "trino_tpu_batched_fragments_total",
        "trino_tpu_batch_lane_occupancy",
        "trino_tpu_shared_scan_hits_total",
    ))
    return problems


def run_megakernel_smoke(scale: float = 0.001) -> List[str]:
    """Megakernel-plane smoke (ops/megakernels.py): a join-heavy query with
    ``pallas_fusion=on`` under the flight recorder must leave a valid
    Perfetto export with PAIRED ``pallas_compile``/``pallas_launch`` spans
    (shape class + fused-op list on the E-args), results bit-identical to
    the serial run, strictly fewer device program launches than serial, and
    the launch/fallback counters registered with HELP text.

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.ops import megakernels as MK
    from trino_tpu.runtime.device_scheduler import program_launches
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    sql = (
        "SELECT n_name, sum(l_extendedprice), count(*) "
        "FROM lineitem "
        "JOIN orders ON l_orderkey = o_orderkey "
        "JOIN customer ON o_custkey = c_custkey "
        "JOIN nation ON c_nationkey = n_nationkey "
        "GROUP BY n_name ORDER BY n_name"
    )
    runner = LocalQueryRunner.tpch(scale=scale)
    n0 = program_launches()
    serial = runner.execute(sql).rows
    serial_launches = program_launches() - n0
    runner.session.set("pallas_fusion", True)
    # a shape that can never fuse, so the fallback counter family registers
    runner.execute("SELECT count(*) FROM nation, region")
    MK.on_pallas_fallback("smoke_probe")
    RECORDER.clear()
    RECORDER.enable()
    try:
        p0 = MK.pallas_launches()
        n0 = program_launches()
        fused = runner.execute(sql).rows
        fused_launches = program_launches() - n0
        fused_pallas = MK.pallas_launches() - p0
    finally:
        RECORDER.disable()
    if fused != serial:
        problems.append("fused results not bit-identical to serial run")
    if fused_pallas < 1:
        problems.append("pallas_fusion=on launched no megakernels")
    if not fused_launches < serial_launches:
        problems.append(
            f"fused path did not dispatch strictly fewer device programs "
            f"({fused_launches} vs serial {serial_launches})"
        )
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    for name in ("pallas_compile", "pallas_launch"):
        b = sum(1 for e in events
                if e.get("name") == name and e.get("ph") == "B")
        e_ = sum(1 for e in events
                 if e.get("name") == name and e.get("ph") == "E")
        if not b:
            problems.append(f"no {name} span in the trace")
        elif b != e_:
            problems.append(f"{name} spans unpaired: {b} B vs {e_} E")
    launches = [
        (e.get("args") or {})
        for e in events
        if e.get("name") == "pallas_launch" and e.get("ph") == "E"
    ]
    if not any(
        a.get("shape_class") and a.get("fused_ops") for a in launches
    ):
        problems.append(
            f"pallas_launch E-args missing shape_class/fused_ops: "
            f"{launches[:3]}"
        )
    if not any(
        "partial_agg" in str(a.get("fused_ops") or "") for a in launches
    ):
        problems.append(
            "no join->partial-agg fused launch in a Q5-shape query"
        )
    problems += _registry_help_problems(required=(
        "trino_tpu_pallas_launches_total",
        "trino_tpu_pallas_fallbacks_total",
        "trino_tpu_device_programs_total",
    ))
    return problems


def run_tensor_smoke(rows: int = 64, dim: int = 8) -> List[str]:
    """Tensor-plane smoke (ops/tensor.py): a vector top-k query with
    ``tensor_plane``/``vector_topk_fusion`` on, under the flight recorder,
    must leave a valid Perfetto export with PAIRED ``vector_kernel`` and
    ``topk_fusion`` spans carrying rows/dim (and k) on their E-args, fused
    results bit-identical to the serial project+sort pair, strictly fewer
    device program launches, and the launch/fallback counters registered
    with HELP text.

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.ops import tensor as T
    from trino_tpu.runtime.device_scheduler import program_launches
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    runner = LocalQueryRunner.tpch(scale=0.001)
    runner.register_catalog("memory", MemoryConnector())
    runner.execute(
        f"CREATE TABLE memory.default.tensor_smoke (id bigint, v vector({dim}))"
    )
    values = ", ".join(
        "({}, ARRAY[{}])".format(
            i, ", ".join(f"{((i * 7 + j * 3) % 11) / 10.0}" for j in range(dim))
        )
        for i in range(rows)
    )
    runner.execute(f"INSERT INTO memory.default.tensor_smoke VALUES {values}")
    q = ", ".join("1.0" if j % 2 == 0 else "0.25" for j in range(dim))
    sql = (
        "SELECT id FROM memory.default.tensor_smoke "
        f"ORDER BY cosine_similarity(v, ARRAY[{q}]) DESC, id LIMIT 5"
    )
    serial = runner.execute(sql).rows
    runner.session.set("tensor_plane", True)
    runner.session.set("vector_topk_fusion", True)
    # register the fallback counter family so the HELP lint sees it
    T.on_topk_fallback("smoke_probe")
    RECORDER.clear()
    RECORDER.enable()
    try:
        v0 = T.vector_launches()
        n0 = program_launches()
        fused = runner.execute(sql).rows
        fused_launches = program_launches() - n0
        fused_vector = T.vector_launches() - v0
        n0 = program_launches()
        runner.session.set("vector_topk_fusion", False)
        serial2 = runner.execute(sql).rows
        serial_launches = program_launches() - n0
    finally:
        RECORDER.disable()
        runner.session.set("tensor_plane", False)
        runner.session.set("vector_topk_fusion", False)
    if fused != serial or serial2 != serial:
        problems.append("fused results not bit-identical to the serial pair")
    if fused_vector < 1:
        problems.append("fusion-on run booked no vector kernel launches")
    if not fused_launches < serial_launches:
        problems.append(
            f"fused path did not dispatch strictly fewer device programs "
            f"({fused_launches} vs serial {serial_launches})"
        )
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    for name in ("vector_kernel", "topk_fusion"):
        b = sum(1 for e in events
                if e.get("name") == name and e.get("ph") == "B")
        e_ = sum(1 for e in events
                 if e.get("name") == name and e.get("ph") == "E")
        if not b:
            problems.append(f"no {name} span in the trace")
        elif b != e_:
            problems.append(f"{name} spans unpaired: {b} B vs {e_} E")
    fusions = [
        (e.get("args") or {})
        for e in events
        if e.get("name") == "topk_fusion" and e.get("ph") == "E"
    ]
    if not any(
        a.get("rows") and a.get("dim") == dim and a.get("k") == 5
        for a in fusions
    ):
        problems.append(
            f"topk_fusion E-args missing rows/dim/k: {fusions[:3]}"
        )
    problems += _registry_help_problems(required=(
        "trino_tpu_vector_kernel_launches_total",
        "trino_tpu_vector_topk_fallbacks_total",
        "trino_tpu_device_programs_total",
    ))
    return problems


def run_ha_smoke(scale: float = 0.001) -> List[str]:
    """Serving-fabric-plane smoke: one deterministic exercise of the HA
    primitives under the flight recorder must leave paired
    ``leader_lease`` / ``dispatch_replay`` / ``worker_drain`` spans on
    monotonic tracks, a crash->resume round trip bit-identical to the
    uninterrupted run, and the new counters
    (``trino_tpu_failovers_total`` / ``trino_tpu_lease_renewals_total`` /
    ``trino_tpu_recovery_torn_records_total``) registered with HELP text.
    Returns a list of problems; [] = pass."""
    import os
    import tempfile
    import time

    from trino_tpu.parallel.runner import DistributedQueryRunner
    from trino_tpu.runtime.failure import ChaosInjector
    from trino_tpu.runtime.ha import (
        CoordinatorCrashError,
        DispatchJournal,
        LeaderLease,
        ScaleController,
        orphaned_journals,
        resume_fte_query,
    )
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    RECORDER.clear()
    RECORDER.enable()
    tmp = tempfile.mkdtemp(prefix="ha_smoke_")
    try:
        # --- leader lease: acquire, renew, chaos expiry, fenced takeover
        primary = LeaderLease(os.path.join(tmp, "ha"), "primary", ttl=0.2)
        standby = LeaderLease(os.path.join(tmp, "ha"), "standby", ttl=0.2)
        if not primary.acquire() or not primary.is_leader():
            problems.append("primary failed to acquire a free lease")
        if standby.acquire():
            problems.append("standby acquired a HELD lease (two leaders)")
        if not primary.renew():
            problems.append("holder renewal failed")
        with ChaosInjector() as chaos:
            chaos.arm("lease_expire", times=1)
            if primary.renew():
                problems.append("lease_expire chaos did not forfeit renewal")
        if primary.is_leader():
            problems.append("forfeited holder still believes it leads")
        time.sleep(0.25)
        if not standby.acquire() or standby.epoch != 2:
            problems.append("standby takeover failed after lease expiry")

        # --- dispatch handoff: crash mid-query, standby replays the journal
        exdir = os.path.join(tmp, "exchange")

        def make_runner():
            r = DistributedQueryRunner.tpch(scale=scale, n_workers=2)
            r.session.set("retry_policy", "TASK")
            r.session.set("fte_exchange_dir", exdir)
            r.session.set("ha_plane", True)
            return r

        oracle = make_runner().execute(SMOKE_SQL).rows
        with ChaosInjector() as chaos:
            chaos.arm("coordinator_crash", times=1, match="_post")
            try:
                make_runner().execute(SMOKE_SQL)
                problems.append("coordinator_crash chaos did not fire")
            except CoordinatorCrashError:
                pass
        orphans = orphaned_journals(exdir)
        if len(orphans) != 1:
            problems.append(f"expected 1 orphaned journal, found {len(orphans)}")
        else:
            resumed = resume_fte_query(make_runner(), orphans[0])
            if resumed.rows != oracle:
                problems.append("resumed result differs from the oracle run")

        # --- torn-tail recovery: a kill-mid-append journal reads clean
        torn_path = os.path.join(tmp, "torn", "journal.jsonl")
        j = DispatchJournal(torn_path)
        j.append({"kind": "begin", "query_id": "qt", "sql": "SELECT 1"})
        with open(torn_path, "a") as f:
            f.write('{"kind": "stage_done", "fid"')  # the torn tail
        records, torn = DispatchJournal.read(torn_path)
        if len(records) != 1 or torn != 1:
            problems.append(
                f"torn-tail read returned {len(records)} records / {torn} torn"
            )

        # --- elastic drain: a graceful scale-down emits worker_drain
        retired: List[str] = []
        ctl = ScaleController(retire=retired.append, min_workers=0)
        ctl.workers.append("http://127.0.0.1:9")
        if not ctl.drain("http://127.0.0.1:9", wait_secs=0.5):
            problems.append("idle worker did not drain clean")
        if retired != ["http://127.0.0.1:9"]:
            problems.append(f"drain did not retire the worker: {retired}")
    finally:
        RECORDER.disable()
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    for name in ("leader_lease", "dispatch_replay", "worker_drain"):
        b = sum(1 for e in events if e.get("name") == name and e.get("ph") == "B")
        e_ = sum(1 for e in events if e.get("name") == name and e.get("ph") == "E")
        if not b:
            problems.append(f"no {name} span in the ha trace")
        elif b != e_:
            problems.append(f"{name} spans unpaired: {b} B vs {e_} E")
    outcomes = [
        (e.get("args") or {}).get("outcome")
        for e in events
        if e.get("name") == "leader_lease" and e.get("ph") == "E"
    ]
    if "acquired" not in outcomes:
        problems.append(f"no lease acquisition recorded (outcomes={outcomes})")
    problems += _registry_help_problems(required=(
        "trino_tpu_failovers_total",
        "trino_tpu_lease_renewals_total",
        "trino_tpu_recovery_torn_records_total",
    ))
    return problems


def run_objectstore_smoke(scale: float = 0.001) -> List[str]:
    """Object-store substrate smoke (runtime/objectstore.py): the durable
    planes — leader lease, dispatch journal, shared warm tier, durable
    exchange — run on the rename-free object backend with the store chaos
    sites armed (throttles retry, torn puts disambiguate by re-reading the
    key, a lagging LIST only delays discovery), a killed coordinator
    resumes bit-identical to the oracle, every request leaves a paired
    ``object_store_request`` span, and the four
    ``trino_tpu_object_store_*_total`` counters are registered with HELP
    text. Returns a list of problems; [] = pass."""
    import tempfile
    import time

    from trino_tpu.fs import Location
    from trino_tpu.parallel.runner import DistributedQueryRunner
    from trino_tpu.runtime.failure import ChaosInjector
    from trino_tpu.runtime.ha import (
        CoordinatorCrashError,
        LeaderLease,
        SharedCacheTier,
        orphaned_journals,
        resume_fte_query,
    )
    from trino_tpu.runtime.metrics import REGISTRY
    from trino_tpu.runtime.objectstore import REQUESTS_HELP, backend_for_root
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    RECORDER.clear()
    RECORDER.enable()
    tmp = tempfile.mkdtemp(prefix="objstore_smoke_")
    base = "object://" + tmp
    requests = REGISTRY.counter(
        "trino_tpu_object_store_requests_total", help=REQUESTS_HELP
    )
    n0 = requests.value
    try:
        exdir = f"{base}/exchange"

        def make_runner():
            r = DistributedQueryRunner.tpch(scale=scale, n_workers=2)
            r.session.set("retry_policy", "TASK")
            r.session.set("fte_exchange_dir", exdir)
            r.session.set("ha_plane", True)
            return r

        oracle = make_runner().execute(SMOKE_SQL).rows

        # --- the conditional-put primitive: exactly one If-None-Match win
        # (also guarantees the cas_conflicts counter exists for the lint)
        fs, _ = backend_for_root(f"{base}/probe")
        if not fs.write_if_absent(Location("object", "probe"), b"a"):
            problems.append("first If-None-Match claim lost on a fresh key")
        if fs.write_if_absent(Location("object", "probe"), b"b"):
            problems.append("duplicate If-None-Match claim succeeded")

        # --- lease takeover + warm tier with the store misbehaving
        with ChaosInjector() as chaos:
            chaos.arm("object_store_throttle", times=3)
            chaos.arm("object_store_torn_put", times=2)
            primary = LeaderLease(f"{base}/ha", "primary", ttl=0.2)
            standby = LeaderLease(f"{base}/ha", "standby", ttl=0.2)
            if not primary.acquire() or not primary.is_leader():
                problems.append("primary failed to acquire the object lease")
            if standby.acquire():
                problems.append("standby acquired a HELD object lease")
            time.sleep(0.25)  # the primary "pauses" past its TTL
            if not standby.acquire() or standby.epoch != 2:
                problems.append("standby takeover failed on the object lease")
            tier = SharedCacheTier(f"{base}/warm")
            tier.publish("k1", {"rows": [[1, 2]]})
            got = tier.get("k1")
            if not got or got.get("rows") != [[1, 2]]:
                problems.append(f"object warm-tier round trip failed: {got!r}")
            for site in ("object_store_throttle", "object_store_torn_put"):
                if not chaos.fired.get(site):
                    problems.append(f"{site} chaos never fired")

        # --- crash -> resume entirely over the object exchange
        with ChaosInjector() as chaos:
            chaos.arm("coordinator_crash", times=1, match="_post")
            chaos.arm("object_store_list_lag", times=1)
            try:
                make_runner().execute(SMOKE_SQL)
                problems.append("coordinator_crash chaos did not fire")
            except CoordinatorCrashError:
                pass
            orphans = orphaned_journals(exdir)
            if not orphans:
                # the armed LIST lagged and hid the journal; per-key reads
                # stay strong, so one re-scan converges
                orphans = orphaned_journals(exdir)
            if len(orphans) != 1:
                problems.append(
                    f"expected 1 orphaned object journal, found {len(orphans)}"
                )
            else:
                resumed = resume_fte_query(make_runner(), orphans[0])
                if resumed.rows != oracle:
                    problems.append(
                        "object-substrate resume differs from the oracle run"
                    )
    finally:
        RECORDER.disable()
    trace = RECORDER.chrome_trace()
    RECORDER.clear()
    problems += validate_chrome_trace(trace)  # paired B/E + monotonic tracks
    events = trace.get("traceEvents", [])
    b = sum(
        1 for e in events
        if e.get("name") == "object_store_request" and e.get("ph") == "B"
    )
    e_ = sum(
        1 for e in events
        if e.get("name") == "object_store_request" and e.get("ph") == "E"
    )
    if not b:
        problems.append("no object_store_request span in the trace")
    elif b != e_:
        problems.append(f"object_store_request spans unpaired: {b} B vs {e_} E")
    outcomes = {
        (e.get("args") or {}).get("outcome")
        for e in events
        if e.get("name") == "object_store_request" and e.get("ph") == "E"
    }
    if "ok" not in outcomes:
        problems.append(
            "no successful object request recorded "
            f"(outcomes={sorted(o for o in outcomes if o)})"
        )
    if not ({"throttled", "timeout", "recovered"} & outcomes):
        problems.append("chaos left no throttled/timeout/recovered outcome")
    if requests.value <= n0:
        problems.append("trino_tpu_object_store_requests_total never moved")
    problems += _registry_help_problems(required=(
        "trino_tpu_object_store_requests_total",
        "trino_tpu_object_store_retries_total",
        "trino_tpu_object_store_throttles_total",
        "trino_tpu_object_store_cas_conflicts_total",
    ))
    return problems


def run_cluster_smoke(scale: float = 0.001) -> List[str]:
    """Cluster observability plane smoke (runtime/clusterobs.py): two
    leased coordinators + two REAL WorkerServers on one substrate. An FTE
    query killed mid-run by ``coordinator_crash`` chaos and resumed by the
    standby (epoch 2) must yield ONE merged Perfetto trace — the
    coordinator segment plus both workers' ``/v1/flightrecorder?query_id=``
    segments pulled over the signed wire, skew-aligned by announcement-
    clock offsets — with >=2 worker lanes carrying task spans, paired B/E
    on monotonic tracks, ``task_attempt`` spans from BOTH leader epochs,
    and dispatch-journal markers on their own lane. The federated
    exposition must pass the HELP lint with per-node labels, and the
    persisted query profile's stage breakdown must sum to within 5% of the
    resumed run's wall time. Returns a list of problems; [] = pass.
    """
    import json as _json
    import os
    import tempfile
    import time
    import urllib.request

    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.metadata import CatalogManager, Session
    from trino_tpu.parallel.runner import DistributedQueryRunner
    from trino_tpu.runtime import clusterobs
    from trino_tpu.runtime.clusterobs import (
        ClockSync,
        ClusterMetrics,
        assemble_cluster_trace,
        build_profile,
        profile_breakdown_secs,
    )
    from trino_tpu.runtime.failure import ChaosInjector
    from trino_tpu.runtime.ha import (
        CoordinatorCrashError,
        DispatchJournal,
        LeaderLease,
        orphaned_journals,
        resume_fte_query,
    )
    from trino_tpu.runtime.metrics import REGISTRY
    from trino_tpu.runtime.observability import (
        RECORDER,
        FlightRecorder,
        validate_chrome_trace,
    )
    from trino_tpu.server.worker import SIGNATURE_HEADER, WorkerServer, sign

    problems: List[str] = []
    secret = "cluster-obs-smoke"
    sql = "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey"
    tmp = tempfile.mkdtemp(prefix="cluster_obs_smoke_")
    exdir = os.path.join(tmp, "exchange")
    profdir = os.path.join(tmp, "profiles")
    schema = "sf" + f"{scale:g}".replace(".", "_")

    def catalogs():
        c = CatalogManager()
        c.register("tpch", TpchConnector(scale=scale, split_target_rows=512))
        return c

    # two REAL workers, each with its OWN flight ring (per-node segments —
    # in production each process's global ring is naturally per-node)
    workers = [WorkerServer(catalogs(), secret=secret).start() for _ in range(2)]
    for w in workers:
        w.tasks.recorder = FlightRecorder()
        w.tasks.recorder.enable()

    def make_runner(lease):
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema=schema), n_workers=2,
            worker_urls=[f"http://{w.address}" for w in workers],
            secret=secret,
        )
        r.catalogs.register(
            "tpch", TpchConnector(scale=scale, split_target_rows=512)
        )
        r.session.set("retry_policy", "TASK")
        r.session.set("join_distribution_type", "PARTITIONED")
        r.session.set("target_partition_rows", 500)
        r.session.set("fte_exchange_dir", exdir)
        r.session.set("ha_plane", True)
        r.session.set("cluster_obs", True)
        r.ha_lease = lease
        return r

    old_env = {
        k: os.environ.get(k)
        for k in ("TRINO_TPU_CLUSTER_OBS", "TRINO_TPU_QUERY_PROFILE_DIR")
    }
    os.environ["TRINO_TPU_CLUSTER_OBS"] = "1"
    os.environ["TRINO_TPU_QUERY_PROFILE_DIR"] = profdir
    RECORDER.clear()
    RECORDER.enable()
    try:
        lease_a = LeaderLease(os.path.join(tmp, "ha"), "coord-a", ttl=0.2)
        lease_b = LeaderLease(os.path.join(tmp, "ha"), "coord-b", ttl=0.2)
        if not lease_a.acquire() or lease_a.epoch != 1:
            problems.append("primary coordinator failed to take epoch 1")
        with ChaosInjector() as chaos:
            chaos.arm("coordinator_crash", times=1, match="_post")
            try:
                make_runner(lease_a).execute(sql)
                problems.append("coordinator_crash chaos did not fire")
            except CoordinatorCrashError:
                pass
        time.sleep(0.25)  # the dead leader's lease lapses
        if not lease_b.acquire() or lease_b.epoch != 2:
            problems.append("standby coordinator failed to take epoch 2")

        orphans = orphaned_journals(exdir)
        if len(orphans) != 1:
            problems.append(f"expected 1 orphaned journal, got {len(orphans)}")
            return problems
        rb = make_runner(lease_b)
        t0 = time.monotonic()
        result = resume_fte_query(rb, orphans[0])
        wall = time.monotonic() - t0
        if not result.rows or not result.rows[0][0]:
            problems.append(f"resumed query returned {result.rows!r}")

        # ---------------- cross-node trace assembly (real wire path). The
        # journal copy rides the result's stats bundle (the on-disk journal
        # is cleaned up with the query's exchange directory on success).
        journal_records = (result.query_stats or {}).get("journal") or []
        if not journal_records:
            problems.append("resumed result carries no journal copy")
            journal_records, _ = DispatchJournal.read(orphans[0])
        qid = next(
            (str(r.get("query_id")) for r in journal_records
             if r.get("kind") == "begin"), "",
        )
        if not qid:
            problems.append("journal has no begin record with a query id")
        epochs_seen = {r.get("epoch") for r in journal_records}
        if not {1, 2} <= epochs_seen:
            problems.append(
                f"journal records span epochs {sorted(epochs_seen)}, "
                "expected both 1 and 2"
            )
        segments = {"coordinator": clusterobs.local_segment([qid])}
        clock = ClockSync()
        cm = ClusterMetrics()
        for i, w in enumerate(workers):
            node = f"worker-{i}"
            rel = "/v1/flightrecorder"
            req = urllib.request.Request(
                f"http://{w.address}{rel}?query_id={qid}", method="GET"
            )
            req.add_header(SIGNATURE_HEADER, sign(secret, "GET", rel))
            with urllib.request.urlopen(req, timeout=10) as resp:
                payload = _json.loads(resp.read())
            segments[node] = payload.get("trace") or {}
            # announcement riders feed clock sync + the federated fold
            # (the same payload shape a PUT /v1/announcement carries)
            body = w.announcement_body()
            if not isinstance(body.get("metrics"), list):
                problems.append(f"{node} announcement missing metrics rider")
            if clock.observe_announcement(node, body.get("clock")) is None:
                problems.append(f"{node} announcement missing clock rider")
            cm.ingest(node, body.get("metrics") or [])
        trace = assemble_cluster_trace(
            segments, offsets=clock.offsets(), journal_records=journal_records
        )
        problems += validate_chrome_trace(trace)  # paired B/E + monotonic
        events = trace.get("traceEvents", [])
        lanes = {
            e["pid"]: (e.get("args") or {}).get("name")
            for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        worker_pids = {p for p, n in lanes.items()
                       if str(n).startswith("worker-")}
        task_pids = {
            e["pid"] for e in events
            if e.get("name") == "task" and e.get("ph") == "B"
        }
        if len(worker_pids & task_pids) < 2:
            problems.append(
                f"merged trace has {len(worker_pids & task_pids)} worker "
                "lanes with task spans, need >= 2"
            )
        epochs = {
            (e.get("args") or {}).get("epoch")
            for e in events
            if e.get("name") == "task_attempt" and e.get("ph") == "B"
        }
        epochs.discard(None)
        if not {1, 2} <= epochs:
            problems.append(
                f"merged trace missing spans from both leader epochs: "
                f"{sorted(epochs)}"
            )
        if not any(e.get("cat") == "journal" for e in events):
            problems.append("no dispatch-journal markers in the merged trace")

        # ---------------- federated exposition: HELP lint + node labels
        text = cm.render(local_registry=REGISTRY)
        fams = [ln.split()[2] for ln in text.splitlines()
                if ln.startswith("# TYPE ")]
        helped = {ln.split()[2] for ln in text.splitlines()
                  if ln.startswith("# HELP ")}
        unhelped = [f for f in fams if f not in helped]
        if unhelped:
            problems.append(
                f"cluster exposition families missing HELP: {unhelped[:5]}"
            )
        for node in ("worker-0", "worker-1", "coordinator"):
            if f'node="{node}"' not in text:
                problems.append(f"cluster exposition missing node label {node}")
        problems += _registry_help_problems()

        # ---------------- persisted profile: schema + sums-to-wall
        qs = result.query_stats or {}
        if not qs.get("stages"):
            problems.append("resumed result carries no stage breakdown")
        store = clusterobs.profile_store()
        if store is None:
            problems.append("profile store not configured under env gate")
            return problems
        store.write(build_profile(
            qid, sql, state="FINISHED", wall_secs=wall, query_stats=qs,
        ))
        profile = store.read(qid)
        if profile is None:
            problems.append("profile bundle not readable after write")
            return problems
        required_keys = {
            "version", "queryId", "query", "state", "wallSecs", "stages",
            "phases", "times", "counts", "operators", "planNodes", "cache",
            "retries", "blacklist", "diagnosis",
        }
        missing = required_keys - set(profile)
        if missing:
            problems.append(f"profile schema missing keys: {sorted(missing)}")
        breakdown = profile_breakdown_secs(profile)
        if wall > 0 and abs(breakdown - wall) > 0.05 * wall:
            problems.append(
                f"profile stage breakdown {breakdown:.4f}s vs wall "
                f"{wall:.4f}s drifts past 5%"
            )
        if not profile.get("diagnosis"):
            problems.append("profile missing the dominant-cost diagnosis")
        if not profile.get("retries"):
            problems.append("profile missing the retry/attempt history")
    finally:
        RECORDER.disable()
        RECORDER.clear()
        for w in workers:
            w.stop()
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return problems


def run_vector_serving_smoke(rows: int = 96, dim: int = 8) -> List[str]:
    """Vector-serving-plane smoke (device_scheduler vector lanes + the IVF
    ANN tier): a burst of concurrent vector top-k statements differing only
    in their query constant, with ``vector_query_batching`` on, must coalesce
    into stacked launches (strictly fewer device programs than the serial
    replay, results bit-identical per query) and leave PAIRED
    ``vector_batch_launch`` spans carrying lanes/rows/dim/k; an
    ``ann_mode=approx`` probe over an IVF index must leave a PAIRED
    ``ann_probe`` span, advance the pruned-splits counter, and deposit an
    on-schema ``system.runtime.ann_recall`` row; the three serving counters
    must pass the HELP lint.

    Returns a list of problems; [] means the smoke check passed.
    """
    import tempfile
    import threading

    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.vector_index import IvfVectorConnector
    from trino_tpu.fs import FileSystemManager, LocalFileSystem
    from trino_tpu.ops import tensor as T
    from trino_tpu.runtime.device_scheduler import SCHEDULER, program_launches
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace
    from trino_tpu.spi.connector import ColumnMetadata, SchemaTableName
    from trino_tpu.spi.types import BIGINT, vector_type

    problems: List[str] = []
    runner = LocalQueryRunner.tpch(scale=0.001)
    runner.register_catalog("memory", MemoryConnector())
    runner.execute(
        f"CREATE TABLE memory.default.serving_smoke (id bigint, v vector({dim}))"
    )
    values = ", ".join(
        "({}, ARRAY[{}])".format(
            i, ", ".join(f"{((i * 7 + j * 3) % 11) / 10.0}" for j in range(dim))
        )
        for i in range(rows)
    )
    runner.execute(f"INSERT INTO memory.default.serving_smoke VALUES {values}")

    def sql_for(qi: int) -> str:
        q = ", ".join(
            f"{((qi * 5 + j * 2) % 9) / 8.0 + 0.125}" for j in range(dim)
        )
        return (
            "SELECT id FROM memory.default.serving_smoke "
            f"ORDER BY cosine_similarity(v, ARRAY[{q}]) DESC, id LIMIT 5"
        )

    lanes = 4
    runner.session.set("tensor_plane", True)
    runner.session.set("vector_topk_fusion", True)
    try:
        serial = []
        n0 = program_launches()
        for i in range(lanes):
            serial.append(runner.execute(sql_for(i)).rows)
        serial_launches = program_launches() - n0

        runner.session.set("device_batching", True)
        runner.session.set("vector_query_batching", True)
        runner.session.set("batch_admit_window_ms", 25.0)
        results: List[Optional[list]] = [None] * lanes
        errors: List[BaseException] = []
        burst_launches = 0
        # a 1-core box can stagger the burst so badly nothing overlaps; the
        # smoke checks the PLANE's artifacts, not this host's scheduler, so
        # retry the burst until a stacked launch engaged (bounded attempts)
        for _ in range(3):
            SCHEDULER.reset_stats()
            RECORDER.clear()
            RECORDER.enable()
            try:
                results = [None] * lanes
                errors = []
                n0 = program_launches()

                def go(i: int) -> None:
                    try:
                        results[i] = runner.execute(sql_for(i)).rows
                    except BaseException as e:  # noqa: BLE001 — reported below
                        errors.append(e)

                threads = [
                    threading.Thread(
                        target=go, args=(i,), name=f"smoke-lane-{i}"
                    )
                    for i in range(lanes)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                burst_launches = program_launches() - n0
            finally:
                RECORDER.disable()
            if errors or SCHEDULER.vector_batched_launches >= 1:
                break
        if errors:
            problems.append(f"batched vector burst raised: {errors[:2]}")
        for i in range(lanes):
            if results[i] is not None and results[i] != serial[i]:
                problems.append(
                    f"batched lane {i} not bit-identical to its serial run"
                )
                break
        if SCHEDULER.vector_batched_launches < 1:
            problems.append("burst packed no stacked vector launch")
        elif not burst_launches < serial_launches:
            problems.append(
                f"batched burst did not dispatch strictly fewer device "
                f"programs ({burst_launches} vs serial {serial_launches})"
            )
        trace = RECORDER.chrome_trace()
        RECORDER.clear()
        problems += validate_chrome_trace(trace)
        events = trace.get("traceEvents", [])
        b = sum(1 for e in events
                if e.get("name") == "vector_batch_launch" and e.get("ph") == "B")
        e_ = sum(1 for e in events
                 if e.get("name") == "vector_batch_launch" and e.get("ph") == "E")
        if not b:
            problems.append("no vector_batch_launch span in the trace")
        elif b != e_:
            problems.append(
                f"vector_batch_launch spans unpaired: {b} B vs {e_} E"
            )
        stacked = [
            (e.get("args") or {})
            for e in events
            if e.get("name") == "vector_batch_launch" and e.get("ph") == "E"
        ]
        if not any(
            a.get("lanes") and a.get("rows") and a.get("dim") == dim
            and a.get("k") == 5
            for a in stacked
        ):
            problems.append(
                f"vector_batch_launch E-args missing lanes/rows/dim/k: "
                f"{stacked[:3]}"
            )

        # ------------------------------------------------ ANN index tier
        tmp = tempfile.mkdtemp(prefix="ivf_smoke_")
        fsm = FileSystemManager()
        fsm.register("local", lambda: LocalFileSystem(tmp))
        ivf = IvfVectorConnector(fsm, "local://ivf")
        rng = np.random.RandomState(11)
        idx_rows = [
            (i, np.round(rng.uniform(-1, 1, size=dim), 6).tolist())
            for i in range(rows)
        ]
        ivf.build_index(
            SchemaTableName("default", "emb"),
            [ColumnMetadata("id", BIGINT), ColumnMetadata("v", vector_type(dim))],
            idx_rows,
            "v",
            n_clusters=6,
        )
        runner.register_catalog("vec", ivf)
        ann_sql = (
            "SELECT id FROM vec.default.emb "
            "ORDER BY cosine_similarity(v, ARRAY["
            + ", ".join(f"{(j % 5) / 4.0 - 0.4}" for j in range(dim))
            + "]) DESC, id LIMIT 5"
        )
        runner.session.set("device_batching", False)
        runner.session.set("vector_query_batching", False)
        exact = runner.execute(ann_sql).rows
        runner.session.set("ann_mode", "approx(nprobe=2)")
        runner.session.set("ann_recall_sample_rate", 1.0)
        p0 = T.ann_pruned_splits()
        s0 = T.ann_recall_samples()
        RECORDER.clear()
        RECORDER.enable()
        try:
            runner.execute(ann_sql)
        finally:
            RECORDER.disable()
        if not T.ann_pruned_splits() > p0:
            problems.append("ann probe pruned no splits")
        if not T.ann_recall_samples() > s0:
            problems.append("ann recall oracle drew no sample")
        trace = RECORDER.chrome_trace()
        RECORDER.clear()
        problems += validate_chrome_trace(trace)
        events = trace.get("traceEvents", [])
        b = sum(1 for e in events
                if e.get("name") == "ann_probe" and e.get("ph") == "B")
        e_ = sum(1 for e in events
                 if e.get("name") == "ann_probe" and e.get("ph") == "E")
        if not b:
            problems.append("no ann_probe span in the trace")
        elif b != e_:
            problems.append(f"ann_probe spans unpaired: {b} B vs {e_} E")
        recall_rows = T.ann_recall_rows()
        if not recall_rows:
            problems.append("system.runtime.ann_recall ring is empty")
        else:
            r = recall_rows[-1]
            ok = (
                len(r) == 6
                and isinstance(r[0], str)
                and all(isinstance(x, int) for x in (r[1], r[2], r[4], r[5]))
                and isinstance(r[3], float)
                and 0.0 <= r[3] <= 1.0
                and r[4] <= r[5]
            )
            if not ok:
                problems.append(f"ann_recall row off-schema: {r!r}")
        runner.session.set("ann_mode", f"approx(nprobe=6)")
        full = runner.execute(ann_sql).rows
        if full != exact:
            problems.append("nprobe=n_clusters not bit-identical to exact")
    finally:
        for knob in (
            "tensor_plane", "vector_topk_fusion", "device_batching",
            "vector_query_batching", "batch_admit_window_ms", "ann_mode",
            "ann_recall_sample_rate",
        ):
            runner.session.properties.pop(knob, None)
    problems += _registry_help_problems(required=(
        "trino_tpu_vector_batched_queries_total",
        "trino_tpu_ann_pruned_splits_total",
        "trino_tpu_ann_recall_samples_total",
        "trino_tpu_device_programs_total",
    ))
    return problems


def run_kernelcost_smoke(scale: float = 0.001) -> List[str]:
    """Kernel cost plane smoke (runtime/kernelcost.py): EXPLAIN ANALYZE
    VERBOSE under the flight recorder must render a per-operator roofline
    diagnosis ("[kernel: flops ... -> memory-bound ...]"), leave a valid
    Perfetto export carrying ``hbm_watermark`` counter-track samples and
    paired ``kernel_cost`` spans, deposit on-schema
    ``system.runtime.kernel_costs`` rows, fold federated rows ingested
    under a worker id into the same table, and the trace validator must
    flag a counter event with a non-numeric sample (mutation check on the
    counter-track conformance rule itself).

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.runtime import kernelcost
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

    problems: List[str] = []
    # hermetic against a deployment cap store: a persisted .kernelcost
    # sibling file would satisfy attribution reads without lowering, and
    # the paired kernel_cost spans this smoke asserts on would never emit
    prev_store = os.environ.pop("TRINO_TPU_CAP_STORE", None)
    kernelcost.clear_ledger()
    kernelcost.clear_memory()  # force fresh lowers: kernel_cost spans emit
    runner = LocalQueryRunner.tpch(scale=scale)
    RECORDER.clear()
    RECORDER.enable()
    try:
        res = runner.execute(
            "EXPLAIN ANALYZE VERBOSE "
            "SELECT l_returnflag, sum(l_extendedprice) FROM lineitem "
            "WHERE l_quantity < 24 GROUP BY l_returnflag"
        )
        text = "\n".join(str(r[0]) for r in res.rows)
        trace = RECORDER.chrome_trace()
    finally:
        RECORDER.disable()
        if prev_store is not None:
            os.environ["TRINO_TPU_CAP_STORE"] = prev_store

    if "[kernel:" not in text:
        problems.append("EXPLAIN ANALYZE VERBOSE rendered no kernel cost line")
    if "-bound" not in text:
        problems.append("no roofline classification in EXPLAIN output")
    problems += [f"trace: {p}" for p in validate_chrome_trace(trace)]
    events = trace.get("traceEvents", [])
    counters = [e for e in events if e.get("ph") == "C"]
    if not counters:
        problems.append("no counter-track events recorded")
    elif not any(e.get("name") == "hbm_watermark" for e in counters):
        problems.append("no hbm_watermark counter track")
    span_names = {e.get("name") for e in events if e.get("ph") == "B"}
    if "kernel_cost" not in span_names:
        problems.append("no paired kernel_cost spans recorded")

    # mutation check: the validator must catch a non-numeric counter sample
    if events:
        data = [e for e in events if e.get("ph") != "M"]
        if data:
            donor = data[-1]
            bad_ev = {
                "name": "hbm_watermark", "cat": "kernelcost", "ph": "C",
                "ts": max(e["ts"] for e in data) + 1,
                "pid": donor["pid"], "tid": donor["tid"],
                "args": {"hbm_bytes": "not-a-number"},
            }
            mutated = {"traceEvents": events + [bad_ev]}
            if not validate_chrome_trace(mutated):
                problems.append(
                    "validator accepted a non-numeric counter sample"
                )

    rows = runner.execute(
        "SELECT node, plan_node, flops, classification, status "
        "FROM system.runtime.kernel_costs"
    ).rows
    if not rows:
        problems.append("system.runtime.kernel_costs returned no rows")
    bad = [
        r for r in rows
        if not isinstance(r[4], str)
        or (r[2] is not None and not isinstance(r[2], float))
    ]
    if bad:
        problems.append(f"kernel_costs rows off-schema: {bad[:3]}")

    # federated fold: rows ingested under a worker id surface with its node
    kernelcost.ingest_federated("smoke-worker", kernelcost.announcement_rows())
    fed = runner.execute(
        "SELECT node FROM system.runtime.kernel_costs"
    ).rows
    if not any(r[0] == "smoke-worker" for r in fed):
        problems.append("federated kernel-cost rows missing from the table")
    problems += _registry_help_problems()
    return problems


def run_hostprof_smoke(scale: float = 0.001) -> List[str]:
    """Host-path observability plane smoke (runtime/hostprof.py): the
    ``host_profile`` session property must scope the sampling profiler to
    the statement (refcounted, off afterwards), the sampler must capture
    collapsed stacks keyed by thread NAME, the speedscope export must pass
    its schema validator, protocol-phase spans (proto_admit/proto_execute
    through the QueryManager) must pair in a valid Perfetto trace, the
    ``system.runtime.host_profile`` table must serve on-schema rows, the
    ``trino_tpu_host_threads{state=}`` gauges must export, and the
    GIL-contention probe must produce a numeric jitter summary.

    Returns a list of problems; [] means the smoke check passed.
    """
    from trino_tpu.runtime.hostprof import (
        PROBE,
        PROFILER,
        update_thread_gauges,
        validate_speedscope,
    )
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.metrics import REGISTRY
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace
    from trino_tpu.runtime.query_manager import QueryManager

    problems: List[str] = []
    runner = LocalQueryRunner.tpch(scale=scale)
    PROFILER.clear()
    RECORDER.clear()
    RECORDER.enable()
    probe = PROBE
    probe.clear()
    probe.start()
    try:
        runner.session.set("host_profile", True)
        qm = QueryManager(runner.execute)
        q = qm.submit(
            "SELECT count(*), sum(l_quantity) FROM lineitem "
            "WHERE l_quantity < 24"
        )
        q.wait_done(timeout=60.0)
        # a second profiled statement keeps the sampler up long enough for
        # ticks at the default 19ms interval even on a warm plan
        runner.execute("SELECT count(*) FROM orders")
        trace = RECORDER.chrome_trace()
    finally:
        runner.session.set("host_profile", False)
        RECORDER.disable()
        probe.stop()
        PROFILER.join()

    if PROFILER.enabled:
        problems.append("profiler still enabled after the session released it")
    if PROFILER.tick_count == 0:
        problems.append("sampler took no ticks during profiled statements")
    collapsed = PROFILER.collapsed()
    if not collapsed:
        problems.append("no collapsed stacks captured")
    if any(not key.split(";")[0] for key in collapsed):
        problems.append("collapsed stack with an empty thread name")
    doc = PROFILER.speedscope()
    problems += [f"speedscope: {p}" for p in validate_speedscope(doc)]
    problems += [f"trace: {p}" for p in validate_chrome_trace(trace)]
    events = trace.get("traceEvents", [])
    begun = {e.get("name") for e in events if e.get("ph") == "B"}
    for want in ("proto_admit", "proto_execute"):
        if want not in begun:
            problems.append(f"no paired {want} protocol-phase span recorded")

    rows = runner.execute(
        "SELECT thread, stack, samples, share "
        "FROM system.runtime.host_profile"
    ).rows
    if not rows:
        problems.append("system.runtime.host_profile returned no rows")
    bad = [
        r for r in rows
        if not isinstance(r[0], str) or not isinstance(r[1], str)
        or not isinstance(r[2], int) or not isinstance(r[3], float)
    ]
    if bad:
        problems.append(f"host_profile rows off-schema: {bad[:3]}")

    update_thread_gauges()
    exposition = REGISTRY.render()
    for state in ("runnable", "blocked"):
        if f'trino_tpu_host_threads{{state="{state}"}}' not in exposition:
            problems.append(f"host thread gauge state={state} not exported")

    summary = probe.summary()
    if not summary.get("samples"):
        problems.append("contention probe recorded no sleep-jitter samples")
    elif not all(
        isinstance(summary.get(k), float)
        for k in ("p50_secs", "p99_secs", "max_secs")
    ):
        problems.append(f"contention probe summary off-schema: {summary}")
    problems += _registry_help_problems()
    return problems


def run_fleet_smoke(scale: float = 0.001) -> List[str]:
    """Active-active coordinator fleet smoke (runtime/fleet.py): a THREE
    coordinator fleet on one membership dir must converge, a non-owner must
    307 a statement to its owner (and the client must follow it to a
    correct result), killing an owner mid-run must lapse its heartbeat and
    reassign ONLY its hash range (survivor-owned keys keep their owner), a
    follower must serve a status-board read for the dead owner's query
    DURING the failover window, the dead owner's users must be served by a
    survivor afterwards, proto_route spans must pair in a valid Perfetto
    trace with a fleet_reassign span for the departure, and the fleet
    counters must pass the shared HELP lint.

    Returns a list of problems; [] means the smoke check passed.
    """
    import tempfile
    import time
    import urllib.error
    import urllib.request

    from trino_tpu.client.client import StatementClient
    from trino_tpu.runtime.fleet import partition_key
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace
    from trino_tpu.server.coordinator import CoordinatorServer

    problems: List[str] = []
    fleet_dir = tempfile.mkdtemp(prefix="fleet_smoke_")
    saved = {
        k: os.environ.get(k)
        for k in ("TRINO_TPU_FLEET_DIR", "TRINO_TPU_FLEET_HEARTBEAT_SECS")
    }
    os.environ["TRINO_TPU_FLEET_DIR"] = fleet_dir
    os.environ["TRINO_TPU_FLEET_HEARTBEAT_SECS"] = "0.2"
    RECORDER.clear()
    RECORDER.enable()
    coords: List[CoordinatorServer] = []
    trace = {}
    try:
        for nid in ("n1", "n2", "n3"):
            coords.append(
                CoordinatorServer(
                    LocalQueryRunner.tpch(scale=scale), node_id=nid
                ).start()
            )
        c1, _c2, c3 = coords
        deadline = time.time() + 5
        while time.time() < deadline:
            if len(c1.fleet.live_members(now=time.time())) == 3:
                break
            time.sleep(0.05)
        live = sorted(c1.fleet.live_members(now=time.time()))
        if live != ["n1", "n2", "n3"]:
            problems.append(f"fleet membership never converged: {live}")

        # one user per owner (the ring is deterministic, so scan)
        users = {}
        for i in range(96):
            user = f"user{i:02d}"
            owner = c1.fleet.owner_of(partition_key(user, ""))["node_id"]
            users.setdefault(owner, user)
            if len(users) == 3:
                break
        if len(users) != 3:
            problems.append(f"ring left a member without keys: {users}")
            return problems

        # partitioned admission: a statement for n3's user POSTed at n1
        # must 307 to n3 at the raw protocol level...
        req = urllib.request.Request(
            f"http://{c1.address}/v1/statement",
            data=b"SELECT count(*) FROM nation", method="POST",
            headers={"X-Trino-User": users["n3"]},
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            problems.append("non-owner served an owned statement (no 307)")
        except urllib.error.HTTPError as e:
            e.read()
            if e.code != 307:
                problems.append(f"non-owner answered {e.code}, wanted 307")
            elif e.headers.get("X-Trino-Fleet-Owner") != "n3":
                problems.append(
                    f"redirect named owner "
                    f"{e.headers.get('X-Trino-Fleet-Owner')}, wanted n3"
                )
        # ...and the client must follow it transparently
        cl = StatementClient(f"http://{c1.address}", user=users["n3"])
        res = cl.execute("SELECT count(*) FROM nation")
        if res.rows != [[25]]:
            problems.append(f"redirected statement wrong: {res.rows}")

        # pre-kill ownership snapshot for the reassignment check
        keys = [f"session:smoke{i:03d}@x" for i in range(120)]
        before = {k: c1.fleet.owner_of(k)["node_id"] for k in keys}
        if "n3" not in set(before.values()):
            keys.append(partition_key(users["n3"], ""))
            before[keys[-1]] = "n3"

        # mid-run owner kill: crash (no deregister — the membership record
        # must LAPSE via the heartbeat TTL, not be cleaned up)
        c3.stop(crash=True)
        deadline = time.time() + 5
        while time.time() < deadline:
            if "n3" not in c1.fleet.live_members(now=time.time()):
                break
            time.sleep(0.05)
        if "n3" in c1.fleet.live_members(now=time.time()):
            problems.append("crashed owner never lapsed from membership")

        # follower status read DURING failover: the dead owner's query
        # answered from a surviving coordinator's status board
        board = c1._fleet_board_status(res.query_id)
        if board is None:
            problems.append(
                "follower could not serve the dead owner's query status"
            )
        elif board.get("fleet_owner") != "n3":
            problems.append(f"status board off-owner: {board}")

        # the dead member's hash range reassigns; everyone else stays put.
        # owner_of reads the quarter-heartbeat membership cache, so poll
        # until the routing view converges (within ~a heartbeat) before
        # judging the final assignment.
        deadline = time.time() + 5
        while time.time() < deadline:
            after = {k: c1.fleet.owner_of(k)["node_id"] for k in keys}
            if "n3" not in set(after.values()):
                break
            time.sleep(0.05)
        moved_wrong = [
            k for k in keys
            if before[k] != "n3" and after[k] != before[k]
        ]
        still_dead = [k for k in keys if before[k] == "n3" and after[k] == "n3"]
        if moved_wrong:
            problems.append(
                f"survivor-owned keys moved on failover: {moved_wrong[:3]}"
            )
        if still_dead:
            problems.append(f"keys still owned by the dead member: {still_dead[:3]}")

        # the dead owner's users are now served by a survivor. The routing
        # ring is refreshed from a quarter-heartbeat cache, so a statement
        # landing inside that window can still chase a dead redirect —
        # failover clients retry, and so does the smoke.
        cl = StatementClient(f"http://{c1.address}", user=users["n3"])
        res2 = None
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                res2 = cl.execute("SELECT count(*) FROM region")
                break
            except OSError:
                time.sleep(0.1)
        if res2 is None:
            problems.append("post-failover statement never succeeded")
        elif res2.rows != [[5]]:
            problems.append(f"post-failover statement wrong: {res2.rows}")
        trace = RECORDER.chrome_trace()
    finally:
        for c in coords:
            try:
                c.stop()
            except Exception:
                pass
        RECORDER.disable()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    problems += [f"trace: {p}" for p in validate_chrome_trace(trace)]
    events = trace.get("traceEvents", [])
    begun = {e.get("name") for e in events if e.get("ph") == "B"}
    if "proto_route" not in begun:
        problems.append("no paired proto_route span recorded")
    if "fleet_reassign" not in begun:
        problems.append("no fleet_reassign span recorded for the departure")
    problems += _registry_help_problems(
        required=(
            "trino_tpu_fleet_heartbeats_total",
            "trino_tpu_fleet_routed_total",
            "trino_tpu_fleet_follower_reads_total",
            "trino_tpu_fleet_reassigns_total",
            "trino_tpu_protocol_queue_depth",
        )
    )
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ooc = bool(argv and "--ooc" in argv)
    problems = run_smoke(ooc=ooc)
    problems += [f"[system] {p}" for p in run_system_smoke()]
    problems += [f"[exchange] {p}" for p in run_exchange_smoke()]
    problems += [f"[fte] {p}" for p in run_fte_smoke()]
    problems += [f"[memory] {p}" for p in run_memory_smoke()]
    problems += [f"[stats] {p}" for p in run_stats_smoke()]
    problems += [f"[cache] {p}" for p in run_cache_smoke()]
    problems += [f"[batching] {p}" for p in run_batching_smoke()]
    problems += [f"[megakernel] {p}" for p in run_megakernel_smoke()]
    problems += [f"[tensor] {p}" for p in run_tensor_smoke()]
    problems += [f"[vector-serving] {p}" for p in run_vector_serving_smoke()]
    problems += [f"[ha] {p}" for p in run_ha_smoke()]
    problems += [f"[objectstore] {p}" for p in run_objectstore_smoke()]
    problems += [f"[cluster] {p}" for p in run_cluster_smoke()]
    problems += [f"[kernelcost] {p}" for p in run_kernelcost_smoke()]
    problems += [f"[hostprof] {p}" for p in run_hostprof_smoke()]
    problems += [f"[fleet] {p}" for p in run_fleet_smoke()]
    if problems:
        for p in problems:
            print(f"SMOKE FAIL: {p}", file=sys.stderr)
        return 1
    print("observability smoke check passed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
