"""Plan-assertion DSL: structural matchers over optimized plans.

The analogue of Trino's sql/planner/assertions/PlanMatchPattern — rule tests
assert the SHAPE of the optimized plan, not its string rendering, so tests
survive symbol renaming and formatting changes.

Usage:
    from tests.plan_assertions import P, assert_plan, assert_no_node
    plan = runner.plan_sql("SELECT ...")
    assert_plan(plan, P.output(P.topn(P.scan("lineitem"), count=10)))
    assert_no_node(plan, SortNode)

Matchers are anchored: ``P.filter(P.scan())`` requires a FilterNode whose
child is a TableScanNode. ``P.any_tree()`` skips any number of intermediate
single-child nodes, like PlanMatchPattern's ``anyTree``.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from trino_tpu.planner.plan import (
    AggregationNode,
    EnforceSingleRowNode,
    ExchangeNode,
    FilterNode,
    JoinNode,
    LimitNode,
    LogicalPlan,
    OutputNode,
    PlanNode,
    ProjectNode,
    SemiJoinNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    WindowNode,
)


class Matcher:
    def __init__(self, node_type, children: Sequence["Matcher"] = (),
                 predicate: Optional[Callable[[PlanNode], bool]] = None,
                 label: str = ""):
        self.node_type = node_type
        self.children = list(children)
        self.predicate = predicate
        self.label = label or (node_type.__name__ if node_type else "any")

    def matches(self, node: PlanNode) -> bool:
        if self.node_type is not None and not isinstance(node, self.node_type):
            return False
        if self.predicate is not None and not self.predicate(node):
            return False
        if not self.children:
            return True
        sources = list(node.sources)
        if len(self.children) != len(sources):
            return False
        return all(m.matches(s) for m, s in zip(self.children, sources))

    def __repr__(self):
        inner = ", ".join(repr(c) for c in self.children)
        return f"{self.label}({inner})"


class _AnyTree(Matcher):
    """Skips any chain of nodes until the child matcher matches some
    descendant reachable through ANY path (PlanMatchPattern.anyTree)."""

    def __init__(self, child: Matcher):
        super().__init__(None, [], None, "anyTree")
        self.child = child

    def matches(self, node: PlanNode) -> bool:
        if self.child.matches(node):
            return True
        return any(self.matches(s) for s in node.sources)

    def __repr__(self):
        return f"anyTree({self.child!r})"


class P:
    """Matcher factories."""

    @staticmethod
    def node(node_type, *children, where=None, label=""):
        return Matcher(node_type, children, where, label)

    @staticmethod
    def any(*children, where=None):
        return Matcher(None, children, where, "any")

    @staticmethod
    def any_tree(child):
        return _AnyTree(child)

    @staticmethod
    def output(*children, **attrs):
        return P.node(OutputNode, *children)

    @staticmethod
    def project(*children):
        return P.node(ProjectNode, *children)

    @staticmethod
    def filter(*children, where=None):
        return P.node(FilterNode, *children, where=where)

    @staticmethod
    def scan(table: Optional[str] = None):
        pred = None
        if table is not None:
            def pred(n, t=table):
                return n.table.schema_table.table == t
        return P.node(TableScanNode, where=pred, label=f"scan[{table}]")

    @staticmethod
    def values(rows: Optional[int] = None):
        pred = None
        if rows is not None:
            def pred(n, r=rows):
                return len(n.rows) == r
        return P.node(ValuesNode, where=pred, label=f"values[{rows}]")

    @staticmethod
    def join(*children, kind=None):
        pred = None
        if kind is not None:
            def pred(n, k=kind):
                return n.kind == k
        return P.node(JoinNode, *children, where=pred)

    @staticmethod
    def semi_join(*children):
        return P.node(SemiJoinNode, *children)

    @staticmethod
    def agg(*children, group_keys: Optional[int] = None):
        pred = None
        if group_keys is not None:
            def pred(n, g=group_keys):
                return len(n.group_keys) == g
        return P.node(AggregationNode, *children, where=pred)

    @staticmethod
    def limit(*children, count: Optional[int] = None):
        pred = None
        if count is not None:
            def pred(n, c=count):
                return n.count == c
        return P.node(LimitNode, *children, where=pred, label=f"limit[{count}]")

    @staticmethod
    def topn(*children, count: Optional[int] = None):
        pred = None
        if count is not None:
            def pred(n, c=count):
                return n.count == c
        return P.node(TopNNode, *children, where=pred, label=f"topn[{count}]")

    @staticmethod
    def sort(*children):
        return P.node(SortNode, *children)

    @staticmethod
    def window(*children):
        return P.node(WindowNode, *children)

    @staticmethod
    def union(*children):
        return P.node(UnionNode, *children)

    @staticmethod
    def single_row(*children):
        return P.node(EnforceSingleRowNode, *children)


def _walk(node: PlanNode):
    yield node
    for s in node.sources:
        yield from _walk(s)


def assert_plan(plan: LogicalPlan, matcher: Matcher) -> None:
    root = plan.root if isinstance(plan, LogicalPlan) else plan
    if not matcher.matches(root):
        from trino_tpu.planner import format_plan

        rendered = format_plan(plan if isinstance(plan, LogicalPlan) else LogicalPlan(root, {}))
        raise AssertionError(
            f"plan does not match {matcher!r}\n--- actual plan ---\n{rendered}"
        )


def assert_plan_contains(plan: LogicalPlan, matcher: Matcher) -> None:
    root = plan.root if isinstance(plan, LogicalPlan) else plan
    if not any(matcher.matches(n) for n in _walk(root)):
        from trino_tpu.planner import format_plan

        rendered = format_plan(plan if isinstance(plan, LogicalPlan) else LogicalPlan(root, {}))
        raise AssertionError(
            f"no subtree matches {matcher!r}\n--- actual plan ---\n{rendered}"
        )


def assert_no_node(plan: LogicalPlan, node_type) -> None:
    root = plan.root if isinstance(plan, LogicalPlan) else plan
    found = [n for n in _walk(root) if isinstance(n, node_type)]
    if found:
        from trino_tpu.planner import format_plan

        rendered = format_plan(plan if isinstance(plan, LogicalPlan) else LogicalPlan(root, {}))
        raise AssertionError(
            f"plan unexpectedly contains {node_type.__name__}\n"
            f"--- actual plan ---\n{rendered}"
        )
