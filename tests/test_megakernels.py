"""Megakernel-plane tests (ops/megakernels.py): the fused hash-join /
partial-agg / repartition-epilogue Pallas kernels under interpret mode on
CPU, bit-identical against the serial op-chain oracle.

Every fused kernel here executes through ``pl.pallas_call(...,
interpret=True)`` (the pallas_interpret=auto resolution on a CPU backend),
so tier-1 exercises the fused path's exact arithmetic against the serial
formulation — the contract ISSUE 12 pins. Launch accounting: a fused
join+agg books ONE device program where the serial walk books two (join
node + aggregation node), asserted below via the device-programs counter.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trino_tpu.ops import megakernels as MK
from trino_tpu.spi.page import Column, Page
from trino_tpu.spi.types import BIGINT, DOUBLE

SCALE = 0.0005


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


def _ab(runner, sql):
    """rows with pallas_fusion off vs on (+ pallas launch delta for on)."""
    runner.session.set("pallas_fusion", False)
    want = runner.execute(sql).rows
    runner.session.set("pallas_fusion", True)
    p0 = MK.pallas_launches()
    got = runner.execute(sql).rows
    dp = MK.pallas_launches() - p0
    runner.session.set("pallas_fusion", False)
    return want, got, dp


class TestFusedJoinShapes:
    """The join-heavy fragment shapes the megakernel plane targets."""

    def test_q5_shape_join_agg_fused(self, runner):
        """Dictionary group key over a join chain: the join->partial-agg
        fusion fires (ONE kernel does build/probe/group-accumulate) and the
        result is bit-identical to the serial chain."""
        want, got, dp = _ab(runner, """
            SELECT n_name, sum(l_extendedprice), count(*)
            FROM lineitem
            JOIN orders ON l_orderkey = o_orderkey
            JOIN customer ON o_custkey = c_custkey
            JOIN nation ON c_nationkey = n_nationkey
            GROUP BY n_name ORDER BY n_name""")
        assert got == want
        assert dp >= 2  # at least probe + expand kernels ran

    def test_q3_shape(self, runner):
        want, got, dp = _ab(runner, """
            SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS rev,
                   o_orderdate, o_shippriority
            FROM customer, orders, lineitem
            WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
              AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
              AND l_shipdate > DATE '1995-03-15'
            GROUP BY l_orderkey, o_orderdate, o_shippriority
            ORDER BY rev DESC, o_orderdate, l_orderkey LIMIT 10""")
        assert got == want
        assert dp >= 2

    def test_q13_shape_left_join(self, runner):
        want, got, dp = _ab(runner, """
            SELECT c_custkey, count(o_orderkey) AS cnt
            FROM customer LEFT JOIN orders ON c_custkey = o_custkey
            GROUP BY c_custkey ORDER BY cnt DESC, c_custkey LIMIT 10""")
        assert got == want
        assert dp >= 2

    def test_right_join_swaps(self, runner):
        want, got, dp = _ab(runner, """
            SELECT n_name, count(*) FROM orders
            RIGHT JOIN customer ON o_custkey = c_custkey
            JOIN nation ON c_nationkey = n_nationkey
            GROUP BY n_name ORDER BY 1""")
        assert got == want
        assert dp >= 2

    def test_fewer_device_programs_per_fragment(self, runner):
        """The acceptance metric: with fusion on, the join+agg fragment
        dispatches STRICTLY fewer device programs (one megakernel node
        program replaces the join-node + aggregation-node programs)."""
        from trino_tpu.runtime.device_scheduler import program_launches

        sql = """
            SELECT n_name, sum(o_totalprice)
            FROM orders JOIN customer ON o_custkey = c_custkey
            JOIN nation ON c_nationkey = n_nationkey
            GROUP BY n_name ORDER BY n_name"""
        runner.session.set("pallas_fusion", False)
        runner.execute(sql)  # warm
        n0 = program_launches()
        want = runner.execute(sql).rows
        serial = program_launches() - n0
        runner.session.set("pallas_fusion", True)
        runner.execute(sql)  # warm
        n0 = program_launches()
        got = runner.execute(sql).rows
        fused = program_launches() - n0
        runner.session.set("pallas_fusion", False)
        assert got == want
        assert fused < serial, (fused, serial)


class TestMegakernelEdgeCases:
    def test_null_sentinel_keys(self, runner):
        """NULL join keys never match (inner) and left-join rows with NULL
        keys still emit their null-padded row — on both paths."""
        sql_inner = """
            SELECT a.x, b.y FROM
              (SELECT IF(t % 3 = 0, CAST(NULL AS BIGINT), t) AS x
               FROM UNNEST(sequence(1, 200)) AS u(t)) a
            JOIN
              (SELECT IF(t % 5 = 0, CAST(NULL AS BIGINT), t) AS k, t AS y
               FROM UNNEST(sequence(1, 300)) AS v(t)) b
            ON a.x = b.k ORDER BY 1, 2"""
        want, got, dp = _ab(runner, sql_inner)
        assert got == want
        assert dp >= 2
        sql_left = sql_inner.replace("JOIN", "LEFT JOIN", 1)
        want, got, dp = _ab(runner, sql_left)
        assert got == want
        assert dp >= 2

    def test_dictionary_encoded_keys(self, runner):
        """Varchar join keys translate probe codes through the build
        dictionary LUT; probe values absent from the build vocabulary are
        real-but-unmatched, same as the serial path."""
        want, got, dp = _ab(runner, """
            SELECT c_name, n.n_name
            FROM customer c JOIN nation n ON c.c_mktsegment = n.n_name
            ORDER BY 1, 2""")
        # c_mktsegment values never appear in nation names: empty result
        # on both paths, via the LUT miss (-1 codes), not via luck
        assert got == want == []
        want, got, dp = _ab(runner, """
            SELECT s.n_name, count(*)
            FROM (SELECT n_name FROM nation) s
            JOIN (SELECT n_name FROM nation WHERE n_regionkey > 1) t
              ON s.n_name = t.n_name
            GROUP BY s.n_name ORDER BY 1""")
        assert got == want
        assert dp >= 2

    def test_empty_build_and_probe_sides(self, runner):
        for pred_side in ("o_custkey < 0", "c_custkey < 0"):
            want, got, dp = _ab(runner, f"""
                SELECT o_orderkey, c_name
                FROM (SELECT * FROM orders WHERE {pred_side.startswith('o') and pred_side or 'TRUE'}) o
                JOIN (SELECT * FROM customer WHERE {pred_side.startswith('c') and pred_side or 'TRUE'}) c
                ON o.o_custkey = c.c_custkey ORDER BY 1 LIMIT 5""")
            assert got == want == []
            assert dp >= 2

    def test_capacity_class_boundary_shapes(self):
        """Probe/build capacities pinned to the pow2/capacity-class edges
        from capstore.capacity_class (1024 exact, 1025 promotes, 4096
        exact): the fused probe+expand kernels against the serial
        _jit_join_match/_jit_join_expand oracle at the kernel level —
        padding and inactive rows ride through both paths identically."""
        import trino_tpu.runtime.executor as E
        from trino_tpu.runtime.capstore import capacity_class

        assert capacity_class(1024) == 1024 and capacity_class(1025) == 4096
        rng = np.random.default_rng(7)
        for n, m in ((1023, 1024), (1024, 1025), (1025, 4096), (4096, 512)):
            pk = jnp.asarray(rng.integers(0, 300, n))
            pv = jnp.asarray(rng.random(n) < 0.9)
            pa = jnp.asarray(rng.random(n) < 0.8)
            bk = jnp.asarray(rng.integers(0, 300, m))
            bv = jnp.asarray(rng.random(m) < 0.9)
            ba = jnp.asarray(rng.random(m) < 0.7)
            probe_page = Page(
                (Column(BIGINT, pk, pv),
                 Column(DOUBLE, jnp.asarray(rng.random(n)), jnp.ones(n, bool))),
                pa,
            )
            build_page = Page(
                (Column(BIGINT, bk, bv),
                 Column(BIGINT, jnp.asarray(rng.integers(0, 99, m)),
                        jnp.ones(m, bool))),
                ba,
            )
            pkeys, bkeys, luts = ((pk, pv),), ((bk, bv),), (None,)
            emit, count, lo, perm_b = E._jit_join_match(
                False, pkeys, bkeys, luts, pa, ba
            )
            cap = E._round_capacity(max(int(jnp.sum(emit)), 1))
            want = E._jit_join_expand(
                cap, emit, count, lo, perm_b, probe_page, build_page
            )
            pr = MK.probe_phase(pkeys, bkeys, luts, pa, ba, False, True)
            assert pr is not None, (n, m)
            got, dest = MK.expand_phase(
                pr, pkeys, bkeys, luts, probe_page, build_page, cap,
                ("pk", "pv_col", "bk", "bpay"), None, None, None, True,
            )
            assert dest is None
            np.testing.assert_array_equal(
                np.asarray(got.active), np.asarray(want.active), str((n, m))
            )
            for gc, wc in zip(got.columns[:2], want.columns[:2]):
                # probe side: identical gathers everywhere (same probe_idx)
                np.testing.assert_array_equal(
                    np.asarray(gc.valid), np.asarray(wc.valid))
                np.testing.assert_array_equal(
                    np.asarray(gc.data), np.asarray(wc.data))
            act = np.asarray(got.active)
            for gc, wc in zip(got.columns[2:], want.columns[2:]):
                # build side: valid masks identical; data compared where
                # valid (unmatched slots gather arbitrary rows on each path)
                np.testing.assert_array_equal(
                    np.asarray(gc.valid), np.asarray(wc.valid))
                sel = act & np.asarray(gc.valid)
                np.testing.assert_array_equal(
                    np.asarray(gc.data)[sel], np.asarray(wc.data)[sel])

    def test_bucket_cap_retry_on_duplicate_heavy_keys(self, runner):
        """> DEFAULT_BUCKET_CAP duplicates per key (3 distinct keys x 120
        build rows each — the orders-status shape, synthetic so the
        interpret-mode probe table stays MBs instead of the GBs the full
        orders x lineitem cross product faults in): the probe phase retries
        at the larger 4x-spaced bucket class (3 launches: probe, retried
        probe, expand), still bit-identical."""
        sql = """
            SELECT b.s, count(*)
            FROM (SELECT t % 3 AS s FROM UNNEST(sequence(1, 360)) AS u(t)) a
            JOIN (SELECT t % 3 AS s FROM UNNEST(sequence(1, 360)) AS w(t)) b
              ON a.s = b.s
            GROUP BY b.s ORDER BY 1
        """
        want, got, dp = _ab(runner, sql)
        assert got == want
        assert dp >= 3

    def test_bucket_skew_falls_back(self, runner, monkeypatch):
        """Pathological skew (table beyond the entry limit) falls back to
        the serial path with the labeled counter ticked — and the query
        still answers correctly."""
        monkeypatch.setattr(MK, "TABLE_ENTRY_LIMIT", 1024)
        f0 = MK.pallas_fallbacks("bucket_skew")
        want, got, _dp = _ab(runner, """
            SELECT count(*)
            FROM orders JOIN lineitem ON o_orderstatus = l_linestatus""")
        assert got == want
        assert MK.pallas_fallbacks("bucket_skew") > f0

    def test_int128_limb_payload_rides_fused_pipeline(self, runner):
        """Long-decimal (int128 two-limb) values through the fused
        join->project->sort-agg pipeline: the limb columns gather/cosort on
        axis 0 exactly like the serial path, and the sum exercises the limb
        accumulator carry on values wider than int64."""
        sql = """
            SELECT o_custkey, sum(CAST(o_totalprice AS DECIMAL(38, 2)) * 100000000)
            FROM orders JOIN customer ON o_custkey = c_custkey
            GROUP BY o_custkey ORDER BY 2 DESC, 1 LIMIT 10"""
        want, got, dp = _ab(runner, sql)
        assert got == want
        assert dp >= 2

    def test_int64_accumulator_wraparound_identity(self, runner):
        """Sums near the int64 edge: fused and serial must wrap identically
        (mod-2^64 accumulation — the limb-recombination contract)."""
        big = (1 << 62) - 1
        sql = f"""
            SELECT b.g, sum(a.v)
            FROM (SELECT t % 5 AS k, {big} - t AS v
                  FROM UNNEST(sequence(1, 100)) AS u(t)) a
            JOIN (SELECT t AS k, t % 2 AS g
                  FROM UNNEST(sequence(0, 4)) AS w(t)) b ON a.k = b.k
            GROUP BY b.g ORDER BY b.g"""
        want, got, dp = _ab(runner, sql)
        assert got == want
        assert dp >= 2


class TestFusedRepartitionEpilogue:
    def _page(self, n=4096, seed=0):
        rng = np.random.default_rng(seed)
        return Page(
            (
                Column(BIGINT, jnp.asarray(rng.integers(0, 500, n)),
                       jnp.asarray(rng.random(n) < 0.9)),
                Column(DOUBLE, jnp.asarray(rng.random(n)),
                       jnp.ones(n, dtype=bool)),
            ),
            jnp.asarray(rng.random(n) < 0.8),
        )

    def test_fused_epilogue_bit_identical(self):
        """hash -> stable cosort -> offsets as ONE kernel == the standalone
        jit epilogue, including NULL-key routing and the inactive tail."""
        from trino_tpu.ops.repartition import _jit_repartition_epilogue

        page = self._page()
        sp, off, cnt = MK.fused_epilogue(page, (0,), 8, interpret=True)
        sp2, off2, cnt2 = _jit_repartition_epilogue(8, (0,), page)
        np.testing.assert_array_equal(np.asarray(off), np.asarray(off2))
        np.testing.assert_array_equal(np.asarray(cnt), np.asarray(cnt2))
        for c1, c2 in zip(sp.columns, sp2.columns):
            np.testing.assert_array_equal(np.asarray(c1.data), np.asarray(c2.data))
            np.testing.assert_array_equal(np.asarray(c1.valid), np.asarray(c2.valid))
        np.testing.assert_array_equal(np.asarray(sp.active), np.asarray(sp2.active))

    def test_attached_dest_frames_identical(self):
        """A megakernel-attached dest yields the exact frames of the
        standalone hash program, and the attachment is consumed."""
        from trino_tpu.ops.repartition import (
            _jit_partition_dest,
            repartition_frames,
        )

        page = self._page(seed=1)
        frames0, counts0 = repartition_frames(page, (0,), 8)
        dest = _jit_partition_dest(8, (0,), page)
        MK.attach_epilogue(page, dest, (0,), 8, keys=("k",))
        frames1, counts1 = repartition_frames(page, (0,), 8)
        assert frames0 == frames1
        assert list(counts0) == list(counts1)
        assert "_megakernel_epilogue" not in page.__dict__

    def test_mismatched_attachment_ignored(self):
        from trino_tpu.ops.repartition import (
            _jit_partition_dest,
            repartition_frames,
        )

        page = self._page(seed=2)
        frames0, _ = repartition_frames(page, (0,), 8)
        MK.attach_epilogue(page, _jit_partition_dest(4, (0,), page), (0,), 4)
        frames1, _ = repartition_frames(page, (0,), 8)  # different spec
        assert frames0 == frames1

    def test_hint_flows_through_projection_to_frames(self, runner):
        """End to end: a repartition_hint on the executor makes the fused
        root compute dest in-kernel, the attachment survives the projection
        rewrap, and the exchange frames are bit-identical to the unhinted
        path."""
        import trino_tpu.sql.parser as P
        from trino_tpu.planner import LogicalPlanner, optimize
        from trino_tpu.ops.repartition import repartition_frames
        from trino_tpu.runtime.executor import PlanExecutor

        sql = ("SELECT o_orderkey, c_name FROM orders "
               "JOIN customer ON o_custkey = c_custkey")
        stmt = P.parse_statement(sql)
        planner = LogicalPlanner(runner.metadata, runner.session)
        plan = optimize(planner.plan(stmt), runner.metadata, runner.session)
        runner.session.set("pallas_fusion", True)
        try:
            ex = PlanExecutor(plan, runner.metadata, runner.session)
            rel = ex.eval(plan.root.source)
            frames0, counts0 = repartition_frames(rel.page, (0,), 4)

            ex2 = PlanExecutor(plan, runner.metadata, runner.session)
            ex2.repartition_hint = ((rel.symbols[0],), 4)
            rel2 = ex2.eval(plan.root.source)
            att = rel2.page.__dict__.get("_megakernel_epilogue")
            assert att and att["n_parts"] == 4
            frames1, counts1 = repartition_frames(rel2.page, (0,), 4)
            assert frames0 == frames1
            assert list(counts0) == list(counts1)
        finally:
            runner.session.set("pallas_fusion", False)


class TestKnobContract:
    def test_knob_off_path_untouched(self, runner, monkeypatch):
        """pallas_fusion off (the default): the megakernel plane is never
        consulted — asserted by poisoning its entry points — and zero
        pallas launches happen. The off path is the HEAD path."""
        def boom(*a, **k):  # pragma: no cover - would fail the test
            raise AssertionError("megakernel path entered with knob off")

        monkeypatch.setattr(MK, "probe_phase", boom)
        monkeypatch.setattr(MK, "expand_phase", boom)
        p0 = MK.pallas_launches()
        runner.session.set("pallas_fusion", False)
        rows = runner.execute("""
            SELECT n_name, count(*) FROM customer
            JOIN nation ON c_nationkey = n_nationkey
            GROUP BY n_name ORDER BY 1""").rows
        assert rows
        assert MK.pallas_launches() == p0

    def test_default_is_off(self, runner):
        assert not runner.session.get("pallas_fusion")

    def test_pallas_interpret_resolution(self):
        from trino_tpu import knobs

        assert knobs.resolve_pallas_interpret("auto", "cpu") is True
        assert knobs.resolve_pallas_interpret("auto", "tpu") is False
        assert knobs.resolve_pallas_interpret("on", "tpu") is True
        assert knobs.resolve_pallas_interpret("off", "cpu") is False

    def test_pallas_aggregation_policy_central(self):
        from trino_tpu import knobs

        assert knobs.resolve_pallas_aggregation("auto") == "off"
        assert knobs.resolve_pallas_aggregation(None) == "off"
        assert knobs.resolve_pallas_aggregation("force") == "tpu"
        assert knobs.resolve_pallas_aggregation("interpret") == "interpret"


@pytest.mark.slow
class TestCorpusBitIdentity:
    def test_tpch_22_corpus_fused_matches_serial(self, runner):
        """Every TPC-H query, fused vs serial, bit-identical rows under
        interpret mode (the full-corpus acceptance sweep)."""
        from tests.tpch_corpus import TPCH_QUERIES

        for name, sql in sorted(TPCH_QUERIES.items()):
            runner.session.set("pallas_fusion", False)
            want = runner.execute(sql).rows
            runner.session.set("pallas_fusion", True)
            got = runner.execute(sql).rows
            runner.session.set("pallas_fusion", False)
            assert got == want, name


class TestCorpusSample:
    """Tier-1 slice of the corpus sweep (the full 22 runs under -m slow):
    the three join-heaviest shapes plus the densest multi-join."""

    @pytest.mark.parametrize("name", ["q03", "q05", "q13", "q21"])
    def test_fused_matches_serial(self, runner, name):
        from tests.tpch_corpus import TPCH_QUERIES

        want, got, _dp = _ab(runner, TPCH_QUERIES[name])
        assert got == want
