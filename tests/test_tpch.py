"""Real TPC-H queries vs the pandas oracle (the BASELINE.md workload ladder:
Q6 scan+filter+sum, Q1 multi-key group-by, Q3/Q14 joins, Q13 left join,
Q18 having+in-subquery+joins, Q5 six-way join)."""

import datetime

import numpy as np
import pandas as pd
import pytest

from tests.oracle import tpch_df, assert_rows_equal

SCALE = 0.0005
EPOCH = datetime.date(1970, 1, 1)


def days(iso: str) -> int:
    return (datetime.date.fromisoformat(iso) - EPOCH).days


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


def test_q6(runner):
    res = runner.execute(
        """
        SELECT sum(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
          AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
          AND l_quantity < 24
        """
    )
    li = tpch_df("lineitem", SCALE)
    m = li[
        (li.l_shipdate >= days("1994-01-01"))
        & (li.l_shipdate < days("1995-01-01"))
        & (li.l_discount >= 0.05)
        & (li.l_discount <= 0.07)
        & (li.l_quantity < 24)
    ]
    expected = (m.l_extendedprice * m.l_discount).sum()
    assert_rows_equal(res.rows, [(expected,)], float_tol=1e-9)


def test_q1(runner):
    res = runner.execute(
        """
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               avg(l_quantity) AS avg_qty,
               avg(l_extendedprice) AS avg_price,
               avg(l_discount) AS avg_disc,
               count(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
        """
    )
    li = tpch_df("lineitem", SCALE)
    m = li[li.l_shipdate <= days("1998-12-01") - 90].copy()
    m["disc_price"] = m.l_extendedprice * (1 - m.l_discount)
    m["charge"] = m.disc_price * (1 + m.l_tax)
    g = (
        m.groupby(["l_returnflag", "l_linestatus"])
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_orderkey", "count"),
        )
        .reset_index()
        .sort_values(["l_returnflag", "l_linestatus"])
    )
    # decimal avg columns round to the column scale (Trino semantics)
    g["avg_qty"] = g.avg_qty.round(2)
    g["avg_price"] = g.avg_price.round(2)
    g["avg_disc"] = g.avg_disc.round(2)
    assert_rows_equal(
        res.rows, [tuple(r) for r in g.itertuples(index=False)], float_tol=1e-9
    )


def test_q3(runner):
    res = runner.execute(
        """
        SELECT l_orderkey,
               sum(l_extendedprice * (1 - l_discount)) AS revenue,
               o_orderdate, o_shippriority
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING'
          AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND o_orderdate < DATE '1995-03-15'
          AND l_shipdate > DATE '1995-03-15'
        GROUP BY l_orderkey, o_orderdate, o_shippriority
        ORDER BY revenue DESC, o_orderdate, l_orderkey
        LIMIT 10
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    m = (
        c[c.c_mktsegment == "BUILDING"]
        .merge(o[o.o_orderdate < days("1995-03-15")], left_on="c_custkey", right_on="o_custkey")
        .merge(li[li.l_shipdate > days("1995-03-15")], left_on="o_orderkey", right_on="l_orderkey")
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = (
        m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"]
        .sum()
        .reset_index()
        .sort_values(["revenue", "o_orderdate", "l_orderkey"], ascending=[False, True, True])
        .head(10)
    )
    assert_rows_equal(
        res.rows,
        [
            (int(r.l_orderkey), round(r.revenue, 4), int(r.o_orderdate), int(r.o_shippriority))
            for r in g.itertuples()
        ],
        float_tol=1e-9,
    )


def test_q5(runner):
    res = runner.execute(
        """
        SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM customer, orders, lineitem, supplier, nation, region
        WHERE c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND l_suppkey = s_suppkey
          AND c_nationkey = s_nationkey
          AND s_nationkey = n_nationkey
          AND n_regionkey = r_regionkey
          AND r_name = 'ASIA'
          AND o_orderdate >= DATE '1994-01-01'
          AND o_orderdate < DATE '1995-01-01'
        GROUP BY n_name
        ORDER BY revenue DESC
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    s = tpch_df("supplier", SCALE)
    n = tpch_df("nation", SCALE)
    r = tpch_df("region", SCALE)
    m = (
        c.merge(o[(o.o_orderdate >= days("1994-01-01")) & (o.o_orderdate < days("1995-01-01"))],
                left_on="c_custkey", right_on="o_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
    )
    m = m[m.c_nationkey == m.s_nationkey]
    m = m.merge(n, left_on="s_nationkey", right_on="n_nationkey").merge(
        r[r.r_name == "ASIA"], left_on="n_regionkey", right_on="r_regionkey"
    )
    m["revenue"] = m.l_extendedprice * (1 - m.l_discount)
    g = m.groupby("n_name")["revenue"].sum().reset_index().sort_values("revenue", ascending=False)
    assert_rows_equal(
        res.rows,
        [(r_.n_name, round(r_.revenue, 4)) for r_ in g.itertuples()],
        float_tol=1e-9,
    )


def test_q13(runner):
    res = runner.execute(
        """
        SELECT c_count, count(*) AS custdist
        FROM (
          SELECT c_custkey, count(o_orderkey) AS c_count
          FROM customer LEFT JOIN orders ON c_custkey = o_custkey
            AND o_comment NOT LIKE '%special%requests%'
          GROUP BY c_custkey
        ) AS c_orders
        GROUP BY c_count
        ORDER BY custdist DESC, c_count DESC
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    of = o[~o.o_comment.str.contains("special.*requests", regex=True)]
    m = c.merge(of, left_on="c_custkey", right_on="o_custkey", how="left")
    cc = m.groupby("c_custkey")["o_orderkey"].count().reset_index(name="c_count")
    cd = (
        cc.groupby("c_count").size().reset_index(name="custdist")
        .sort_values(["custdist", "c_count"], ascending=[False, False])
    )
    assert_rows_equal(
        res.rows, [(int(r.c_count), int(r.custdist)) for r in cd.itertuples()]
    )


def test_q14(runner):
    res = runner.execute(
        """
        SELECT 100.00 * sum(CASE WHEN p_type LIKE 'PROMO%'
                                 THEN l_extendedprice * (1 - l_discount)
                                 ELSE 0 END)
               / sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
        FROM lineitem, part
        WHERE l_partkey = p_partkey
          AND l_shipdate >= DATE '1995-09-01'
          AND l_shipdate < DATE '1995-10-01'
        """
    )
    li = tpch_df("lineitem", SCALE)
    p = tpch_df("part", SCALE)
    m = li[(li.l_shipdate >= days("1995-09-01")) & (li.l_shipdate < days("1995-10-01"))].merge(
        p, left_on="l_partkey", right_on="p_partkey"
    )
    disc = m.l_extendedprice * (1 - m.l_discount)
    promo = disc.where(m.p_type.str.startswith("PROMO"), 0.0)
    expected = 100.0 * promo.sum() / disc.sum()
    assert_rows_equal(res.rows, [(expected,)], float_tol=1e-9)


def test_q18(runner):
    res = runner.execute(
        """
        SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity)
        FROM customer, orders, lineitem
        WHERE o_orderkey IN (
            SELECT l_orderkey FROM lineitem
            GROUP BY l_orderkey HAVING sum(l_quantity) > 150
          )
          AND c_custkey = o_custkey
          AND o_orderkey = l_orderkey
        GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        ORDER BY o_totalprice DESC, o_orderdate, o_orderkey
        LIMIT 100
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    big = li.groupby("l_orderkey")["l_quantity"].sum()
    big = set(big[big > 150].index)
    m = (
        c.merge(o[o.o_orderkey.isin(big)], left_on="c_custkey", right_on="o_custkey")
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
    )
    g = (
        m.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"])["l_quantity"]
        .sum()
        .reset_index()
        .sort_values(["o_totalprice", "o_orderdate", "o_orderkey"], ascending=[False, True, True])
        .head(100)
    )
    assert_rows_equal(
        res.rows,
        [
            (r.c_name, int(r.c_custkey), int(r.o_orderkey), int(r.o_orderdate),
             r.o_totalprice, r.l_quantity)
            for r in g.itertuples()
        ],
        float_tol=1e-9,
    )


def test_q12(runner):
    res = runner.execute(
        """
        SELECT l_shipmode,
               sum(CASE WHEN o_orderpriority = '1-URGENT' OR o_orderpriority = '2-HIGH'
                        THEN 1 ELSE 0 END) AS high_line_count,
               sum(CASE WHEN o_orderpriority <> '1-URGENT' AND o_orderpriority <> '2-HIGH'
                        THEN 1 ELSE 0 END) AS low_line_count
        FROM orders, lineitem
        WHERE o_orderkey = l_orderkey
          AND l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate
          AND l_shipdate < l_commitdate
          AND l_receiptdate >= DATE '1994-01-01'
          AND l_receiptdate < DATE '1995-01-01'
        GROUP BY l_shipmode
        ORDER BY l_shipmode
        """
    )
    o = tpch_df("orders", SCALE)
    li = tpch_df("lineitem", SCALE)
    m = li[
        li.l_shipmode.isin(["MAIL", "SHIP"])
        & (li.l_commitdate < li.l_receiptdate)
        & (li.l_shipdate < li.l_commitdate)
        & (li.l_receiptdate >= days("1994-01-01"))
        & (li.l_receiptdate < days("1995-01-01"))
    ].merge(o, left_on="l_orderkey", right_on="o_orderkey")
    high = m.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    g = (
        m.assign(h=high.astype(int), l=(~high).astype(int))
        .groupby("l_shipmode")
        .agg(h=("h", "sum"), l=("l", "sum"))
        .reset_index()
        .sort_values("l_shipmode")
    )
    assert_rows_equal(
        res.rows, [(r.l_shipmode, int(r.h), int(r.l)) for r in g.itertuples()]
    )


def test_q19_simplified(runner):
    # Q19's OR-of-ANDs over two tables (quantity windows x brand x container)
    res = runner.execute(
        """
        SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem, part
        WHERE p_partkey = l_partkey
          AND ((p_brand = 'Brand#12' AND l_quantity BETWEEN 1 AND 11)
            OR (p_brand = 'Brand#23' AND l_quantity BETWEEN 10 AND 20))
        """
    )
    li = tpch_df("lineitem", SCALE)
    p = tpch_df("part", SCALE)
    m = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    cond = ((m.p_brand == "Brand#12") & m.l_quantity.between(1, 11)) | (
        (m.p_brand == "Brand#23") & m.l_quantity.between(10, 20)
    )
    expected = (m[cond].l_extendedprice * (1 - m[cond].l_discount)).sum()
    assert_rows_equal(res.rows, [(round(expected, 4),)], float_tol=1e-9)
