"""Page wire serde: framing + compression + checksums for the DCN tier.

Reference blueprint: execution/buffer/PagesSerdeFactory.java:56-90 — flat block
encodings + LZ4/ZSTD compression (+ optional AES) with a per-page frame. The
byte-level work (LZ4, checksum) runs in C++ (trino_tpu.native); framing is here.

Frame layout (little-endian):
  magic 'TPG1' | ncols u32 | capacity u64 | nbuffers u32
  per buffer: dtype_code u8 | codec u8 (0=raw, 1=lz4) | raw_len u64 |
              comp_len u64 | checksum u64 | payload
Buffers, in order: active mask, then per column (data, valid), then per string
column its dictionary as a utf-8 '\\x00'-joined blob.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import native
from ..spi.page import Column, Dictionary, Page
from ..spi.types import Type, parse_type

MAGIC = b"TPG1"

_DTYPES = [
    np.dtype(np.bool_), np.dtype(np.int8), np.dtype(np.int16), np.dtype(np.int32),
    np.dtype(np.int64), np.dtype(np.float32), np.dtype(np.float64),
    np.dtype(np.uint8),
]
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}

MIN_COMPRESS = 64  # don't bother compressing tiny buffers


def _encode_buffer(arr: np.ndarray, use_native: bool) -> bytes:
    raw = np.ascontiguousarray(arr).tobytes()
    codec = 0
    payload = raw
    if use_native and native.native_available() and len(raw) >= MIN_COMPRESS:
        comp = native.lz4_compress(raw)
        if len(comp) < len(raw):
            codec = 1
            payload = comp
    checksum = native.hash64(payload) if native.native_available() else 0
    header = struct.pack(
        "<BBQQQ", _DTYPE_CODE[arr.dtype], codec, len(raw), len(payload), checksum
    )
    return header + payload


def _decode_buffer(buf: memoryview, offset: int) -> Tuple[np.ndarray, int]:
    dtype_code, codec, raw_len, comp_len, checksum = struct.unpack_from(
        "<BBQQQ", buf, offset
    )
    offset += struct.calcsize("<BBQQQ")
    payload = bytes(buf[offset : offset + comp_len])
    offset += comp_len
    if native.native_available() and checksum:
        actual = native.hash64(payload)
        if actual != checksum:
            raise ValueError("page frame checksum mismatch")
    if codec == 1:
        payload = native.lz4_decompress(payload, raw_len)
    arr = np.frombuffer(payload, dtype=_DTYPES[dtype_code])
    return arr, offset


def serialize_page(page: Page, compress: bool = True) -> bytes:
    """Page -> wire bytes (host side of PartitionedOutput / spooled results)."""
    buffers: List[bytes] = []
    active = np.asarray(page.active)
    buffers.append(_encode_buffer(active, compress))
    dict_blobs: List[bytes] = []
    for c in page.columns:
        buffers.append(_encode_buffer(np.asarray(c.data), compress))
        buffers.append(_encode_buffer(np.asarray(c.valid), compress))
        if c.dictionary is not None:
            blob = "\x00".join(str(s) for s in c.dictionary.values).encode()
            dict_blobs.append(_encode_buffer(np.frombuffer(blob, dtype=np.uint8), compress))
        else:
            dict_blobs.append(b"")
    # column type names (small, uncompressed text section)
    type_names = "\x00".join(c.type.display() for c in page.columns).encode()
    has_dict = bytes(1 if c.dictionary is not None else 0 for c in page.columns)
    head = MAGIC + struct.pack(
        "<IQI", page.num_columns, page.capacity, len(type_names)
    )
    out = [head, type_names, has_dict]
    out.extend(buffers)
    out.extend(b for b in dict_blobs if b)
    return b"".join(out)


def deserialize_page(data: bytes) -> Page:
    buf = memoryview(data)
    if bytes(buf[:4]) != MAGIC:
        raise ValueError("bad page frame magic")
    ncols, capacity, tn_len = struct.unpack_from("<IQI", buf, 4)
    offset = 4 + struct.calcsize("<IQI")
    type_names = bytes(buf[offset : offset + tn_len]).decode().split("\x00") if tn_len else []
    offset += tn_len
    has_dict = list(buf[offset : offset + ncols])
    offset += ncols
    active, offset = _decode_buffer(buf, offset)
    cols: List[Column] = []
    raw_cols: List[Tuple[np.ndarray, np.ndarray]] = []
    for _ in range(ncols):
        data_arr, offset = _decode_buffer(buf, offset)
        valid_arr, offset = _decode_buffer(buf, offset)
        raw_cols.append((data_arr, valid_arr))
    dictionaries: List[Optional[Dictionary]] = []
    for i in range(ncols):
        if has_dict[i]:
            blob, offset = _decode_buffer(buf, offset)
            values = bytes(blob.tobytes()).decode().split("\x00")
            dictionaries.append(Dictionary(np.asarray(values, dtype=object)))
        else:
            dictionaries.append(None)
    for i, ((data_arr, valid_arr), tname) in enumerate(zip(raw_cols, type_names)):
        type_ = parse_type(tname)
        cols.append(
            Column(
                type_,
                jnp.asarray(data_arr.astype(type_.storage_dtype, copy=False)),
                jnp.asarray(valid_arr.astype(np.bool_, copy=False)),
                dictionaries[i],
            )
        )
    return Page(tuple(cols), jnp.asarray(active.astype(np.bool_, copy=False)))
