"""Window function execution (ref: operator/window/WindowOperator.java +
framing, SURVEY.md §2.5).

Sort-based: rows are sorted by (partition keys, order keys); per-sorted-row
FRAME BOUNDS [lo, hi] are computed as index arrays, and frame aggregates
become prefix-sum differences (sum/count/avg) or running scans with
partition resets (min/max) — no per-row loops, all static shapes. Results
scatter back to original row positions via the inverse permutation.

Frames (ref: operator/window/FramedWindowFunction + WindowPartition.java):
- ROWS with any bound combination (UNBOUNDED/offset/CURRENT)
- RANGE with UNBOUNDED/CURRENT bounds (CURRENT ROW = the rank-peer group);
  value-offset RANGE frames raise (needs order-key arithmetic — later round)
- default: RANGE UNBOUNDED PRECEDING..CURRENT ROW when ORDER BY is present,
  else the whole partition (SQL standard defaults)
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import kernels as K
from ..planner.plan import WindowFrame, WindowNode
from ..spi.page import Column, Page
from ..spi.types import BIGINT, DOUBLE, DecimalType, is_floating

if TYPE_CHECKING:
    from .executor import PlanExecutor, Relation


_AGG_FUNCS = ("sum", "count", "avg", "min", "max")


def _const_param(wf, i: int, what: str, allow_none: bool = False):
    """Scalar window parameters (ntile N, lead/lag offset/default, nth_value
    N) must be literals — evaluating one row's value and broadcasting it
    would be silently wrong (Trino evaluates these per row; constants cover
    the practical surface and anything else must error loudly)."""
    consts = wf.const_args
    v = consts[i] if i < len(consts) else None
    if v == "__nonconst__":
        raise NotImplementedError(f"{what} must be a constant expression")
    if v is None and not allow_none:
        raise NotImplementedError(f"{what} must be a constant expression")
    return v


def _running_extreme(vals: jnp.ndarray, reset: jnp.ndarray, kind: str) -> jnp.ndarray:
    """Per-position running min/max that restarts at ``reset`` marks — an
    associative scan over (value, boundary) pairs, so partitions never leak."""
    op = jnp.minimum if kind == "min" else jnp.maximum

    def combine(a, b):
        av, ab = a
        bv, bb = b
        return jnp.where(bb, bv, op(av, bv)), ab | bb

    out, _ = jax.lax.associative_scan(combine, (vals, reset))
    return out


def execute_window(executor: "PlanExecutor", rel: "Relation", node: WindowNode):
    from .executor import Relation

    cap = rel.capacity
    active = rel.page.active

    part_cols = [
        (rel.column_for(s).data, rel.column_for(s).valid) for s in node.partition_by
    ]
    # sort: partitions grouped, then order-by within partition
    sort_keys: List[jnp.ndarray] = []
    for data, valid in part_cols:
        sort_keys.append(K.encode_sort_column(data, valid, True, False))
    for o in node.order_by:
        c = rel.column_for(o.symbol)
        sort_keys.append(K.encode_sort_column(c.data, c.valid, o.ascending, o.nulls_first))
    perm = K.lexsort_perm(sort_keys, active) if sort_keys else jnp.arange(cap)
    inv = jnp.zeros(cap, dtype=jnp.int32).at[perm].set(jnp.arange(cap, dtype=jnp.int32))

    active_s = active[perm]
    # partition boundaries
    if part_cols:
        pkeys_s = [K.encode_sort_column(d, v, True, False)[perm] for d, v in part_cols]
        diff = jnp.zeros(cap, dtype=bool)
        for k in pkeys_s:
            diff = diff | (k != jnp.roll(k, 1))
    else:
        diff = jnp.zeros(cap, dtype=bool)
    first = jnp.zeros(cap, dtype=bool).at[0].set(True)
    prev_active = jnp.roll(active_s, 1).at[0].set(False)
    new_part = active_s & (first | diff | ~prev_active)
    pid = (jnp.cumsum(new_part.astype(jnp.int32)) - 1).astype(jnp.int32)

    # order-key change points (rank/dense_rank peer groups) — reuse the
    # already-encoded order-by tail of sort_keys
    if node.order_by:
        odiff = jnp.zeros(cap, dtype=bool)
        for k in sort_keys[len(part_cols):]:
            ks = k[perm]
            odiff = odiff | (ks != jnp.roll(ks, 1))
        peer_start = new_part | (active_s & odiff)
    else:
        peer_start = new_part

    idx = jnp.arange(cap)
    part_anchor = jax.lax.cummax(jnp.where(new_part, idx, 0))
    peer_anchor = jax.lax.cummax(jnp.where(peer_start, idx, 0))
    part_count = K.segment_reduce(active_s.astype(jnp.int64), active_s, pid, cap, "count")
    count_here = part_count[pid]
    part_end = part_anchor + jnp.maximum(count_here - 1, 0).astype(idx.dtype)
    peer_id = (jnp.cumsum(peer_start.astype(jnp.int32)) - 1).astype(jnp.int32)
    peer_count = K.segment_reduce(active_s.astype(jnp.int64), active_s, peer_id, cap, "count")
    peer_end = peer_anchor + jnp.maximum(peer_count[peer_id] - 1, 0).astype(idx.dtype)

    def frame_bounds(frame: Optional[WindowFrame]) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Per-sorted-row inclusive [lo, hi] index arrays (clamped to the
        partition); hi < lo encodes an empty frame."""
        if frame is None:
            if node.order_by:
                return part_anchor, peer_end  # RANGE UNBOUNDED..CURRENT
            return part_anchor, part_end
        if frame.type_ == "RANGE" and (
            frame.start_kind in ("PRECEDING", "FOLLOWING")
            or frame.end_kind in ("PRECEDING", "FOLLOWING")
        ):
            raise NotImplementedError(
                "RANGE frames with value offsets are not supported yet"
            )
        rows = frame.type_ == "ROWS"

        def bound(kind, value, is_start):
            if kind == "UNBOUNDED_PRECEDING":
                return part_anchor
            if kind == "UNBOUNDED_FOLLOWING":
                return part_end
            if kind == "CURRENT_ROW":
                if rows:
                    return idx
                return peer_anchor if is_start else peer_end
            delta = int(value)
            return idx - delta if kind == "PRECEDING" else idx + delta

        lo = jnp.maximum(bound(frame.start_kind, frame.start_value, True), part_anchor)
        hi = jnp.minimum(bound(frame.end_kind, frame.end_value, False), part_end)
        return lo, hi

    def framed_sum(vals: jnp.ndarray, lo, hi) -> jnp.ndarray:
        """Inclusive [lo, hi] segment sums via one prefix sum."""
        ps = K.cumsum(vals)
        lo_c = jnp.clip(lo, 0, cap - 1)
        hi_c = jnp.clip(hi, 0, cap - 1)
        s = ps[hi_c] - ps[lo_c] + vals[lo_c]
        return jnp.where(hi >= lo, s, jnp.zeros_like(s))

    out_cols = list(rel.page.columns)
    out_symbols = list(rel.symbols)
    for sym, wf in node.functions:
        name = wf.function
        if name == "row_number":
            vals_s = (idx - part_anchor + 1).astype(jnp.int64)
            col = Column(BIGINT, vals_s[inv], active)
        elif name == "rank":
            vals_s = (peer_anchor - part_anchor + 1).astype(jnp.int64)
            col = Column(BIGINT, vals_s[inv], active)
        elif name == "dense_rank":
            c = jnp.cumsum(peer_start.astype(jnp.int64))
            vals_s = c - c[part_anchor] + 1
            col = Column(BIGINT, vals_s[inv], active)
        elif name == "percent_rank":
            r = (peer_anchor - part_anchor).astype(jnp.float64)
            denom = jnp.maximum(count_here - 1, 1).astype(jnp.float64)
            vals_s = jnp.where(count_here > 1, r / denom, 0.0)
            col = Column(DOUBLE, vals_s[inv], active)
        elif name == "cume_dist":
            n_le = (peer_end - part_anchor + 1).astype(jnp.float64)
            vals_s = n_le / jnp.maximum(count_here, 1).astype(jnp.float64)
            col = Column(DOUBLE, vals_s[inv], active)
        elif name == "ntile":
            n = int(_const_param(wf, 0, "ntile bucket count"))
            n = max(n, 1)
            r = (idx - part_anchor).astype(jnp.int64)
            size = count_here // n
            rem = count_here % n
            # first `rem` buckets take one extra row (ref: NTileFunction.java)
            threshold = (size + 1) * rem
            vals_s = jnp.where(
                (r < threshold) | (size == 0),
                r // jnp.maximum(size + 1, 1),
                rem + (r - threshold) // jnp.maximum(size, 1),
            ) + 1
            col = Column(BIGINT, vals_s[inv], active)
        elif name in ("lead", "lag"):
            arg = rel.column_for(wf.args[0])
            offset = 1
            if len(wf.args) > 1:
                offset = int(_const_param(wf, 1, f"{name} offset"))
            default = None
            if len(wf.args) > 2:
                default = _const_param(wf, 2, f"{name} default", allow_none=True)
            shift = -offset if name == "lead" else offset
            data_s = arg.data[perm]
            valid_s = arg.valid[perm]
            rolled = jnp.roll(data_s, shift)
            rolled_valid = jnp.roll(valid_s, shift)
            rolled_pid = jnp.roll(pid, shift)
            rolled_active = jnp.roll(active_s, shift)
            # jnp.roll wraps; positions whose source crossed the array edge
            # must not alias another partition's rows
            in_range = (idx + shift >= 0) & (idx + shift < cap)
            same = (rolled_pid == pid) & active_s & rolled_active & in_range
            out_data = rolled
            out_valid = same & rolled_valid
            if default is not None:
                if arg.dictionary is not None:
                    code = arg.dictionary.code_of(default)
                    if code < 0:
                        raise NotImplementedError(
                            f"{name} default not in the column dictionary"
                        )
                    fill = jnp.int32(code)
                else:
                    fill = jnp.asarray(default, dtype=data_s.dtype)
                out_data = jnp.where(same, rolled, fill)
                out_valid = jnp.where(same, out_valid, active_s)
            col = Column(arg.type, out_data[inv], out_valid[inv], arg.dictionary)
        elif name in _AGG_FUNCS:
            lo, hi = frame_bounds(wf.frame)
            if wf.args:
                arg = rel.column_for(wf.args[0])
                vals_s = arg.data[perm]
                valid_s = arg.valid[perm]
            else:
                arg = None
                vals_s = jnp.ones(cap, dtype=jnp.int64)
                valid_s = jnp.ones(cap, dtype=jnp.bool_)
            w = active_s & valid_s
            cnt = framed_sum(w.astype(jnp.int64), lo, hi)
            if name == "count":
                agg = cnt
                out_type, out_valid = BIGINT, active_s
            elif name in ("min", "max"):
                if jnp.issubdtype(vals_s.dtype, jnp.floating):
                    sent = jnp.inf if name == "min" else -jnp.inf
                    masked = jnp.where(w, vals_s, sent)
                else:
                    info = jnp.iinfo(jnp.int64)
                    sent = info.max if name == "min" else info.min
                    masked = jnp.where(w, vals_s.astype(jnp.int64), sent)
                # running scans with partition resets cover frames anchored at
                # a partition edge (prefix/suffix/whole); the anchoring is a
                # STATIC property of the frame spec
                f = wf.frame
                prefix_anchored = f is None or f.start_kind == "UNBOUNDED_PRECEDING"
                suffix_anchored = f is not None and f.end_kind == "UNBOUNDED_FOLLOWING"
                if prefix_anchored:
                    run_fwd = _running_extreme(masked, new_part, name)
                    agg = run_fwd[jnp.clip(hi, 0, cap - 1)]
                elif suffix_anchored:
                    next_part = jnp.roll(new_part, -1).at[-1].set(True)
                    run_bwd = jnp.flip(
                        _running_extreme(jnp.flip(masked), jnp.flip(next_part), name)
                    )
                    agg = run_bwd[jnp.clip(lo, 0, cap - 1)]
                else:
                    raise NotImplementedError(
                        f"{name} over a frame bounded on both sides is not "
                        "supported yet"
                    )
                out_type, out_valid = wf.output_type, active_s & (cnt > 0)
            else:  # sum / avg
                acc = jnp.float64 if (arg is not None and is_floating(arg.type)) else jnp.int64
                agg = framed_sum(jnp.where(w, vals_s.astype(acc), 0).astype(acc), lo, hi)
                out_type, out_valid = wf.output_type, active_s & (cnt > 0)
                if name == "avg":
                    if isinstance(out_type, DecimalType):
                        # decimal avg keeps scale: round-half-up division
                        half = cnt // 2
                        denom = jnp.maximum(cnt, 1)
                        agg = jnp.where(
                            agg >= 0,
                            (agg + half) // denom,
                            -((-agg + half) // denom),
                        )
                    else:
                        agg = agg.astype(jnp.float64) / jnp.maximum(cnt, 1)
                        if arg is not None and isinstance(arg.type, DecimalType):
                            agg = agg / float(10**arg.type.scale)
            dt = out_type.storage_dtype
            col = Column(
                out_type,
                agg.astype(dt)[inv],
                out_valid[inv] if out_valid is not None else active,
                arg.dictionary if (arg is not None and name in ("min", "max")) else None,
            )
        elif name in ("first_value", "last_value", "nth_value"):
            arg = rel.column_for(wf.args[0])
            data_s = arg.data[perm]
            valid_s = arg.valid[perm]
            lo, hi = frame_bounds(wf.frame)
            if name == "first_value":
                pos = lo
                in_frame = hi >= lo
            elif name == "last_value":
                pos = hi
                in_frame = hi >= lo
            else:
                n_arg = int(_const_param(wf, 1, "nth_value offset"))
                pos = lo + max(n_arg, 1) - 1
                in_frame = pos <= hi
            pos = jnp.clip(pos, 0, cap - 1)
            col = Column(
                arg.type,
                data_s[pos][inv],
                (valid_s[pos] & in_frame & active_s)[inv],
                arg.dictionary,
            )
        else:
            raise NotImplementedError(f"window function {name}")
        out_cols.append(col)
        out_symbols.append(sym)

    return Relation(Page(tuple(out_cols), active), tuple(out_symbols))
