"""Native LZ4 codec + page wire serde tests (ref: PagesSerdeFactory tests +
TestingPagesSerdeFactory roundtrips)."""

import numpy as np
import pytest

from trino_tpu import BIGINT, DOUBLE, Column, Page, native
from trino_tpu.runtime.serde import deserialize_page, serialize_page
from trino_tpu.spi.page import Dictionary


needs_native = pytest.mark.skipif(
    not native.native_available(), reason="g++ toolchain unavailable"
)


@needs_native
class TestLz4:
    def test_roundtrip_random(self):
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
        comp = native.lz4_compress(data)
        assert native.lz4_decompress(comp, len(data)) == data

    def test_roundtrip_compressible(self):
        data = (b"abcd" * 10_000) + bytes(50_000)
        comp = native.lz4_compress(data)
        assert len(comp) < len(data) // 10  # highly repetitive -> >10x
        assert native.lz4_decompress(comp, len(data)) == data

    def test_empty_and_tiny(self):
        for data in [b"", b"x", b"hello world"]:
            comp = native.lz4_compress(data)
            assert native.lz4_decompress(comp, len(data)) == data

    def test_corrupt_raises(self):
        data = b"abcd" * 1000
        comp = bytearray(native.lz4_compress(data))
        comp[0] ^= 0xFF
        with pytest.raises((ValueError, RuntimeError)):
            native.lz4_decompress(bytes(comp), len(data))

    def test_hash64_distinct(self):
        a = native.hash64(b"hello")
        b = native.hash64(b"hellp")
        assert a != b
        assert native.hash64(b"hello") == a


class TestPageSerde:
    def _page(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        ints = Column.from_numpy(
            BIGINT, rng.integers(0, 50, 1000), valid=rng.random(1000) > 0.1
        )
        dbls = Column.from_numpy(DOUBLE, rng.random(1000))
        strs = Column.from_strings(
            [["apple", "pear", None, "fig"][i % 4] for i in range(1000)]
        )
        active = np.ones(1000, dtype=np.bool_)
        active[990:] = False
        return Page((ints, dbls, strs), jnp.asarray(active))

    def test_roundtrip(self):
        page = self._page()
        wire = serialize_page(page)
        back = deserialize_page(wire)
        assert back.to_pylist() == page.to_pylist()
        assert back.columns[2].dictionary is not None

    def test_roundtrip_uncompressed(self):
        page = self._page()
        wire = serialize_page(page, compress=False)
        assert deserialize_page(wire).to_pylist() == page.to_pylist()

    @needs_native
    def test_compression_shrinks_wire(self):
        page = self._page()  # low-cardinality ints compress well
        assert len(serialize_page(page, compress=True)) < len(
            serialize_page(page, compress=False)
        )

    @needs_native
    def test_checksum_detects_corruption(self):
        wire = bytearray(serialize_page(self._page()))
        wire[-10] ^= 0xFF
        with pytest.raises(ValueError):
            deserialize_page(bytes(wire))
