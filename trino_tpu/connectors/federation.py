"""DB-API federation connector — query external SQL databases as catalogs.

Reference blueprint: plugin/trino-base-jdbc (JdbcClient.java:56 — metadata
from the remote catalog, QueryBuilder rendering pushed-down TupleDomains into
WHERE clauses, JdbcSplit) and its per-database plugins (trino-sqlite is not in
the reference tree, but trino-postgresql/mysql follow the same shape). The
engine analogue federates over any Python DB-API 2.0 driver; sqlite3 (stdlib)
is the bundled dialect, playing the role the JDBC drivers play there.

TPU-first adjustment: a split fetches its whole rowid range into ONE
fixed-capacity Page (strings dictionary-encoded at ingest) so downstream
execution is a single XLA program per split, not a row stream.
"""

from __future__ import annotations

import datetime
import re
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..spi.connector import (
    ColumnMetadata,
    ColumnStatistics,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    Split,
    TableHandle,
    TableMetadata,
    SchemaTableName,
    TableStatistics,
)
from ..spi.page import Column, Page
from ..spi.predicate import Domain, TupleDomain
from ..spi.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    Type,
    VarcharType,
    is_string,
)

_EPOCH = datetime.date(1970, 1, 1)


class Dialect:
    """Remote-dialect hooks (the JdbcClient surface a per-database plugin
    overrides). The base implementation targets sqlite."""

    def quote(self, ident: str) -> str:
        return '"' + ident.replace('"', '""') + '"'

    def list_tables(self, conn) -> List[str]:
        cur = conn.execute(
            "SELECT name FROM sqlite_master WHERE type IN ('table', 'view') "
            "AND name NOT LIKE 'sqlite_%'"
        )
        return [r[0] for r in cur.fetchall()]

    def table_columns(self, conn, table: str) -> List[Tuple[str, str]]:
        cur = conn.execute(f"PRAGMA table_info({self.quote(table)})")
        return [(r[1], r[2] or "") for r in cur.fetchall()]

    def map_type(self, decl: str) -> Optional[Type]:
        d = decl.upper()
        if re.search(r"INT", d):
            return BIGINT
        if re.search(r"CHAR|CLOB|TEXT", d):
            return VarcharType()
        if re.search(r"REAL|FLOA|DOUB|NUMERIC|DECIMAL", d):
            return DOUBLE
        if "BOOL" in d:
            return BOOLEAN
        if "DATE" in d:
            return DATE
        if d == "":
            # sqlite columns may be declared without affinity; treat as text
            return VarcharType()
        return None

    def rowid_bounds(self, conn, table: str) -> Optional[Tuple[int, int]]:
        try:
            cur = conn.execute(
                f"SELECT min(rowid), max(rowid) FROM {self.quote(table)}"
            )
            lo, hi = cur.fetchone()
            if lo is None:
                return None
            return int(lo), int(hi)
        except Exception:
            return None  # WITHOUT ROWID / views

    def literal(self, v: Any, type_: Type) -> str:
        if v is None:
            return "NULL"
        if type_ is DATE and isinstance(v, (int, np.integer)):
            return f"'{(_EPOCH + datetime.timedelta(days=int(v))).isoformat()}'"
        if is_string(type_) or isinstance(v, str):
            return "'" + str(v).replace("'", "''") + "'"
        if type_ is BOOLEAN:
            return "1" if v else "0"
        return repr(float(v)) if isinstance(v, float) else repr(int(v))


@dataclass(frozen=True)
class _FedHandle:
    """connector_handle payload: pushed-down constraint."""

    constraint: TupleDomain = TupleDomain.all()


class DbApiConnector(Connector):
    """Federate one remote database as a single-schema catalog.

    ``connect_fn`` returns a NEW DB-API connection (connections are
    thread-affine in sqlite; one is opened per thread, like the reference's
    per-task JDBC connections)."""

    name = "federation"

    def __init__(self, connect_fn: Callable[[], Any], schema: str = "default",
                 dialect: Optional[Dialect] = None, split_rows: int = 1 << 20,
                 metadata_ttl_secs: float = 10.0):
        self._connect_fn = connect_fn
        self._schema = schema
        self._dialect = dialect or Dialect()
        self._split_rows = split_rows
        self._meta_ttl = metadata_ttl_secs
        self._meta_cache: Dict[SchemaTableName, Tuple[float, Optional[TableMetadata]]] = {}
        self._meta_lock = threading.Lock()
        self._tls = threading.local()
        self._meta = _FedMetadata(self)
        self._splits = _FedSplitManager(self)
        self._pages = _FedPageSourceProvider(self)

    def _conn(self):
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = self._connect_fn()
            self._tls.conn = conn
        return conn

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages


class _FedMetadata(ConnectorMetadata):
    def __init__(self, c: DbApiConnector):
        self._c = c

    def list_schemas(self):
        return [self._c._schema]

    def list_tables(self, schema: Optional[str] = None):
        if schema is not None and schema != self._c._schema:
            return []
        d = self._c._dialect
        return [
            SchemaTableName(self._c._schema, t)
            for t in d.list_tables(self._c._conn())
        ]

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        if name.schema != self._c._schema:
            return None
        import time

        # short-TTL cache: one query resolves the same table several times
        # (planner, executor, page source) — don't round-trip each time
        # (the JdbcClient metadata caching analogue)
        with self._c._meta_lock:
            hit = self._c._meta_cache.get(name)
            if hit is not None and time.time() - hit[0] < self._c._meta_ttl:
                return hit[1]
        meta = self._load_table_metadata(name)
        with self._c._meta_lock:
            self._c._meta_cache[name] = (time.time(), meta)
        return meta

    def _load_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        d = self._c._dialect
        conn = self._c._conn()
        if name.table not in set(d.list_tables(conn)):
            return None
        cols = []
        for cname, decl in d.table_columns(conn, name.table):
            t = d.map_type(decl)
            if t is not None:
                cols.append(ColumnMetadata(cname, t))
        if not cols:
            return None
        return TableMetadata(name, tuple(cols))

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        d = self._c._dialect
        conn = self._c._conn()
        try:
            cur = conn.execute(
                f"SELECT count(*) FROM {d.quote(handle.schema_table.table)}"
            )
            n = float(cur.fetchone()[0])
        except Exception:
            return TableStatistics()
        return TableStatistics(row_count=n)

    def apply_filter(self, handle: TableHandle, domain: TupleDomain):
        # absorbed into the remote WHERE clause (QueryBuilder.java analogue)
        prev = handle.connector_handle or _FedHandle()
        return TableHandle(
            handle.catalog,
            handle.schema_table,
            connector_handle=_FedHandle(prev.constraint.intersect(domain)),
        )


class _FedSplitManager(ConnectorSplitManager):
    def __init__(self, c: DbApiConnector):
        self._c = c

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        d = self._c._dialect
        bounds = d.rowid_bounds(self._c._conn(), handle.schema_table.table)
        if bounds is None or desired_splits <= 1:
            return [Split(handle, 0, 1, info=None)]
        lo, hi = bounds
        n = min(desired_splits, max(1, (hi - lo) // self._c._split_rows + 1))
        edges = np.linspace(lo, hi + 1, n + 1).astype(np.int64)
        return [
            Split(handle, i, n, info=(int(edges[i]), int(edges[i + 1])))
            for i in range(n)
        ]


def _render_where(dialect: Dialect, meta: TableMetadata,
                  constraint: TupleDomain, rowid_range) -> str:
    conjuncts: List[str] = []
    types = {c.name: c.type for c in meta.columns}
    for col, dom in constraint.as_dict().items():
        t = types.get(col)
        if t is None:
            continue
        q = dialect.quote(col)
        if dom.none:
            # contradiction: nulls may still pass when allowed (IS NULL), else
            # nothing can (0=1) — prune remotely instead of fetching the table
            conjuncts.append(f"({q} IS NULL)" if dom.nulls_allowed else "(0=1)")
            continue
        parts: List[str] = []
        r = dom.range
        if dom.in_values is not None:
            vals = ", ".join(dialect.literal(v, t) for v in sorted(dom.in_values))
            parts.append(f"{q} IN ({vals})" if vals else "0=1")
        else:
            if r.low is not None:
                op = ">=" if r.low_inclusive else ">"
                parts.append(f"{q} {op} {dialect.literal(r.low, t)}")
            if r.high is not None:
                op = "<=" if r.high_inclusive else "<"
                parts.append(f"{q} {op} {dialect.literal(r.high, t)}")
        clause = " AND ".join(parts) if parts else None
        if dom.nulls_allowed:
            clause = f"({clause} OR {q} IS NULL)" if clause else None
        elif clause is None:
            clause = f"{q} IS NOT NULL"
        if clause:
            conjuncts.append(f"({clause})")
    if rowid_range is not None:
        conjuncts.append(f"rowid >= {rowid_range[0]} AND rowid < {rowid_range[1]}")
    return (" WHERE " + " AND ".join(conjuncts)) if conjuncts else ""


class _FedPageSourceProvider(ConnectorPageSourceProvider):
    def __init__(self, c: DbApiConnector):
        self._c = c

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        c = self._c
        d = c._dialect
        meta = c._meta.get_table_metadata(split.table.schema_table)
        if meta is None:
            raise ValueError(f"table not found: {split.table.schema_table}")
        cols = [meta.columns[i] for i in column_indexes]
        fh: _FedHandle = split.table.connector_handle or _FedHandle()
        select = ", ".join(d.quote(cm.name) for cm in cols) or "1"
        sql = (
            f"SELECT {select} FROM {d.quote(split.table.schema_table.table)}"
            + _render_where(d, meta, fh.constraint, split.info)
        )
        rows = c._conn().execute(sql).fetchall()
        n = len(rows)
        out: List[Column] = []
        for j, cm in enumerate(cols):
            values = [r[j] for r in rows]
            out.append(_column_from_values(cm.type, values, max(n, 1)))
        return Page(tuple(out), _active_mask(n, max(n, 1)))


def _active_mask(n: int, cap: int):
    import jax.numpy as jnp

    m = np.zeros(cap, dtype=np.bool_)
    m[:n] = True
    return jnp.asarray(m)


def _column_from_values(t: Type, values: List[Any], cap: int) -> Column:
    if is_string(t):
        strings = [None if v is None else str(v) for v in values]
        return Column.from_strings(strings, t, capacity=cap)
    valid = np.array([v is not None for v in values], dtype=np.bool_)
    if t is DATE:
        days = [
            0 if v is None else (datetime.date.fromisoformat(str(v)[:10]) - _EPOCH).days
            for v in values
        ]
        return Column.from_numpy(t, np.asarray(days, dtype=np.int64), valid, cap)
    if t is BOOLEAN:
        data = np.array([bool(v) if v is not None else False for v in values])
        return Column.from_numpy(t, data, valid, cap)
    if t is DOUBLE:
        data = np.array(
            [float(v) if v is not None else 0.0 for v in values], dtype=np.float64
        )
        return Column.from_numpy(t, data, valid, cap)
    data = np.array(
        [int(v) if v is not None else 0 for v in values], dtype=np.int64
    )
    return Column.from_numpy(t, data, valid, cap)
