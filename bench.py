#!/usr/bin/env python
"""Benchmark: TPC-H Q6 at SF1 through the full engine on the available device.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

- metric: tpch_q6_sf1_rows_per_sec — lineitem rows scanned per second through
  the compiled scan->filter->project->sum pipeline (steady-state, data resident
  in device memory; the BASELINE.json config #1 workload).
- vs_baseline: speedup vs single-thread numpy computing the identical Q6 over
  the identical host arrays (the stand-in for the JVM operator pipeline until a
  reference Trino cluster is benchmarked; BASELINE.md records that the Trino
  repo publishes no absolute numbers).
"""

import json
import os
import sys
import time

import numpy as np

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

# BASELINE ladder config #2: multi-key group-by (GroupByHash path)
Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price, avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
"""


def numpy_baseline(scale: float):
    """Single-thread numpy Q6 over the same generated data; returns (result, secs)."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.connectors.tpch import generator as g

    conn = TpchConnector(scale=scale)
    total = conn.split_count("lineitem", scale)
    cols = {"l_shipdate": [], "l_discount": [], "l_quantity": [], "l_extendedprice": []}
    for s in range(total):
        data = g.generate_split("lineitem", scale, s, total)
        for k in cols:
            cols[k].append(data.columns[k])
    arrs = {k: np.concatenate(v) for k, v in cols.items()}
    lo = (np.datetime64("1994-01-01") - np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64("1995-01-01") - np.datetime64("1970-01-01")).astype(int)

    def run():
        m = (
            (arrs["l_shipdate"] >= lo)
            & (arrs["l_shipdate"] < hi)
            & (arrs["l_discount"] >= 5)
            & (arrs["l_discount"] <= 7)
            & (arrs["l_quantity"] < 2400)
        )
        return np.sum(arrs["l_extendedprice"][m] * arrs["l_discount"][m])

    run()  # warm page cache
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        result = run()
        times.append(time.perf_counter() - t0)
    return result, min(times), len(arrs["l_shipdate"])


def _device_healthcheck(timeout_secs: int = 150) -> None:
    """The remote-TPU tunnel can wedge (see BASELINE.md notes), and a hung
    device call blocks in native code where signals can't interrupt it — so the
    probe runs in a subprocess with a hard timeout. On failure the parent pins
    the CPU backend before its own first device use, so the benchmark always
    produces its line."""
    import subprocess

    import jax

    probe = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "np.asarray(jax.jit(lambda a: a * 2 + 1)(jnp.ones(8)))"
    )
    try:
        subprocess.run(
            [sys.executable, "-c", probe],
            timeout=timeout_secs,
            check=True,
            capture_output=True,
        )
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        sys.stderr.write("bench: device unhealthy, falling back to CPU backend\n")
        jax.config.update("jax_platforms", "cpu")


def main():
    scale = float(os.environ.get("BENCH_SCALE", "1"))
    runs = int(os.environ.get("BENCH_RUNS", "10"))

    import jax

    import trino_tpu  # noqa: F401  (enables x64)

    _device_healthcheck()
    from trino_tpu.runtime import LocalQueryRunner
    from trino_tpu.runtime.traced import compile_query

    t0 = time.time()
    runner = LocalQueryRunner.tpch(scale=scale)
    plan = runner.plan_sql(Q6)
    fn, pages, names = compile_query(plan, runner.metadata, runner.session)
    jfn = jax.jit(fn)
    gen_secs = time.time() - t0

    # rows scanned — computed from generator metadata, NOT from the device pages:
    # with the remote-TPU tunnel, touching the page buffers with any other
    # program (even an eager device-side count) degrades every later execution
    # to a full input re-upload (~0.45s for SF1)
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.connectors.tpch import generator as g

    conn = runner.catalogs.get("tpch")
    nsplits = conn.split_count("lineitem", scale)
    total_rows = sum(
        g.lineitem_split_rows(scale, s, nsplits) for s in range(nsplits)
    )

    # Timing strategy for the remote-TPU tunnel: block_until_ready returns
    # before compute finishes, and any host fetch forces input re-upload on
    # later dispatches. So we run K chained query iterations inside ONE device
    # program (each iteration data-depends on the previous result, defeating
    # CSE) and take the slope between two K values — fixed costs (upload, RTT)
    # cancel, leaving pure per-query device time.
    import jax.numpy as jnp
    from jax import lax

    def make_looped(k: int):
        def looped(*scan_pages):
            def body(i, carry):
                # data-dependent no-op perturbation: active & (carry >= 0)
                bit = carry >= jnp.int64(-(10**18))
                perturbed = [
                    type(p)(p.columns, p.active & bit) for p in scan_pages
                ]
                out = fn(*perturbed)
                return carry + out.columns[0].data[0]

            return lax.fori_loop(0, k, body, jnp.int64(0))

        return jax.jit(looped)

    k1, k2 = 8, 72
    f1, f2 = make_looped(k1), make_looped(k2)
    t0 = time.time()
    _ = np.asarray(f1(*pages))  # compile + run
    _ = np.asarray(f2(*pages))
    compile_secs = time.time() - t0

    def timed(f):
        best = float("inf")
        for _ in range(max(3, runs // 3)):
            t0 = time.perf_counter()
            _ = np.asarray(f(*pages))
            best = min(best, time.perf_counter() - t0)
        return best

    t_k1 = timed(f1)
    t_k2 = timed(f2)
    best = max((t_k2 - t_k1) / (k2 - k1), 1e-9)
    times = [t_k1, t_k2]

    out = jfn(*pages)
    engine_result = out.to_pylist()[0][0]

    # secondary ladder metric: Q1 group-by through the traced path
    q1_plan = runner.plan_sql(Q1)
    q1_fn, q1_pages, _ = compile_query(q1_plan, runner.metadata, runner.session)

    def make_q1_looped(k: int):
        def looped(*scan_pages):
            def body(i, carry):
                bit = carry >= jnp.int64(-(10**18))
                perturbed = [type(p)(p.columns, p.active & bit) for p in scan_pages]
                res = q1_fn(*perturbed)
                return carry + res.columns[2].data[0]

            return lax.fori_loop(0, k, body, jnp.int64(0))

        return jax.jit(looped)

    try:
        import signal

        def _q1_timeout(signum, frame):
            raise TimeoutError("q1 measurement timed out")

        signal.signal(signal.SIGALRM, _q1_timeout)
        signal.alarm(int(os.environ.get("BENCH_Q1_TIMEOUT", "240")))
        g1, g2 = make_q1_looped(2), make_q1_looped(10)
        _ = np.asarray(g1(*q1_pages))
        _ = np.asarray(g2(*q1_pages))

        def timed_q1(f):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                _ = np.asarray(f(*q1_pages))
                best = min(best, time.perf_counter() - t0)
            return best

        q1_secs = max((timed_q1(g2) - timed_q1(g1)) / 8, 1e-9)
        signal.alarm(0)
    except Exception as e:  # noqa: BLE001 — Q1 is informational detail
        q1_secs = None
        q1_err = f"{type(e).__name__}: {e}"
    finally:
        try:
            signal.alarm(0)
        except Exception:
            pass

    np_result, np_secs, np_rows = numpy_baseline(scale)
    # cross-check correctness against the host baseline (scaled decimal: 1e-4)
    np_revenue = np_result / 10**4
    assert np_rows == total_rows, (np_rows, total_rows)
    assert abs(float(engine_result) - np_revenue) <= 1e-6 * max(1.0, abs(np_revenue)), (
        engine_result,
        np_revenue,
    )

    rows_per_sec = total_rows / best
    baseline_rps = np_rows / np_secs
    record = {
        "metric": f"tpch_q6_sf{scale:g}_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / baseline_rps, 3),
        "detail": {
            "device": jax.devices()[0].device_kind,
            "backend": jax.default_backend(),
            "query_secs_best": round(best, 6),
            "loop_secs_k8_k72": [round(t, 6) for t in times],
            "numpy_secs": round(np_secs, 6),
            "rows": total_rows,
            "compile_secs": round(compile_secs, 2),
            "datagen_secs": round(gen_secs, 2),
            "revenue": float(engine_result),
        },
    }
    if q1_secs is not None:
        record["detail"]["q1_secs"] = round(q1_secs, 6)
        record["detail"]["q1_rows_per_sec"] = round(total_rows / q1_secs, 1)
    else:
        record["detail"]["q1_error"] = q1_err
    print(json.dumps(record))


if __name__ == "__main__":
    main()
