"""Device cost observability plane: XLA cost-model attribution + roofline.

Every BENCH number since round 5 is CPU-labeled, and per-operator *wall*
time has existed since rounds 2/17 — but the engine could not say what a
device program COSTS: FLOPs, HBM bytes, and peak device memory were
invisible, so "memory-bound vs compute-bound" was folklore.  "Query
Processing on Tensor Computation Runtimes" (arXiv:2203.01877) is the
measurement playbook this module implements; arXiv:2606.24647 (GPU-Presto)
motivates the roofline framing that makes offload decisions auditable.

Three pieces:

- :func:`jit` — THE engine-wide ``jax.jit`` wrapper.  It is a transparent
  pass-through (same jitted callable, zero extra dispatch work, byte-
  identical results) until a recording scope is installed; then each call
  attributes the program's XLA ``cost_analysis()`` (flops, bytes accessed)
  and compiled ``memory_analysis()`` (argument/output/temp HBM) to the
  scope's plan node.  Records are keyed like the capstore program cache:
  sha256 of (label, plan-node structural fingerprint, platform, abstract
  arg signature) — stable across processes — and persisted as a sibling
  file of ``$TRINO_TPU_CAP_STORE`` so warm processes (whose jit dispatch
  never lowers: the XLA compile cache hit) still attribute without paying
  a re-trace.  The engine lint rule ``jit-without-cost-hook`` pins every
  ``jax.jit`` call site in ``trino_tpu/`` to this wrapper.
- :func:`attributing` — the per-plan-node recording scope the executor's
  stats path installs (EXPLAIN ANALYZE VERBOSE / kernel_cost session
  property).  Scopes nest like operator evaluation does; a program records
  against the INNERMOST open scope.  Calls made while tracing an enclosing
  program (vmapped lanes, traced subplans) are skipped — the enclosing
  program is the one that launches, so it owns the cost.
- Roofline diagnosis — :func:`classify`/:func:`render_roofline` turn
  (flops, bytes, measured device seconds) into the one-line verdict
  EXPLAIN ANALYZE VERBOSE appends per operator::

      flops 1.2G · hbm 890MB · arith 1.3 flop/B → memory-bound,
      72% of roofline @ cpu

  Peak FLOP/s / bytes/s per platform come from ``$TRINO_TPU_ROOFLINE_PEAKS``
  (``"cpu=5e10:2e10,tpu=1.97e14:8.19e11"``); the built-in defaults are
  conservative placeholders, labeled as such in the output of
  :func:`roofline_peaks`.

Availability degrades, never raises: ``cost_analysis``/``memory_analysis``
vary by backend and jax version, Pallas interpret-mode programs may expose
neither, and a mesh/shard_map program may refuse to lower standalone — any
such path records a ``cost_unavailable`` row and ticks
``trino_tpu_kernel_cost_unavailable_total{reason}``.

Cluster-wide surface: every attribution lands in a bounded process ledger
behind ``system.runtime.kernel_costs``; with the round-17 federated plane
on, worker announcements piggyback a bounded ledger snapshot
(:func:`announcement_rows`) that the coordinator folds in
(:func:`ingest_federated`), so the system table shows every node's rows.
Paired ``kernel_cost`` flight spans ride the assembled cluster trace, and
each attribution bumps an ``hbm_watermark`` Perfetto counter track on the
recording thread's lane (the device-lane proxy).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

import jax

from .. import knobs

# --------------------------------------------------------------------------- #
# roofline peaks
# --------------------------------------------------------------------------- #

# conservative single-core/host-class placeholders (FLOP/s, bytes/s) — real
# deployments pin measured peaks via $TRINO_TPU_ROOFLINE_PEAKS; the point of
# shipping defaults is that the CLASSIFICATION (memory- vs compute-bound) is
# driven by arithmetic intensity vs the ridge point, which is robust to the
# absolute numbers being placeholder-grade
DEFAULT_PEAKS: Dict[str, Tuple[float, float]] = {
    "cpu": (5.0e10, 2.0e10),
    "tpu": (1.97e14, 8.19e11),
    "gpu": (9.89e13, 2.04e12),
    "interpreter": (5.0e10, 2.0e10),
}

ENV_PEAKS = "TRINO_TPU_ROOFLINE_PEAKS"


def roofline_peaks(platform: str) -> Tuple[float, float, str]:
    """(peak_flops_per_sec, peak_bytes_per_sec, provenance) for a platform.

    ``$TRINO_TPU_ROOFLINE_PEAKS`` format: ``platform=FLOPS:BYTES`` pairs,
    comma-separated — ``"cpu=5e10:2e10,tpu=1.97e14:8.19e11"``. Unparseable
    entries are ignored (a typo'd knob degrades to defaults, it does not
    take down EXPLAIN). Provenance is ``"env"`` or ``"default"`` so the
    output can say whether the pct-of-roofline is against a measured peak.
    """
    spec = knobs.env_str(ENV_PEAKS) or ""
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        name, _, vals = entry.partition("=")
        if name.strip().lower() != platform.lower():
            continue
        fl, _, by = vals.partition(":")
        try:
            pf, pb = float(fl), float(by)
        except ValueError:
            continue
        if pf > 0 and pb > 0:
            return pf, pb, "env"
    pf, pb = DEFAULT_PEAKS.get(platform.lower(), DEFAULT_PEAKS["cpu"])
    return pf, pb, "default"


def classify(
    flops: Optional[float],
    bytes_accessed: Optional[float],
    device_secs: Optional[float] = None,
    platform: Optional[str] = None,
) -> Optional[dict]:
    """Roofline verdict for one program (or one operator's aggregate).

    Returns ``None`` when the cost model gave us nothing to classify.
    ``roofline_pct`` is achieved FLOP/s over the roofline-attainable rate at
    this arithmetic intensity — only computable when a measured
    ``device_secs`` is supplied (EXPLAIN's fenced stats mode), ``None``
    otherwise (the honest answer for unmeasured ledger rows).
    """
    if not flops and not bytes_accessed:
        return None
    platform = platform or jax.default_backend()
    peak_flops, peak_bw, provenance = roofline_peaks(platform)
    flops = float(flops or 0.0)
    bytes_accessed = float(bytes_accessed or 0.0)
    ai = flops / bytes_accessed if bytes_accessed > 0 else None
    ridge = peak_flops / peak_bw
    if ai is None:
        bound = "compute-bound" if flops else "memory-bound"
    else:
        bound = "memory-bound" if ai < ridge else "compute-bound"
    attainable = (
        min(peak_flops, ai * peak_bw) if ai is not None else peak_flops
    )
    pct = None
    if device_secs and device_secs > 0 and attainable > 0 and flops > 0:
        pct = min((flops / device_secs) / attainable, 1.0)
    return {
        "platform": platform,
        "arithmetic_intensity": ai,
        "classification": bound,
        "attainable_flops_per_sec": attainable,
        "roofline_pct": pct,
        "peaks_provenance": provenance,
    }


def _si(v: float) -> str:
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.3g}{unit}"
    return f"{v:.3g}"


def _bytes_h(v: float) -> str:
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if abs(v) >= div:
            return f"{v / div:.3g}{unit}"
    return f"{v:.0f}B"


def render_roofline(
    flops: Optional[float],
    bytes_accessed: Optional[float],
    peak_hbm_bytes: Optional[float] = None,
    device_secs: Optional[float] = None,
    platform: Optional[str] = None,
) -> Optional[str]:
    """The EXPLAIN ANALYZE VERBOSE one-liner. ``None`` when unclassifiable
    (the caller renders ``cost_unavailable`` instead)."""
    verdict = classify(flops, bytes_accessed, device_secs, platform)
    if verdict is None:
        return None
    parts = []
    if flops:
        parts.append(f"flops {_si(float(flops))}")
    if bytes_accessed:
        parts.append(f"hbm {_bytes_h(float(bytes_accessed))}")
    if peak_hbm_bytes:
        parts.append(f"peak {_bytes_h(float(peak_hbm_bytes))}")
    ai = verdict["arithmetic_intensity"]
    if ai is not None:
        parts.append(f"arith {ai:.3g} flop/B")
    tail = verdict["classification"]
    if verdict["roofline_pct"] is not None:
        tail += f", {verdict['roofline_pct'] * 100.0:.0f}% of roofline"
    tail += f" @ {verdict['platform']}"
    return " · ".join(parts) + " → " + tail


# --------------------------------------------------------------------------- #
# unavailable accounting
# --------------------------------------------------------------------------- #


def _count_unavailable(reason: str) -> None:
    try:
        from .metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_kernel_cost_unavailable_total",
            labels={"reason": reason},
            help="kernel-cost attributions degraded to cost_unavailable",
        ).inc()
    except Exception:  # noqa: BLE001 — observability never fails the query
        pass


# --------------------------------------------------------------------------- #
# persisted record store (sibling of the capstore file)
# --------------------------------------------------------------------------- #

_store_lock = threading.Lock()
_record_cache: Dict[str, dict] = {}  # program key -> record (ok records only)
_persisted_cache: Optional[Dict[str, dict]] = None
_persisted_mtime: Optional[float] = None


def store_path() -> Optional[str]:
    """Persisted kernel-cost records live NEXT TO the capstore file (the
    two stores describe the same compiled programs: capstore the shapes,
    this one the costs), so one deployment knob provisions both."""
    from . import capstore

    base = capstore.store_path()
    return base + ".kernelcost" if base else None


def _read_persisted() -> Dict[str, dict]:
    global _persisted_cache, _persisted_mtime
    path = store_path()
    if path is None:
        return {}
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return {}
    if _persisted_cache is not None and _persisted_mtime == mtime:
        return _persisted_cache
    try:
        with open(path, "r") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    _persisted_cache, _persisted_mtime = data, mtime
    return data


def _persist(key: str, record: dict) -> None:
    global _persisted_cache, _persisted_mtime
    path = store_path()
    if path is None:
        return
    with _store_lock:
        data = dict(_read_persisted())
        data[key] = record
        d = os.path.dirname(os.path.abspath(path)) or "."
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".kernelcost-")
            with os.fdopen(fd, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
            _persisted_cache = data
            try:
                _persisted_mtime = os.stat(path).st_mtime
            except OSError:
                _persisted_mtime = None
        except OSError:
            pass


def clear_memory() -> None:
    """Test hook: drop the in-process record cache + persisted-file cache."""
    global _persisted_cache, _persisted_mtime
    with _store_lock:
        _record_cache.clear()
        _persisted_cache = None
        _persisted_mtime = None


# --------------------------------------------------------------------------- #
# attribution scopes
# --------------------------------------------------------------------------- #


class _Scope:
    __slots__ = ("node_key", "node_label", "sink", "query_id", "seen")

    def __init__(self, node_key: str, node_label: str, sink, query_id: str):
        self.node_key = node_key
        self.node_label = node_label
        self.sink = sink
        self.query_id = query_id
        self.seen: set = set()  # program keys already ledgered in this scope


_tls = threading.local()


def _stack() -> List[_Scope]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = []
        _tls.stack = stack
    return stack


def current_scope() -> Optional[_Scope]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextmanager
def attributing(
    node_key: str,
    node_label: str = "",
    sink=None,
    query_id: str = "",
):
    """Install a per-plan-node recording scope on this thread. Programs
    launched while the scope is innermost attribute to it; nested scopes
    (child operators) shadow it exactly like operator evaluation nests."""
    stack = _stack()
    scope = _Scope(node_key, node_label, sink, query_id)
    stack.append(scope)
    try:
        yield scope
    finally:
        stack.pop()


def session_enabled(session) -> bool:
    """The ``kernel_cost`` session property (default off: the wrapper is a
    pass-through and every output byte matches the unrecorded path)."""
    try:
        return bool(session.get("kernel_cost"))
    except KeyError:
        return False


# --------------------------------------------------------------------------- #
# the cost-recording jit wrapper
# --------------------------------------------------------------------------- #


def _static_token(v: Any) -> str:
    """Cross-process-stable token for a static argument. Callables (compiled
    expression closures) reduce to their qualname — the plan-node structural
    fingerprint in the record key is what disambiguates two closures with
    the same qualname (the closures are derived from the node's own
    expressions, which the fingerprint covers)."""
    if callable(v):
        return getattr(v, "__qualname__", None) or type(v).__name__
    if isinstance(v, (tuple, list)):
        return "(" + ",".join(_static_token(x) for x in v) + ")"
    return repr(v)


def _tree_signature(v: Any) -> Optional[str]:
    """Abstract (shape, dtype) signature of a dynamic argument's pytree;
    ``None`` when a leaf is a tracer — we are inside an enclosing program's
    trace, and THAT program owns the launch cost."""
    leaves, treedef = jax.tree_util.tree_flatten(v)
    sig = []
    for leaf in leaves:
        if isinstance(leaf, jax.core.Tracer):
            return None
        sig.append(
            (tuple(getattr(leaf, "shape", ())),
             str(getattr(leaf, "dtype", type(leaf).__name__)))
        )
    return f"{treedef}|{sig}"


class CostJit:
    """A ``jax.jit`` with the cost hook. Transparent: ``__call__`` is the
    jitted callable plus one thread-local read; every other attribute
    (``lower``, ``trace``, ``clear_cache``, ...) proxies to the jit."""

    def __init__(self, fun, label: str, jit_kwargs: dict):
        self._jit = jax.jit(fun, **jit_kwargs)  # lint: disable=jit-without-cost-hook -- the one sanctioned jax.jit: this IS the cost hook
        self.label = label
        static = jit_kwargs.get("static_argnums", ())
        if isinstance(static, int):
            static = (static,)
        self._static = frozenset(static or ())
        self.__wrapped__ = fun

    def __call__(self, *args, **kwargs):
        out = self._jit(*args, **kwargs)
        if current_scope() is not None:
            try:
                self._attribute(args, kwargs)
            except Exception:  # noqa: BLE001 — recording must never fail a query
                _count_unavailable("hook_error")
        return out

    def __getattr__(self, name):
        return getattr(self._jit, name)

    # ------------------------------------------------------------ recording

    def _signature(self, args, kwargs) -> Optional[str]:
        parts: List[str] = []
        for i, a in enumerate(args):
            if i in self._static:
                parts.append("s:" + _static_token(a))
            else:
                sig = _tree_signature(a)
                if sig is None:
                    return None
                parts.append("d:" + sig)
        for k in sorted(kwargs):
            sig = _tree_signature(kwargs[k])
            if sig is None:
                return None
            parts.append(f"k:{k}=" + sig)
        return ";".join(parts)

    def _attribute(self, args, kwargs) -> None:
        scope = current_scope()
        if scope is None:
            return
        sig = self._signature(args, kwargs)
        if sig is None:
            return  # tracing an enclosing program — it owns the cost
        platform = jax.default_backend()
        raw = f"{self.label}|{scope.node_key}|{platform}|{sig}"
        key = hashlib.sha256(raw.encode()).hexdigest()[:24]
        record = _record_cache.get(key)
        source = "memory"
        if record is None:
            persisted = _read_persisted().get(key)
            if isinstance(persisted, dict):
                # warm-process path: the XLA compile cache meant this
                # program never lowered here — attribute from the store
                record = dict(persisted)
                record["source"] = source = "store"
                _record_cache[key] = record
        if record is None:
            source = "computed"
            record = self._compute_record(key, platform, args, kwargs)
            _record_cache[key] = record
            if record.get("status") == "ok":
                _persist(key, {
                    k: v for k, v in record.items() if k != "source"
                })
        self._deliver(scope, key, record, source)

    def _compute_record(self, key, platform, args, kwargs) -> dict:
        from .observability import RECORDER

        record = {
            "label": self.label,
            "key": key,
            "platform": platform,
            "status": "ok",
            "source": "computed",
            "flops": None,
            "bytes_accessed": None,
            "argument_bytes": None,
            "output_bytes": None,
            "temp_bytes": None,
            "generated_code_bytes": None,
            "peak_hbm_bytes": None,
        }
        with RECORDER.span("kernel_cost", "kernelcost",
                           label=self.label, key=key) as sp:
            try:
                compiled = self._jit.lower(*args, **kwargs).compile()
            except Exception as e:  # noqa: BLE001 — degrade, never raise
                record["status"] = "cost_unavailable"
                record["reason"] = f"lower_failed:{type(e).__name__}"
                _count_unavailable("lower_failed")
                sp["status"] = record["status"]
                return record
            got_any = False
            try:
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else None
                if isinstance(ca, dict):
                    flops = float(ca.get("flops", -1.0))
                    nbytes = float(ca.get("bytes accessed", -1.0))
                    if flops >= 0:
                        record["flops"] = flops
                        got_any = True
                    if nbytes >= 0:
                        record["bytes_accessed"] = nbytes
                        got_any = True
            except Exception:  # noqa: BLE001
                pass
            try:
                ma = compiled.memory_analysis()
                total = 0.0
                for attr, field in (
                    ("argument_size_in_bytes", "argument_bytes"),
                    ("output_size_in_bytes", "output_bytes"),
                    ("temp_size_in_bytes", "temp_bytes"),
                    ("generated_code_size_in_bytes", "generated_code_bytes"),
                ):
                    v = getattr(ma, attr, None)
                    if v is not None:
                        record[field] = int(v)
                        if field != "generated_code_bytes":
                            total += float(v)
                        got_any = True
                if got_any:
                    # peak live HBM of one launch: arguments + outputs +
                    # XLA temp allocations (generated code is static)
                    record["peak_hbm_bytes"] = int(total)
            except Exception:  # noqa: BLE001
                pass
            if not got_any:
                record["status"] = "cost_unavailable"
                record["reason"] = "cost_analysis_unavailable"
                _count_unavailable("cost_analysis_unavailable")
            sp["status"] = record["status"]
            if record["flops"] is not None:
                sp["flops"] = record["flops"]
            if record["bytes_accessed"] is not None:
                sp["bytes_accessed"] = record["bytes_accessed"]
        return record

    def _deliver(self, scope: _Scope, key: str, record: dict, source: str) -> None:
        from .observability import RECORDER

        if scope.sink is not None:
            scope.sink(record)
        if key not in scope.seen:
            scope.seen.add(key)
            _ledger_append(scope, record)
        if RECORDER.enabled and record.get("peak_hbm_bytes"):
            # HBM-watermark counter track: one Perfetto "C" series per
            # recording thread (the device-lane proxy) — the assembled
            # cluster trace shows the live watermark under the span lanes
            RECORDER.counter_event(
                "hbm_watermark", "kernelcost",
                hbm_bytes=int(record["peak_hbm_bytes"]),
            )


def jit(fun=None, *, label: Optional[str] = None, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement with the cost hook; supports the
    decorator form (``@jit`` / ``@partial(jit, static_argnums=...)``) and
    the call form (``jit(fn, static_argnums=...)``)."""
    if fun is None:
        def deco(f):
            return jit(f, label=label, **jit_kwargs)
        return deco
    return CostJit(
        fun, label or getattr(fun, "__name__", "jit"), jit_kwargs
    )


# --------------------------------------------------------------------------- #
# process ledger + cluster federation
# --------------------------------------------------------------------------- #

_LEDGER_CAP = 512
_ledger_lock = threading.Lock()
_ledger: deque = deque(maxlen=_LEDGER_CAP)

ANNOUNCE_ROWS_MAX = 64  # bounded rider: announcements must stay heartbeat-sized
_FEDERATED_TTL_SECS = 300.0
_federated: Dict[str, Tuple[float, List[dict]]] = {}


def _ledger_append(scope: _Scope, record: dict) -> None:
    verdict = classify(
        record.get("flops"), record.get("bytes_accessed"),
        platform=record.get("platform"),
    ) or {}
    row = {
        "ts": time.time(),
        "query_id": scope.query_id,
        "plan_node": scope.node_label,
        "label": record.get("label"),
        "key": record.get("key"),
        "platform": record.get("platform"),
        "flops": record.get("flops"),
        "bytes_accessed": record.get("bytes_accessed"),
        "peak_hbm_bytes": record.get("peak_hbm_bytes"),
        "arithmetic_intensity": verdict.get("arithmetic_intensity"),
        "classification": verdict.get("classification"),
        "status": record.get("status"),
    }
    with _ledger_lock:
        _ledger.append(row)


def ledger_rows() -> List[dict]:
    with _ledger_lock:
        return list(_ledger)


def clear_ledger() -> None:
    """Test hook."""
    with _ledger_lock:
        _ledger.clear()
    with _store_lock:
        _federated.clear()


def announcement_rows(limit: int = ANNOUNCE_ROWS_MAX) -> List[dict]:
    """Bounded latest-rows snapshot a worker announcement piggybacks
    (federated plane rider, same discipline as announcement_metrics)."""
    with _ledger_lock:
        rows = list(_ledger)[-max(int(limit), 0):]
    return rows


def ingest_federated(node_id: str, rows) -> int:
    """Coordinator side: fold a worker's announced kernel-cost rows in.
    Last announcement wins per node; nodes silent past the TTL evict."""
    if not isinstance(rows, list):
        return 0
    clean = [r for r in rows if isinstance(r, dict)][:ANNOUNCE_ROWS_MAX]
    now = time.time()
    with _store_lock:
        _federated[node_id] = (now, clean)
        for nid in [
            n for n, (ts, _) in _federated.items()
            if now - ts > _FEDERATED_TTL_SECS
        ]:
            del _federated[nid]
    return len(clean)


def federated_rows() -> List[Tuple[str, dict]]:
    """(node_id, row) pairs from live announcements (TTL-pruned)."""
    now = time.time()
    out: List[Tuple[str, dict]] = []
    with _store_lock:
        for nid, (ts, rows) in _federated.items():
            if now - ts > _FEDERATED_TTL_SECS:
                continue
            out.extend((nid, r) for r in rows)
    return out
