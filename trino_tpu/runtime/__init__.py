from .local import LocalQueryRunner, QueryResult
from .executor import PlanExecutor, ExecutionError
