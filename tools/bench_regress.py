"""Noise-aware comparison of two `bench.py ladder` JSONs.

The regression half of ROADMAP item 1: a BENCH diff three PRs later is not a
gate; this is. Feed it a baseline ladder record and a candidate (both from
`python bench.py ladder`, schema_version >= 3) and it renders per-query
verdicts that respect measured dispersion:

- **regression** — the candidate median is slower by more than
  ``max(k * max(MADs), rel_floor * base_median, abs_floor)``. The k*MAD term
  is the noise gate (median-of-N with median-absolute-deviation is robust to
  the one-slow-run outliers wall benches always have); the floors keep a
  dead-quiet machine (MAD 0) from flagging microsecond jitter.
- **improvement** — faster by the same margin (symmetric, so a follow-up
  run's "improvement" on the inverse comparison corroborates a regression).
- **ok** — inside the noise band. An identical re-run is always ok.
- **result-changed** — result fingerprints disagree: the candidate computed
  a DIFFERENT answer, which outranks any timing delta.
- **missing** — the query ran in the baseline but not the candidate.

Cross-platform comparisons are refused (exit 2): a cpu-vs-tpu delta is a
hardware statement, not a regression verdict.

Exit codes: 0 = ok/improvement everywhere, 1 = any regression /
result-changed / missing, 2 = not comparable (schema or platform).
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional, Tuple

DEFAULT_K = 3.0
# noise floors for quiet machines: below both of these a delta is jitter,
# whatever the MADs say
ABS_FLOOR_SECS = 1e-3
REL_FLOOR = 0.05


def _schema_problems(record: dict, who: str) -> List[str]:
    problems = []
    if not isinstance(record, dict):
        return [f"{who}: not a JSON object"]
    if record.get("bench") != "ladder":
        problems.append(f"{who}: not a ladder record (bench={record.get('bench')!r})")
    if not isinstance(record.get("schema_version"), int) or record.get(
        "schema_version", 0
    ) < 3:
        problems.append(
            f"{who}: schema_version must be >= 3 "
            f"(got {record.get('schema_version')!r})"
        )
    if not record.get("platform"):
        problems.append(f"{who}: missing platform label")
    results = record.get("results")
    if not isinstance(results, dict) or not results:
        problems.append(f"{who}: missing results")
        return problems
    for name, r in results.items():
        if not isinstance(r, dict) or not isinstance(
            r.get("median_secs"), (int, float)
        ):
            problems.append(f"{who}: results[{name!r}] missing median_secs")
        elif not isinstance(r.get("mad_secs"), (int, float)):
            problems.append(f"{who}: results[{name!r}] missing mad_secs")
    return problems


def compare(base: dict, cand: dict, k: float = DEFAULT_K) -> dict:
    """Structured verdict document (the CLI prints exactly this)."""
    problems = _schema_problems(base, "base") + _schema_problems(cand, "candidate")
    if problems:
        return {"overall": "incomparable", "problems": problems}
    if base["platform"] != cand["platform"]:
        return {
            "overall": "incomparable",
            "problems": [
                f"platform mismatch: base={base['platform']!r} "
                f"candidate={cand['platform']!r} — cross-hardware deltas are "
                "not regressions"
            ],
        }
    queries = {}
    for name, b in base["results"].items():
        c = cand["results"].get(name)
        if c is None or not isinstance(c.get("median_secs"), (int, float)):
            queries[name] = {"verdict": "missing"}
            continue
        b_med = float(b["median_secs"])
        c_med = float(c["median_secs"])
        noise = k * max(float(b.get("mad_secs") or 0.0),
                        float(c.get("mad_secs") or 0.0))
        threshold = max(noise, REL_FLOOR * b_med, ABS_FLOOR_SECS)
        delta = c_med - b_med
        if b.get("fingerprint") and c.get("fingerprint") and (
            b["fingerprint"] != c["fingerprint"]
        ):
            verdict = "result-changed"
        elif delta > threshold:
            verdict = "regression"
        elif delta < -threshold:
            verdict = "improvement"
        else:
            verdict = "ok"
        queries[name] = {
            "verdict": verdict,
            "base_median_secs": b_med,
            "cand_median_secs": c_med,
            "delta_secs": round(delta, 6),
            "threshold_secs": round(threshold, 6),
        }
    bad = [n for n, q in queries.items()
           if q["verdict"] in ("regression", "result-changed", "missing")]
    return {
        "overall": "regression" if bad else "ok",
        "platform": base["platform"],
        "k": k,
        "flagged": sorted(bad),
        "queries": queries,
    }


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    k = DEFAULT_K
    paths: List[str] = []
    it = iter(argv)
    for a in it:
        if a == "--k":
            k = float(next(it, DEFAULT_K))
        elif a.startswith("--k="):
            k = float(a.split("=", 1)[1])
        else:
            paths.append(a)
    if len(paths) != 2:
        print(
            "usage: python tools/bench_regress.py [--k K] BASE.json CAND.json",
            file=sys.stderr,
        )
        return 2
    try:
        base, cand = _load(paths[0]), _load(paths[1])
    except (OSError, ValueError) as e:
        print(f"bench_regress: {e}", file=sys.stderr)
        return 2
    report = compare(base, cand, k=k)
    print(json.dumps(report, indent=2))
    if report["overall"] == "incomparable":
        return 2
    return 1 if report["overall"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main())
