"""Filesystem abstraction: object-store-shaped path API.

Reference blueprint: lib/trino-filesystem/src/main/java/io/trino/filesystem/
TrinoFileSystem.java:60 — the engine never touches java.io directly; every
reader/writer goes through a Location + TrinoFileSystem pair resolved per
scheme (s3/gcs/azure/hdfs/local implementations). This module is the same
contract shaped for the TPU engine's host side:

- a :class:`Location` is ``scheme://host/path``; schemes resolve through the
  :class:`FileSystemManager` registry.
- the API is OBJECT-STORE-shaped: no mkdir/rename primitives in the read
  path, listing is BY PREFIX, writes are whole-object puts with an atomic
  commit (temp + rename locally; multipart-put semantics on a real store).
  Code written against it ports to s3:// by registering another factory.

Only the local implementation ships (the image has no object-store creds);
the contract is what the lakehouse connector and the metastore build on.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple


@dataclass(frozen=True)
class Location:
    """Parsed storage location (ref: filesystem/Location.java)."""

    scheme: str
    path: str  # scheme-relative, no leading slash

    @staticmethod
    def parse(uri: str) -> "Location":
        if "://" not in uri:
            # bare paths are local (the reference maps them to file://)
            return Location("local", uri.lstrip("/"))
        scheme, _, rest = uri.partition("://")
        return Location(scheme.lower(), rest.lstrip("/"))

    def uri(self) -> str:
        return f"{self.scheme}://{self.path}"

    def child(self, *parts: str) -> "Location":
        path = "/".join([self.path.rstrip("/")] + [p.strip("/") for p in parts])
        return Location(self.scheme, path)

    @property
    def name(self) -> str:
        return self.path.rsplit("/", 1)[-1]


@dataclass(frozen=True)
class FileEntry:
    location: Location
    length: int


class TrinoFileSystem:
    """The per-scheme filesystem contract (TrinoFileSystem.java:60)."""

    def read(self, location: Location) -> bytes:
        raise NotImplementedError

    def write(self, location: Location, data: bytes) -> None:
        """Whole-object put, atomic: readers never observe partial objects."""
        raise NotImplementedError

    def write_if_absent(self, location: Location, data: bytes) -> bool:
        """Atomic create-EXCLUSIVE put: False when the object already
        exists (the optimistic-commit primitive — S3 If-None-Match / GCS
        precondition; iceberg-style metadata swaps race on it)."""
        raise NotImplementedError

    def read_with_etag(self, location: Location) -> Tuple[bytes, str]:
        """Read the object plus its etag (S3 GET returns both). The etag
        names the exact content version for a later :meth:`write_if_match`."""
        data = self.read(location)
        return data, hashlib.md5(data).hexdigest()

    def write_if_match(
        self, location: Location, data: bytes, etag: str
    ) -> Optional[str]:
        """Conditional put (S3 If-Match): replace the object ONLY if its
        current etag equals ``etag``. Returns the new etag on success, None
        when someone else won the race (or the object vanished). This is
        the CAS primitive every rename-free durable plane fences on."""
        raise NotImplementedError

    def delete(self, location: Location) -> None:
        raise NotImplementedError

    def exists(self, location: Location) -> bool:
        raise NotImplementedError

    def list_files(self, prefix: Location) -> Iterator[FileEntry]:
        """All objects whose path starts with ``prefix`` (recursive — the
        object-store model has no directories)."""
        raise NotImplementedError


class LocalFileSystem(TrinoFileSystem):
    """local:// filesystem rooted at a directory (filesystem/local/
    LocalFileSystem.java). Writes are temp-file + rename — the local stand-in
    for an object store's atomic put."""

    _tmp_seq = itertools.count()  # process-local: unique tmp names per writer

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._cas_lock = threading.Lock()

    def _os_path(self, location: Location) -> str:
        p = os.path.normpath(os.path.join(self.root, location.path))
        if p != self.root and not p.startswith(self.root + os.sep):
            raise ValueError(f"path escapes filesystem root: {location.uri()}")
        return p

    def _tmp_name(self, p: str) -> str:
        # pid + counter: racing writers (threads OR forked workers) to the
        # same path must never share a tmp name, else one renames the
        # other's half-written bytes into place.
        return f"{p}.{os.getpid()}.{next(self._tmp_seq)}.tmp"

    def read(self, location: Location) -> bytes:
        with open(self._os_path(location), "rb") as f:
            return f.read()

    def write(self, location: Location, data: bytes) -> None:
        p = self._os_path(location)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = self._tmp_name(p)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def write_if_absent(self, location: Location, data: bytes) -> bool:
        # Fully write a private tmp file, then link(2) it into place: the
        # object appears complete-or-not-at-all. The old O_EXCL-then-write
        # shape published an empty claim the instant the fd opened — a
        # crash mid-write left a partial object permanently blocking every
        # future claimer of the key.
        p = self._os_path(location)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = self._tmp_name(p)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, p)  # FileExistsError = lost the race
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    def write_if_match(
        self, location: Location, data: bytes, etag: str
    ) -> Optional[str]:
        p = self._os_path(location)
        with self._cas_lock:
            try:
                with open(p, "rb") as f:  # lint: disable=blocking-call-under-lock -- the lock IS the CAS serializer: read-compare-replace must be one atomic step
                    current = hashlib.md5(f.read()).hexdigest()
            except FileNotFoundError:
                return None
            if current != etag:
                return None
            tmp = self._tmp_name(p)
            with open(tmp, "wb") as f:  # lint: disable=blocking-call-under-lock -- the lock IS the CAS serializer: read-compare-replace must be one atomic step
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, p)
            return hashlib.md5(data).hexdigest()

    def delete(self, location: Location) -> None:
        try:
            os.unlink(self._os_path(location))
        except FileNotFoundError:
            pass

    def exists(self, location: Location) -> bool:
        return os.path.exists(self._os_path(location))

    def list_files(self, prefix: Location) -> Iterator[FileEntry]:
        base = self._os_path(prefix)
        if os.path.isfile(base):
            yield FileEntry(prefix, os.path.getsize(base))
            return
        for root, dirs, files in os.walk(base):
            dirs.sort()
            for fn in sorted(files):
                if fn.endswith(".tmp"):
                    continue
                full = os.path.join(root, fn)
                rel = os.path.relpath(full, self.root).replace(os.sep, "/")
                try:
                    size = os.path.getsize(full)
                except FileNotFoundError:
                    # concurrent evictor (cache rmtree / exchange sweep)
                    # deleted the entry mid-walk: a vanished object is not
                    # a listing failure, just absent from this page
                    continue
                yield FileEntry(Location(prefix.scheme, rel), size)


class FileSystemManager:
    """Scheme -> filesystem registry (the FileSystemFactory set the
    reference assembles from catalog config)."""

    def __init__(self):
        self._factories: Dict[str, Callable[[], TrinoFileSystem]] = {}
        self._instances: Dict[str, TrinoFileSystem] = {}
        self._lock = threading.Lock()

    def register(self, scheme: str, factory: Callable[[], TrinoFileSystem]) -> None:
        with self._lock:
            self._factories[scheme.lower()] = factory
            self._instances.pop(scheme.lower(), None)

    def for_location(self, location: Location) -> TrinoFileSystem:
        with self._lock:
            fs = self._instances.get(location.scheme)
            if fs is None:
                factory = self._factories.get(location.scheme)
                if factory is None:
                    raise ValueError(
                        f"no filesystem registered for scheme {location.scheme!r}"
                    )
                fs = factory()
                self._instances[location.scheme] = fs
            return fs
