"""Worker server: the task execution HTTP API.

Reference blueprint: server/TaskResource.java:93 (`POST /v1/task/{taskId}` →
SqlTaskManager.updateTask → SqlTaskExecution, SURVEY.md §3.2) — the
coordinator→worker control plane. A task = one fragment × one partition; inputs
arrive as serde-framed pages (the §3.3 data plane), outputs return the same way.

Round-1 simplifications: synchronous execution in the request handler (no task
state long-polling yet), and the fragment plan travels pickled — acceptable
inside a trusted cluster perimeter exactly like Trino's Java-serialized
operator descriptors; a schema'd plan codec is the round-2 replacement.
"""

from __future__ import annotations

import pickle
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..metadata import CatalogManager, Metadata, Session
from ..planner.plan import LogicalPlan
from ..runtime.serde import deserialize_page, serialize_page


class TaskDescriptor:
    """What the coordinator ships per task (HttpRemoteTask's update payload)."""

    def __init__(self, root, types, session_props, partition, n_workers, inputs):
        self.root = root                  # fragment root PlanNode
        self.types = types                # symbol -> Type
        self.session_props = session_props
        self.partition = partition
        self.n_workers = n_workers
        self.inputs = inputs              # fragment_id -> list[page bytes]


def encode_task(desc: TaskDescriptor) -> bytes:
    return pickle.dumps(desc)


def decode_task(data: bytes) -> TaskDescriptor:
    return pickle.loads(data)


class WorkerServer:
    """Executes fragments against locally-registered catalogs (workers mount
    the same catalog config as the coordinator, as in Trino)."""

    def __init__(self, catalogs: CatalogManager, host: str = "127.0.0.1", port: int = 0):
        self.catalogs = catalogs
        self.metadata = Metadata(catalogs)
        self.host = host
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) >= 2 and parts[0] == "v1" and parts[1] == "task":
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    try:
                        payload = worker._run_task(body)
                        self.send_response(200)
                        self.send_header("Content-Type", "application/octet-stream")
                        self.send_header("Content-Length", str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                    except Exception as e:  # noqa: BLE001 — task errors -> protocol
                        msg = f"{type(e).__name__}: {e}".encode()
                        self.send_response(500)
                        self.send_header("Content-Length", str(len(msg)))
                        self.end_headers()
                        self.wfile.write(msg)
                    return
                # drain the body: keep-alive clients desync otherwise
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "WorkerServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------------ tasks

    def _run_task(self, body: bytes) -> bytes:
        from ..parallel.runner import _FragmentExecutor, run_fragment_partition

        desc = decode_task(body)
        session = Session(properties=dict(desc.session_props))
        staged = {
            fid: [deserialize_page(b) for b in pages]
            for fid, pages in desc.inputs.items()
        }
        plan = LogicalPlan(desc.root, desc.types)
        executor = _FragmentExecutor(
            plan, self.metadata, session, staged, desc.partition, desc.n_workers
        )
        return serialize_page(run_fragment_partition(executor, desc.root))
