"""Query observability plane: flight recorder + per-query stats collector.

Reference blueprint: the reference's operator/OperatorStats.java +
QueryStats.java rollups (the numbers EXPLAIN ANALYZE and /v1/query render),
its OpenTelemetry spans, and the JFR-style always-on flight recording the
ecosystem leans on for production triage. Three pieces:

- ``FlightRecorder``: a bounded ring buffer of pipeline events (bucket
  start/end, prefetch issue/complete, host->device transfer, XLA compile,
  spill write/read, exchange push/pull) exportable as Chrome/Perfetto
  trace-event JSON (``chrome_trace``). Off by default — hot paths guard on
  ``RECORDER.enabled`` (one attribute read) so the disabled plane costs
  nothing measurable.
- ``QueryStatsCollector``: per-query attribution of device-busy vs
  host-wait vs compile time, per fragment and per operator, plus the
  counters every perf PR cites (compile-cache, capstore, spill bytes,
  prefetch hits, exchange bytes). JAX dispatch is asynchronous, so exact
  per-operator numbers need explicit ``block_until_ready`` fencing — the
  opt-in sync mode (``query_stats_sync`` session property / EXPLAIN ANALYZE
  VERBOSE); async mode keeps today's behavior and reports dispatch/drain
  deltas only.
- Compile attribution: one process-wide ``jax.monitoring`` duration
  listener routes ``backend_compile`` durations into every compile window
  open on the compiling thread (operator windows nest inside query
  windows), the Prometheus registry, and the flight recorder.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

# logical pid for all engine events (one process; workers override)
_PID = 1
_PROCESS_NAME = "trino-tpu"


def _now_us() -> int:
    # monotonic: Perfetto sorts on ts, and the smoke check asserts per-track
    # monotonicity — wall clock can step backwards under NTP
    return time.monotonic_ns() // 1000


def _ring_capacity_from_env() -> int:
    """Ring capacity: $TRINO_TPU_FLIGHT_RING (events), default 65536.
    Floored at 16 — a sub-page ring records nothing useful."""

    from .. import knobs

    return max(knobs.env_int("TRINO_TPU_FLIGHT_RING", 65536), 16)


class FlightRecorder:
    """Bounded ring buffer of trace events in Chrome trace-event form.

    Spans emit paired B/E duration events (same thread by construction —
    ``span`` is a context manager), point events emit "i" instants. The
    buffer is a deque(maxlen): recording never blocks and never grows; old
    events fall off the front (a B whose E survived the wrap is reported by
    the validator, so exports from a live ring are explicit about loss).
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = _ring_capacity_from_env()
        self.enabled = False  # plain attribute: ONE read guards hot paths
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._tids: Dict[int, int] = {}  # thread ident -> small stable tid
        self._tid_names: Dict[int, str] = {}
        # ring overflow is data loss — count it so truncated exports are
        # explicit instead of silently short (dropped_events rides the
        # chrome_trace export and a Prometheus counter)
        self.dropped_events = 0
        # recording is on while manually enabled OR any scoped user holds a
        # reference (concurrent flight_recorder=true queries: the first to
        # finish must not truncate the others' recording)
        self._manual = False
        self._refs = 0

    # ------------------------------------------------------------- control

    def _recompute(self) -> None:
        self.enabled = self._manual or self._refs > 0

    def enable(self) -> None:
        with self._lock:
            self._manual = True
            self._recompute()

    def disable(self) -> None:
        with self._lock:
            self._manual = False
            self._recompute()

    def acquire(self) -> None:
        """Scoped enable (refcounted): pair with release()."""
        with self._lock:
            self._refs += 1
            self._recompute()

    def release(self) -> None:
        with self._lock:
            self._refs = max(0, self._refs - 1)
            self._recompute()

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped_events = 0

    # ------------------------------------------------------------ recording

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = len(self._tids) + 1
                self._tids[ident] = tid
                self._tid_names[tid] = threading.current_thread().name
            return tid

    def _emit(self, ev: dict) -> None:
        dropped = False
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped_events += 1
                dropped = True
            self._buf.append(ev)
        if dropped:
            _counter(
                "trino_tpu_flight_dropped_events_total",
                "flight-recorder events pushed off the ring by overflow",
            ).inc()

    @contextmanager
    def span(self, name: str, cat: str, **args):
        """Paired B/E duration event on the current thread's track. Yields a
        mutable dict: keys written into it while the span is open land on
        the E event's args — outcome labels only known at span end (e.g.
        ``task_attempt`` ok/failed) ride the close event."""
        if not self.enabled:
            yield {}
            return
        tid = self._tid()
        self._emit(
            {"name": name, "cat": cat, "ph": "B", "ts": _now_us(),
             "pid": _PID, "tid": tid, "args": dict(args)}
        )
        end_args: Dict[str, object] = {}
        try:
            yield end_args
        finally:
            ev = {"name": name, "cat": cat, "ph": "E", "ts": _now_us(),
                  "pid": _PID, "tid": tid}
            if end_args:
                ev["args"] = dict(end_args)
            self._emit(ev)

    def instant(self, name: str, cat: str, **args) -> None:
        if not self.enabled:
            return
        self._emit(
            {"name": name, "cat": cat, "ph": "i", "ts": _now_us(), "s": "t",
             "pid": _PID, "tid": self._tid(), "args": dict(args)}
        )

    def counter_event(self, name: str, cat: str, **values) -> None:
        """A Perfetto counter-track sample (ph "C"): ``values`` are the
        series on the track named ``name`` for this thread's lane. Used for
        the live HBM-watermark track — one sample per kernel-cost
        attribution, rendered by Perfetto as a stepped counter under the
        lane's span track."""
        if not self.enabled:
            return
        self._emit(
            {"name": name, "cat": cat, "ph": "C", "ts": _now_us(),
             "pid": _PID, "tid": self._tid(),
             "args": {k: float(v) for k, v in values.items()}}
        )

    def complete(self, name: str, cat: str, dur_secs: float, **args) -> None:
        """An "X" event for a duration only known at its end (e.g. an XLA
        compile reported by the jax.monitoring listener)."""
        if not self.enabled:
            return
        dur_us = max(int(dur_secs * 1e6), 0)
        self._emit(
            {"name": name, "cat": cat, "ph": "X", "ts": _now_us() - dur_us,
             "dur": dur_us, "pid": _PID, "tid": self._tid(),
             "args": dict(args)}
        )

    # -------------------------------------------------------------- export

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._buf)

    def thread_names(self) -> Dict[int, str]:
        """tid -> thread name (the cluster observability plane's per-node
        segment export rebuilds thread_name metadata from this)."""
        with self._lock:
            return dict(self._tid_names)

    def chrome_trace(self) -> dict:
        """Chrome/Perfetto trace-event JSON (load in ui.perfetto.dev or
        chrome://tracing)."""
        with self._lock:
            events = list(self._buf)
            tid_names = dict(self._tid_names)
        meta: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
             "args": {"name": _PROCESS_NAME}}
        ]
        for tid, tname in sorted(tid_names.items()):
            meta.append(
                {"name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
                 "args": {"name": tname}}
            )
        return {
            "traceEvents": meta + sorted(events, key=lambda e: e["ts"]),
            "displayTimeUnit": "ms",
            # ring-overflow visibility: events lost since the last clear()
            "droppedEvents": self.dropped_events,
        }


RECORDER = FlightRecorder()


def validate_chrome_trace(trace: dict) -> List[str]:
    """Minimal schema validation for an exported trace: required fields,
    known pids/tids (declared via metadata events), per-track monotonic
    timestamps, paired B/E events, non-negative X durations, and numeric
    non-empty args on counter ("C") events. Returns a list of problems
    ([] = valid) — the observability smoke check's contract."""
    problems: List[str] = []
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    known_pids = set()
    known_tids = set()
    for ev in events:
        if ev.get("ph") == "M":
            known_pids.add(ev.get("pid"))
            if ev.get("name") == "thread_name":
                known_tids.add((ev.get("pid"), ev.get("tid")))
    stacks: Dict[tuple, List[str]] = {}
    last_ts: Dict[tuple, int] = {}
    for i, ev in enumerate(events):
        for field in ("name", "ph", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i} missing {field!r}")
        if ev.get("ph") == "M":
            continue
        if "ts" not in ev:
            problems.append(f"event {i} missing 'ts'")
            continue
        key = (ev["pid"], ev["tid"])
        if ev["pid"] not in known_pids:
            problems.append(f"event {i} has undeclared pid {ev['pid']}")
        if key not in known_tids:
            problems.append(f"event {i} has undeclared tid {ev['tid']}")
        if ev["ts"] < last_ts.get(key, 0):
            problems.append(
                f"event {i} ({ev['name']!r}) ts not monotonic on tid {ev['tid']}"
            )
        last_ts[key] = ev["ts"]
        ph = ev["ph"]
        if ph == "B":
            stacks.setdefault(key, []).append(ev["name"])
        elif ph == "E":
            stack = stacks.setdefault(key, [])
            if not stack:
                problems.append(
                    f"event {i} ({ev['name']!r}) E without matching B on "
                    f"tid {ev['tid']}"
                )
            else:
                stack.pop()
        elif ph == "X":
            if ev.get("dur", 0) < 0:
                problems.append(f"event {i} ({ev['name']!r}) negative dur")
        elif ph == "C":
            # counter-track sample: args IS the sample — every value must
            # be numeric or Perfetto drops the series silently
            cargs = ev.get("args")
            if not isinstance(cargs, dict) or not cargs:
                problems.append(
                    f"event {i} ({ev['name']!r}) counter event without args"
                )
            else:
                for k, v in cargs.items():
                    if isinstance(v, bool) or not isinstance(v, (int, float)):
                        problems.append(
                            f"event {i} ({ev['name']!r}) counter series "
                            f"{k!r} non-numeric value {v!r}"
                        )
        elif ph not in ("i", "I"):
            problems.append(f"event {i} unknown ph {ph!r}")
    for (pid, tid), stack in stacks.items():
        for name in stack:
            problems.append(f"unclosed B event {name!r} on tid {tid}")
    return problems


# --------------------------------------------------------------------------- #
# per-query stats collection
# --------------------------------------------------------------------------- #


class QueryStatsCollector:
    """Thread-safe per-query accumulator for the observability plane.

    Time attribution (seconds): ``device_busy`` (inside device dispatch +
    drain), ``host_wait`` (blocked on host I/O / prefetch results),
    ``compile`` (XLA compiles, attributed by the jax.monitoring listener).
    Exact per-operator splits need sync mode (block_until_ready fencing —
    see PlanExecutor.collect_stats); async callers still get honest query-
    level dispatch/drain deltas plus every counter.
    """

    _TIME_KEYS = (
        "device_busy_secs", "host_wait_secs", "compile_secs", "emit_secs",
        "fallback_secs", "dispatch_secs",
    )
    _COUNT_KEYS = (
        "compile_count", "compile_cache_hits", "caps_from_store",
        "spill_write_bytes", "spill_read_bytes", "spill_count",
        "prefetch_hits", "prefetch_misses",
        "exchange_push_bytes", "exchange_pull_bytes",
        "h2d_bytes", "input_rows", "overflow_retries",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self.times: Dict[str, float] = {k: 0.0 for k in self._TIME_KEYS}
        self.counts: Dict[str, int] = {k: 0 for k in self._COUNT_KEYS}
        # fragment id -> {"device_busy_secs": ..., "compile_secs": ..., ...}
        self.fragments: Dict[int, Dict[str, float]] = {}
        # operator label -> {"device_secs", "host_secs", "compile_secs",
        #                    "rows", "invocations"}
        self.operators: Dict[str, Dict[str, float]] = {}
        # plan-node key ("<preorder idx>:<kind>") -> cardinality actuals
        # (the statistics feedback plane's estimate-vs-actual rows; only the
        # WINNING attempt of a speculative FTE pair folds in here)
        self.nodes: Dict[str, Dict[str, object]] = {}
        # plan-node label -> aggregated XLA cost-model attribution
        # (runtime/kernelcost.py sink; flops/bytes sum over the node's
        # distinct programs, peak HBM is a max — programs launch serially
        # per operator so the watermark is the largest single launch)
        self.kernel_costs: Dict[str, Dict[str, object]] = {}
        self.sync_mode = False

    def add_time(self, key: str, secs: float, fragment: Optional[int] = None) -> None:
        with self._lock:
            self.times[key] = self.times.get(key, 0.0) + secs
            if fragment is not None:
                frag = self.fragments.setdefault(fragment, {})
                frag[key] = frag.get(key, 0.0) + secs

    def add_fragment_time(self, fragment: int, key: str, secs: float) -> None:
        """Fragment-level time whose QUERY total was already credited by
        another path (e.g. the jax compile listener books query-level
        compile_secs; the fragment share lands here without re-counting)."""
        with self._lock:
            frag = self.fragments.setdefault(fragment, {})
            frag[key] = frag.get(key, 0.0) + secs

    def add_count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + n

    def add_operator(
        self, label: str, device_secs: float = 0.0, host_secs: float = 0.0,
        compile_secs: float = 0.0, rows: int = 0,
    ) -> None:
        with self._lock:
            op = self.operators.setdefault(
                label,
                {"device_secs": 0.0, "host_secs": 0.0, "compile_secs": 0.0,
                 "rows": 0, "invocations": 0},
            )
            op["device_secs"] += device_secs
            op["host_secs"] += host_secs
            op["compile_secs"] += compile_secs
            op["rows"] += rows
            op["invocations"] += 1

    def add_node(
        self,
        key: str,
        kind: str = "",
        actual_rows: int = 0,
        estimated_rows: Optional[float] = None,
        q_error: Optional[float] = None,
        input_rows: int = 0,
        output_bytes: int = 0,
        null_fraction: Optional[float] = None,
        build_rows: Optional[int] = None,
        dynamic_filter_selectivity: Optional[float] = None,
    ) -> None:
        """Per-plan-node cardinality actuals (statstore.observe_query is the
        one writer; re-observation of the same key overwrites — actuals are
        aggregated across fragments/attempts BEFORE they land here)."""
        with self._lock:
            self.nodes[key] = {
                "kind": kind,
                "actualRows": int(actual_rows),
                "estimatedRows": estimated_rows,
                "qError": q_error,
                "inputRows": int(input_rows),
                "outputBytes": int(output_bytes),
                "nullFraction": null_fraction,
                "buildRows": build_rows,
                "dynamicFilterSelectivity": dynamic_filter_selectivity,
            }

    def add_kernel_cost(self, node_label: str, record: dict) -> None:
        """Fold one program's cost record (kernelcost.CostJit attribution)
        into the plan node's aggregate."""
        with self._lock:
            agg = self.kernel_costs.setdefault(
                node_label,
                {"flops": 0.0, "bytesAccessed": 0.0, "peakHbmBytes": 0,
                 "programs": 0, "unavailable": 0},
            )
            agg["programs"] += 1
            if record.get("status") != "ok":
                agg["unavailable"] += 1
                return
            if record.get("flops"):
                agg["flops"] += float(record["flops"])
            if record.get("bytes_accessed"):
                agg["bytesAccessed"] += float(record["bytes_accessed"])
            if record.get("peak_hbm_bytes"):
                agg["peakHbmBytes"] = max(
                    agg["peakHbmBytes"], int(record["peak_hbm_bytes"])
                )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "syncMode": self.sync_mode,
                "times": dict(self.times),
                "counts": dict(self.counts),
                "fragments": {
                    str(fid): dict(v) for fid, v in sorted(self.fragments.items())
                },
                "operators": {k: dict(v) for k, v in self.operators.items()},
                "planNodes": {k: dict(v) for k, v in self.nodes.items()},
                "kernelCosts": {
                    k: dict(v) for k, v in self.kernel_costs.items()
                },
            }



def query_stats_fields(snapshot: dict) -> dict:
    """QueryStatsCollector.snapshot() -> Trino-parity queryStats fields
    (QueryStats.java naming). The ONE mapping the /v1/query/{id} payload
    uses — keep field additions here, not inlined in the coordinator."""
    times = snapshot.get("times", {})
    counts = snapshot.get("counts", {})
    return {
        "deviceBusyTime": round(times.get("device_busy_secs", 0.0), 6),
        "hostWaitTime": round(times.get("host_wait_secs", 0.0), 6),
        "dispatchTime": round(times.get("dispatch_secs", 0.0), 6),
        "analysisTime": round(times.get("compile_secs", 0.0), 6),
        "spilledDataSize": counts.get("spill_write_bytes", 0),
        "spilledReadDataSize": counts.get("spill_read_bytes", 0),
        "internalNetworkInputDataSize": counts.get("exchange_pull_bytes", 0),
        "internalNetworkOutputDataSize": counts.get("exchange_push_bytes", 0),
        "physicalInputDataSize": counts.get("h2d_bytes", 0),
        "rawInputPositions": counts.get("input_rows", 0),
        "prefetchHits": counts.get("prefetch_hits", 0),
        "prefetchMisses": counts.get("prefetch_misses", 0),
        "compileCount": counts.get("compile_count", 0),
        "capacityVectorsFromStore": counts.get("caps_from_store", 0),
        "syncAttribution": snapshot.get("syncMode", False),
        "operatorSummaries": snapshot.get("operators", {}),
        "planNodeStats": snapshot.get("planNodes", {}),
        # XLA cost-model attribution per plan node (kernel_cost sessions)
        "kernelCostSummaries": snapshot.get("kernelCosts", {}),
        # warm-path cache plane (runtime/cachestore.py): the tier that
        # served the query ("result"/"fragment"/"plan"; None = cold) and
        # human provenance text ("result cache HIT @ snapshot 42")
        "cacheHitTier": snapshot.get("cacheHitTier"),
        "cacheProvenance": snapshot.get("cacheProvenance"),
    }


# ----------------------------------------------------------- active collector

_tls = threading.local()


def current_collector() -> Optional[QueryStatsCollector]:
    return getattr(_tls, "collector", None)


@contextmanager
def collecting(collector: Optional[QueryStatsCollector]):
    """Install ``collector`` as this thread's active collector (spill /
    exchange / compile hooks report to it without explicit plumbing)."""
    prev = getattr(_tls, "collector", None)
    _tls.collector = collector
    try:
        yield collector
    finally:
        _tls.collector = prev


class _CompileWindow:
    __slots__ = ("seconds", "count")

    def __init__(self):
        self.seconds = 0.0
        self.count = 0


@contextmanager
def compile_window():
    """Accumulates XLA backend-compile seconds that land on THIS thread while
    the window is open. Windows nest (an operator window inside a query
    window): the listener credits every open window, so exclusive times are
    derived by subtracting child windows."""
    _ensure_jax_listener()
    stack = getattr(_tls, "compile_windows", None)
    if stack is None:
        stack = []
        _tls.compile_windows = stack
    w = _CompileWindow()
    stack.append(w)
    try:
        yield w
    finally:
        stack.pop()


_listener_lock = threading.Lock()
_listener_registered = False


def _on_jax_duration(event: str, duration: float, **kwargs) -> None:
    if not event.endswith("backend_compile_duration"):
        return
    for w in getattr(_tls, "compile_windows", ()):
        w.seconds += duration
        w.count += 1
    c = current_collector()
    if c is not None:
        c.add_time("compile_secs", duration)
        c.add_count("compile_count")
    if RECORDER.enabled:
        RECORDER.complete("xla_compile", "compile", duration)
    try:
        from .metrics import DEFAULT_BUCKETS, REGISTRY

        REGISTRY.counter(
            "trino_tpu_xla_compiles_total", help="XLA backend compiles"
        ).inc()
        REGISTRY.histogram(
            "trino_tpu_xla_compile_secs", help="XLA backend compile duration",
            buckets=DEFAULT_BUCKETS,
        ).observe(duration)
    except Exception:
        pass


def _ensure_jax_listener() -> None:
    global _listener_registered
    if _listener_registered:
        return
    with _listener_lock:
        if _listener_registered:
            return
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_jax_duration
            )
        except Exception:
            pass  # plane degrades to no compile attribution, never fails
        _listener_registered = True


# ------------------------------------------------------------- event helpers

# process counters resolved ONCE: the hooks below sit on per-page hot paths
# (exchange sink add, output buffer add, spill blobs) where a registry
# lookup — lock + sorted-label key build — per call would be real overhead
_counters: Dict[str, object] = {}


def _counter(name: str, help_: str):
    c = _counters.get(name)
    if c is None:
        from .metrics import REGISTRY

        c = _counters[name] = REGISTRY.counter(name, help=help_)
    return c


def on_spill_write(nbytes: int, event: bool = True) -> None:
    """Spill-to-host/disk write: counters + flight event (callable from any
    thread; collector attribution rides the caller thread's collector).
    Pass ``event=False`` when the call site emits its own richer span."""
    c = current_collector()
    if c is not None:
        c.add_count("spill_write_bytes", nbytes)
        c.add_count("spill_count")
    _counter(
        "trino_tpu_spill_write_bytes_total", "bytes spilled to host/disk"
    ).inc(nbytes)
    if event:
        RECORDER.instant("spill_write", "spill", bytes=nbytes)


def on_spill_read(nbytes: int, event: bool = True) -> None:
    c = current_collector()
    if c is not None:
        c.add_count("spill_read_bytes", nbytes)
    _counter(
        "trino_tpu_spill_read_bytes_total", "bytes read back from spill"
    ).inc(nbytes)
    if event:
        RECORDER.instant("spill_read", "spill", bytes=nbytes)


def on_exchange_push(nbytes: int) -> None:
    c = current_collector()
    if c is not None:
        c.add_count("exchange_push_bytes", nbytes)
    _counter(
        "trino_tpu_exchange_push_bytes_total",
        "bytes written to exchange sinks",
    ).inc(nbytes)
    RECORDER.instant("exchange_push", "exchange", bytes=nbytes)


def on_exchange_pull(nbytes: int) -> None:
    c = current_collector()
    if c is not None:
        c.add_count("exchange_pull_bytes", nbytes)
    _counter(
        "trino_tpu_exchange_pull_bytes_total",
        "bytes read from exchange sources",
    ).inc(nbytes)
    RECORDER.instant("exchange_pull", "exchange", bytes=nbytes)
