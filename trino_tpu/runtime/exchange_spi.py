"""Durable-exchange SPI: task outputs written to storage for task-level retry.

Reference blueprint: core/trino-spi/.../spi/exchange/ExchangeManager.java:39
(Exchange / ExchangeSink / ExchangeSource contracts) with the filesystem
implementation plugin/trino-exchange-filesystem (FileSystemExchangeSink —
sinks commit ATOMICALLY so a retried task attempt either fully replaces or
never appears; consumers deduplicate by reading exactly one committed attempt
per partition, ref: ExchangeSourceOutputSelector).

The durable unit is a task attempt's complete output (SURVEY.md §5.4 —
"checkpoint/resume": resume = re-running failed tasks from stored inputs).
Local-directory layout:

    base/<query>/<fragment>/p<partition>/attempt-<n>.pages   (committed, gathered)
    base/<query>/<fragment>/p<partition>/.tmp-<n>            (uncommitted)

Round-5 PARTITIONED layout (the worker-direct data plane: producers write
their output PRE-PARTITIONED for the consumer stage, so no exchange byte
ever transits the coordinator — ref: FileSystemExchangeSink writes one file
per output partition, FileSystemExchangeManager.java):

    base/<query>/<fragment>/p<partition>/attempt-<n>.parts/part<k>.pages
    base/<query>/<fragment>/p<partition>/attempt-<n>.parts/meta.json
    base/<query>/<fragment>/p<partition>/.tmpdir-<n>/        (uncommitted)

commit() renames the directory — atomic on POSIX, so an attempt's part
files appear all-or-nothing and first-committed-wins dedup is per-attempt.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

from .failure import ChaosInjector, InjectedFailure, chaos_fire
from .observability import RECORDER, on_exchange_pull, on_exchange_push

# frame coalescing: buffered sink writes batch small page frames into ~1 MiB
# file writes (one syscall per flush instead of an open/write/close per page)
FLUSH_TARGET_BYTES = 1 << 20


class QueryExchangeRemoved(RuntimeError):
    """Commit attempted after the query's exchange was swept (zombie task)."""


class ExchangeDataCorruption(ValueError):
    """A COMMITTED attempt's stored frames failed to decode (truncated or
    corrupt TPG2 frame). Carries the exchange location so the FTE scheduler
    can quarantine the attempt and re-run the PRODUCER task — a consumer
    retry alone would re-read the same corrupt bytes forever. The message
    format is parseable (``parse_corruption``) because worker-side failures
    cross the wire as text. Subclasses ValueError: corruption has always
    surfaced as ValueError to serde consumers (round-7 contract)."""

    def __init__(self, root: str, partition: int, attempt: Optional[int],
                 detail: str = ""):
        self.root = root
        self.partition = int(partition)
        self.attempt = attempt
        super().__init__(
            f"exchange data corruption [dir={root}] [part={partition}] "
            f"[attempt={attempt if attempt is not None else -1}]: {detail}"
        )


@contextmanager
def decode_guard(root: str, partition: int, attempt: Optional[int]):
    """Wrap DECODE of blobs read from a committed attempt: a ValueError
    inside becomes :class:`ExchangeDataCorruption` tagged with the attempt
    the blobs came FROM. The attempt must be captured at READ time, never
    re-derived at failure time — by then a concurrent sibling's recovery
    may have quarantined the corrupt attempt and the producer re-committed,
    so a fresh ``committed_parts_attempt()`` lookup would tag (and the
    scheduler then quarantine) the GOOD fresh attempt."""
    try:
        yield
    except ExchangeDataCorruption:
        raise
    except ValueError as e:
        raise ExchangeDataCorruption(root, partition, attempt, str(e)) from e


_CORRUPTION_RE = re.compile(
    r"exchange data corruption \[dir=(.+?)\] \[part=(\d+)\] \[attempt=(-?\d+)\]"
)


def parse_corruption(text: Optional[str]) -> Optional[dict]:
    """Recover {dir, partition, attempt} from a (possibly remote) failure
    message; None when the text is not a corruption report."""
    m = _CORRUPTION_RE.search(text or "")
    if m is None:
        return None
    attempt = int(m.group(3))
    return {
        "dir": m.group(1),
        "partition": int(m.group(2)),
        "attempt": None if attempt < 0 else attempt,
    }


# tombstones live beside the query directory: base/.removed-<query>
_TOMBSTONE_PREFIX = ".removed-"


def _query_removed(path_inside_query: str) -> bool:
    """Walk up from an exchange path to find base/<query>; check tombstone."""
    # layout: base/<query>/<fragment>/p<partition>/...
    p = os.path.abspath(path_inside_query)
    parts = p.split(os.sep)
    for i in range(len(parts) - 1, 1, -1):
        candidate = os.sep.join(parts[: i - 1]) or os.sep
        marker = os.path.join(candidate, _TOMBSTONE_PREFIX + parts[i - 1])
        if os.path.exists(marker):
            return True
    return False


def _read_pages(path: str) -> Iterator[bytes]:
    """STREAM length-prefixed page blobs from one attempt file (the one
    reader both layouts share): frames yield as they are read — the consumer
    can decode/device_put frame i while frame i+1 is still on disk, and a
    multi-GiB attempt never materializes whole in host memory. Exchange-pull
    accounting lands per frame AS it is read, not after a full-file pass."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                break
            if len(header) != 8:
                raise ValueError(f"truncated frame header in {path}")
            size = int.from_bytes(header, "little")
            blob = f.read(size)
            if len(blob) != size:
                raise ValueError(
                    f"truncated frame in {path}: wanted {size} bytes, "
                    f"got {len(blob)}"
                )
            on_exchange_pull(len(blob))
            yield blob


class ExchangeSink:
    """Write one task attempt's output pages; commit() makes them visible
    atomically (rename), abort() discards. Frames coalesce in memory up to
    FLUSH_TARGET_BYTES per write (each flush emits an ``exchange_flush``
    flight-recorder span)."""

    def __init__(self, part_dir: str, attempt: int):
        self._final = os.path.join(part_dir, f"attempt-{attempt}.pages")
        self._tmp = os.path.join(part_dir, f".tmp-{attempt}")
        os.makedirs(part_dir, exist_ok=True)
        self._fh = open(self._tmp, "wb")
        self._buf = bytearray()

    def add(self, page_blob: bytes) -> None:
        self._buf += len(page_blob).to_bytes(8, "little")
        self._buf += page_blob
        on_exchange_push(len(page_blob))
        if len(self._buf) >= FLUSH_TARGET_BYTES:
            self._flush()

    def _flush(self) -> None:
        if not self._buf:
            return
        with RECORDER.span("exchange_flush", "exchange", bytes=len(self._buf)):
            self._fh.write(self._buf)
        self._buf = bytearray()

    def commit(self) -> None:
        self._flush()
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        if _query_removed(self._final):
            self.abort()
            raise QueryExchangeRemoved(self._final)
        try:
            os.replace(self._tmp, self._final)  # atomic: committed or absent
        except OSError:
            # the sweep's rmtree can delete the parent dir mid-window:
            # surface the zombie-task signal, not a generic OSError
            if _query_removed(self._final):
                raise QueryExchangeRemoved(self._final)
            raise
        if _query_removed(self._final):
            # TOCTOU close (same window as PartitionedExchangeSink.commit):
            # the sweep landed while the rename was in flight and its rmtree
            # may have missed the just-renamed file — undo the commit
            try:
                os.unlink(self._final)
            except OSError:
                pass
            raise QueryExchangeRemoved(self._final)

    def abort(self) -> None:
        try:
            self._fh.close()
        finally:
            if os.path.exists(self._tmp):
                os.unlink(self._tmp)


class PartitionedExchangeSink:
    """Write one task attempt's output PRE-PARTITIONED for the consumer
    stage: part files accumulate in a temp directory; commit() renames it
    into place atomically (all part files visible together or not at all).

    Buffered writers: each part's file handle opens ONCE on its first flush
    (the old per-add_part open/append/close cost n_pages syscall triples),
    frames coalesce to FLUSH_TARGET_BYTES per write, and a part that never
    receives a frame never creates a file — readers already treat a missing
    part file as ``[]``, so empty parts cost nothing on either side."""

    def __init__(self, part_dir: str, attempt: int):
        self._final = os.path.join(part_dir, f"attempt-{attempt}.parts")
        self._tmp = os.path.join(part_dir, f".tmpdir-{attempt}")
        shutil.rmtree(self._tmp, ignore_errors=True)  # stale crashed attempt
        os.makedirs(self._tmp, exist_ok=True)
        self._rows = 0
        self._fhs: Dict[int, object] = {}  # open-once part handles
        self._bufs: Dict[int, bytearray] = {}

    def add_part(self, k: int, page_blob: bytes, rows: int = 0) -> None:
        buf = self._bufs.get(k)
        if buf is None:
            buf = self._bufs[k] = bytearray()
        buf += len(page_blob).to_bytes(8, "little")
        buf += page_blob
        on_exchange_push(len(page_blob))
        self._rows += rows
        if len(buf) >= FLUSH_TARGET_BYTES:
            self._flush(k)

    def _flush(self, k: int) -> None:
        buf = self._bufs.get(k)
        if not buf:
            return
        fh = self._fhs.get(k)
        if fh is None:
            fh = self._fhs[k] = open(
                os.path.join(self._tmp, f"part{k}.pages"), "wb"
            )
        with RECORDER.span("exchange_flush", "exchange", part=k, bytes=len(buf)):
            fh.write(buf)
        self._bufs[k] = bytearray()

    def _close_handles(self, strict: bool = False) -> None:
        """``strict`` (the commit path) lets a close-time write-back failure
        (disk full, quota, delayed NFS write) PROPAGATE — committing a
        truncated part file would turn a retryable producer error into a
        permanent consumer-side decode failure. abort() swallows: the data
        is being discarded anyway."""
        err: Optional[OSError] = None
        for fh in self._fhs.values():
            try:
                fh.close()
            except OSError as e:
                if strict and err is None:
                    err = e
        self._fhs.clear()
        if err is not None:
            raise err

    def commit(self, meta: Optional[Dict] = None) -> None:
        try:
            for k in list(self._bufs):
                self._flush(k)
            self._close_handles(strict=True)
        except OSError:
            # buffered frames flush HERE, so a sweep that removed the tmpdir
            # surfaces as a failed open/write: zombie signal, not an OSError
            if _query_removed(self._final):
                self.abort()
                raise QueryExchangeRemoved(self._final)
            raise
        # chaos site "exchange_torn_commit": crash AFTER the part files are
        # written, BEFORE the atomic rename — the torn attempt must never
        # become visible. The retry commits under a NEW attempt number
        # (numbers never reuse), so a leftover tmpdir is cleaned by the
        # task layer's abort() or, at the latest, by query-end
        # remove_query; only a re-run of the SAME attempt number sweeps
        # it in the sink constructor (sweeping OTHER attempts' tmpdirs
        # would corrupt a concurrent speculative sibling's in-flight write)
        if chaos_fire("exchange_torn_commit", text=self._final) is not None:
            raise InjectedFailure(
                f"injected torn commit (crash before rename of {self._final})"
            )
        if _query_removed(self._final):
            # zombie-task guard: the coordinator already finished this query
            # and swept its exchange; committing now would resurrect the
            # directory and leak it forever (the coordinator never re-sweeps)
            self.abort()
            raise QueryExchangeRemoved(self._final)
        m = {"rows": self._rows}
        if meta:
            m.update(meta)
        try:
            with open(os.path.join(self._tmp, "meta.json"), "w") as f:
                json.dump(m, f)
            os.replace(self._tmp, self._final)  # atomic: committed or absent
        except OSError:
            # sweep deleted the parent dir mid-window: zombie signal, not OSError
            if _query_removed(self._final):
                raise QueryExchangeRemoved(self._final)
            raise
        if _query_removed(self._final):
            # TOCTOU close: the sweep can land between the check above and
            # the rename — in that window the rename resurrects a directory
            # the coordinator will never re-sweep. Re-check after the rename
            # and undo the commit (removing AFTER the sweep is safe: nothing
            # reads a tombstoned query's exchange).
            shutil.rmtree(self._final, ignore_errors=True)
            raise QueryExchangeRemoved(self._final)
        # chaos site "exchange_corrupt_frame": damage a COMMITTED attempt —
        # the commit succeeded, the task reports FINISHED, and the fault
        # only surfaces when a consumer decodes the frames (the scheduler
        # must quarantine this attempt and re-run the producer). Empty
        # commits (all parts skipped) have no frame to cut: leave the
        # armed firing for the next data-bearing commit
        if ChaosInjector._global is not None:  # keep production commits free
            # of the listdir/stat scan; armed-firing order is preserved (the
            # corruptible check still runs before chaos_fire decrements)
            if _corruptible_part(self._final) is not None:
                if chaos_fire("exchange_corrupt_frame", text=self._final) is not None:
                    _chaos_truncate_one_part(self._final)

    def abort(self) -> None:
        self._close_handles()
        shutil.rmtree(self._tmp, ignore_errors=True)


def _corruptible_part(attempt_dir: str) -> Optional[str]:
    """First part file big enough to hold at least one frame, or None."""
    try:
        names = sorted(os.listdir(attempt_dir))
    except OSError:
        return None
    for f in names:
        if f.endswith(".pages"):
            path = os.path.join(attempt_dir, f)
            if os.path.getsize(path) > 8:
                return path
    return None


def _chaos_truncate_one_part(attempt_dir: str) -> None:
    """Cut 5 bytes off the first part file: always lands mid-frame (every
    frame is an 8-byte length prefix + payload), so the read side MUST
    surface 'truncated frame' — a boundary-aligned cut could silently drop
    whole frames and corrupt results without detection."""
    path = _corruptible_part(attempt_dir)
    if path is not None:
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(size - 5)


class Exchange:
    """One fragment's durable output across its partitions."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def sink(self, partition: int, attempt: int) -> ExchangeSink:
        return ExchangeSink(os.path.join(self.root, f"p{partition}"), attempt)

    def part_sink(self, partition: int, attempt: int) -> PartitionedExchangeSink:
        return PartitionedExchangeSink(
            os.path.join(self.root, f"p{partition}"), attempt
        )

    def committed_parts_attempt(self, partition: int) -> Optional[int]:
        d = os.path.join(self.root, f"p{partition}")
        if not os.path.isdir(d):
            return None
        attempts = sorted(
            int(f[len("attempt-"):-len(".parts")])
            for f in os.listdir(d)
            if f.startswith("attempt-") and f.endswith(".parts")
        )
        return attempts[0] if attempts else None

    def _quarantined_attempt(self, partition: int) -> Optional[int]:
        """Newest attempt a consumer quarantined for this partition, or
        None when no quarantine marker exists."""
        d = os.path.join(self.root, f"p{partition}")
        try:
            names = os.listdir(d)
        except OSError:
            return None
        nums = [
            int(f[len(".corrupt-"):].split(".", 1)[0])
            for f in names
            if f.startswith(".corrupt-")
        ]
        return max(nums) if nums else None

    def quarantine_attempt(self, partition: int, attempt: Optional[int] = None) -> bool:
        """Hide a corrupt committed attempt from attempt selection (rename
        to a dotted name ``committed_*_attempt`` never lists), so the
        producer's NEXT attempt becomes the first-committed winner. Without
        this, first-committed-wins dedup would keep handing consumers the
        same corrupt bytes no matter how many times the producer re-runs."""
        d = os.path.join(self.root, f"p{partition}")
        if attempt is None:
            attempt = self.committed_parts_attempt(partition)
            if attempt is None:
                attempt = self.committed_attempt(partition)
        if attempt is None:
            return False
        moved = False
        for suffix in (".parts", ".pages"):
            src = os.path.join(d, f"attempt-{attempt}{suffix}")
            if os.path.exists(src):
                try:
                    os.replace(src, os.path.join(d, f".corrupt-{attempt}{suffix}"))
                    moved = True
                except OSError:
                    pass
        return moved

    def iter_part(self, partition: int, k: int,
                  attempt: Optional[int] = None) -> Iterator[bytes]:
        """STREAM consumer part ``k``'s page blobs from this partition's ONE
        selected committed attempt (empty when the part got no rows): frames
        yield as read, so the consumer overlaps decode with file I/O and the
        attempt never buffers whole in memory. Undecodable stored frames
        surface as :class:`ExchangeDataCorruption` tagged with this
        partition + attempt (the scheduler's quarantine-and-rerun signal).
        Pass ``attempt`` when the caller already selected one (a consumer
        reading several parts of one partition must read — and tag decode
        failures with — ONE attempt throughout, never re-select per part)."""
        if attempt is None:
            attempt = self.committed_parts_attempt(partition)
        if attempt is None:
            quarantined = self._quarantined_attempt(partition)
            if quarantined is not None:
                # every committed attempt was quarantined and the producer
                # has not re-committed yet: this is the corruption-recovery
                # window, not a missing exchange — raising corruption routes
                # the consumer through quarantine-and-rerun (gated on the
                # producer's fresh commit) instead of a blind timed retry
                raise ExchangeDataCorruption(
                    self.root, partition, quarantined,
                    "all committed attempts quarantined; "
                    "awaiting producer re-commit",
                )
            raise FileNotFoundError(
                f"no committed partitioned attempt for p{partition} in {self.root}"
            )
        attempt_dir = os.path.join(
            self.root, f"p{partition}", f"attempt-{attempt}.parts"
        )
        path = os.path.join(attempt_dir, f"part{k}.pages")
        if not os.path.exists(path):
            if not os.path.isdir(attempt_dir):
                # the whole attempt vanished between selection and read: a
                # SIBLING consumer quarantined it mid-stage. This is NOT the
                # "missing part = no rows" case — treating it as empty would
                # durably commit a wrong result; surface as corruption so
                # this consumer also retries onto the fresh attempt
                raise ExchangeDataCorruption(
                    self.root, partition, attempt,
                    "attempt quarantined by a concurrent consumer",
                )
            return  # committed, this consumer part just got no rows
        try:
            yield from _read_pages(path)
        except FileNotFoundError as e:
            # quarantine renamed the attempt dir between exists() and open()
            raise ExchangeDataCorruption(
                self.root, partition, attempt,
                "attempt quarantined by a concurrent consumer",
            ) from e
        except ValueError as e:
            raise ExchangeDataCorruption(self.root, partition, attempt, str(e)) from e

    def source_part(self, partition: int, k: int,
                    attempt: Optional[int] = None) -> List[bytes]:
        """List form of :meth:`iter_part` (small parts / tests)."""
        return list(self.iter_part(partition, k, attempt))

    def attempt_meta(self, partition: int) -> Dict:
        """Committed attempt's metadata (row counts — what adaptive
        replanning reads; NO page payload)."""
        attempt = self.committed_parts_attempt(partition)
        if attempt is None:
            return {}
        path = os.path.join(
            self.root, f"p{partition}", f"attempt-{attempt}.parts", "meta.json"
        )
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def committed_attempt(self, partition: int) -> Optional[int]:
        d = os.path.join(self.root, f"p{partition}")
        if not os.path.isdir(d):
            return None
        attempts = sorted(
            int(f[len("attempt-"):-len(".pages")])
            for f in os.listdir(d)
            if f.startswith("attempt-") and f.endswith(".pages")
        )
        return attempts[0] if attempts else None

    def iter_source(self, partition: int) -> Iterator[bytes]:
        """Stream pages of the ONE selected committed attempt (first
        committed wins — duplicate attempt outputs are never mixed)."""
        attempt = self.committed_attempt(partition)
        if attempt is None:
            quarantined = self._quarantined_attempt(partition)
            if quarantined is not None:
                raise ExchangeDataCorruption(
                    self.root, partition, quarantined,
                    "all committed attempts quarantined; "
                    "awaiting producer re-commit",
                )
            raise FileNotFoundError(
                f"no committed attempt for partition {partition} in {self.root}"
            )
        path = os.path.join(self.root, f"p{partition}", f"attempt-{attempt}.pages")
        try:
            yield from _read_pages(path)
        except FileNotFoundError as e:
            # selected attempt quarantined between selection and open() by a
            # concurrent consumer — corruption recovery, not a missing file
            raise ExchangeDataCorruption(
                self.root, partition, attempt,
                "attempt quarantined by a concurrent consumer",
            ) from e
        except ValueError as e:
            raise ExchangeDataCorruption(self.root, partition, attempt, str(e)) from e

    def source(self, partition: int) -> List[bytes]:
        """List form of :meth:`iter_source` (small attempts / tests)."""
        return list(self.iter_source(partition))


def exchange_for(root: str):
    """Substrate dispatch for the THREE places an exchange is rebuilt from
    a spec's ``dir`` string (stage input, attempt output, quarantine):
    an ``object://`` root mounts the rename-free commit-marker exchange
    (runtime/objectstore.ObjectExchange, same surface), anything else the
    local directory layout. Setting ``fte_exchange_dir=object:///...`` is
    the only step needed to run FTE on the object substrate."""
    if str(root).startswith("object://"):
        from .objectstore import ObjectExchange

        return ObjectExchange(root)
    return Exchange(root)


class ExchangeManager:
    """ref: spi/exchange/ExchangeManager.java:39 — creates per-(query,
    fragment) durable exchanges. Filesystem implementation; an
    ``object://`` base mounts the object-store implementation of the same
    surface (commit markers instead of renames, tombstone objects instead
    of rmtree)."""

    def __init__(self, base_dir: Optional[str] = None):
        self._owns = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="trino_tpu_exchange_")
        self._object = str(self.base_dir).startswith("object://")

    def create_exchange(self, query_id: str, fragment_id: int):
        if self._object:
            return exchange_for(
                f"{self.base_dir.rstrip('/')}/{query_id}/{fragment_id}"
            )
        return Exchange(os.path.join(self.base_dir, query_id, str(fragment_id)))

    def remove_query(self, query_id: str) -> None:
        if self._object:
            from .objectstore import object_remove_query

            object_remove_query(self.base_dir, query_id)
            return
        # tombstone FIRST: a zombie worker task committing after this sweep
        # observes the marker and aborts instead of resurrecting the dir
        try:
            with open(
                os.path.join(self.base_dir, _TOMBSTONE_PREFIX + query_id), "w"
            ):
                pass
        except OSError:
            pass
        shutil.rmtree(os.path.join(self.base_dir, query_id), ignore_errors=True)

    def close(self) -> None:
        if self._owns:
            shutil.rmtree(self.base_dir, ignore_errors=True)
