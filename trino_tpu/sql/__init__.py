from .parser import parse_statement, parse_expression, ParseError
from . import tree
