"""MATCH_RECOGNIZE tests.

Coverage model: the reference's row-pattern engine tests —
operator/window/matcher (Matcher.java NFA preference order),
TestRowPatternMatching.java (quantifiers, alternation, skip modes, empty
matches), and the docs' stock V-pattern example (docs/src/main/sphinx/sql/
match-recognize.md)."""

import pytest

from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


def q(runner, sql):
    return runner.execute(sql).rows


STOCK = """(VALUES
    (1, 1, 90), (1, 2, 80), (1, 3, 70), (1, 4, 85), (1, 5, 95), (1, 6, 60),
    (2, 1, 20), (2, 2, 50), (2, 3, 40), (2, 4, 10)
) AS t(sym, day, price)"""


class TestVPattern:
    def test_one_row_per_match_partitioned(self, runner):
        rows = q(runner, f"""
            SELECT * FROM {STOCK}
            MATCH_RECOGNIZE (
              PARTITION BY sym ORDER BY day
              MEASURES FIRST(down.price) AS strt, LAST(down.price) AS bottom,
                       LAST(up.price) AS top
              ONE ROW PER MATCH
              AFTER MATCH SKIP PAST LAST ROW
              PATTERN (down+ up+)
              DEFINE down AS down.price < PREV(down.price),
                     up AS up.price > PREV(up.price)
            )
        """)
        # sym 1: 80,70 down then 85,95 up; sym 2: 50->40->10 down, no up after
        assert rows == [(1, 80, 70, 95)]

    def test_all_rows_per_match(self, runner):
        rows = q(runner, f"""
            SELECT sym, day, price, cls FROM {STOCK}
            MATCH_RECOGNIZE (
              PARTITION BY sym ORDER BY day
              MEASURES CLASSIFIER() AS cls
              ALL ROWS PER MATCH
              PATTERN (down+ up+)
              DEFINE down AS down.price < PREV(down.price),
                     up AS up.price > PREV(up.price)
            )
        """)
        assert rows == [(1, 2, 80, "down"), (1, 3, 70, "down"),
                        (1, 4, 85, "up"), (1, 5, 95, "up")]

    def test_match_number_and_skip_to_next_row(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1, 10), (2, 8), (3, 6), (4, 9)) AS t(day, price)
            MATCH_RECOGNIZE (
              ORDER BY day
              MEASURES MATCH_NUMBER() AS mno, count(*) AS n
              ONE ROW PER MATCH
              AFTER MATCH SKIP TO NEXT ROW
              PATTERN (down+)
              DEFINE down AS down.price < PREV(down.price)
            )
        """)
        # greedy down+ from day2 (8,6), then from day3 (6)
        assert rows == [(1, 2), (2, 1)]


class TestQuantifiersAndAlternation:
    def test_bounded_quantifier(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1), (2), (3), (4), (5)) AS t(x)
            MATCH_RECOGNIZE (
              ORDER BY x
              MEASURES count(*) AS n, FIRST(x) AS f, LAST(x) AS l
              ONE ROW PER MATCH
              PATTERN (a{2,3})
              DEFINE a AS true
            )
        """)
        # greedy {2,3}: rows 1-3, then rows 4-5
        assert rows == [(3, 1, 3), (2, 4, 5)]

    def test_reluctant_quantifier(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1), (2), (3), (4)) AS t(x)
            MATCH_RECOGNIZE (
              ORDER BY x
              MEASURES count(*) AS n
              ONE ROW PER MATCH
              PATTERN (a+?)
              DEFINE a AS true
            )
        """)
        # reluctant: minimal 1-row matches
        assert rows == [(1,)] * 4

    def test_alternation_preference(self, runner):
        # alternation prefers the FIRST alternative even when shorter
        rows = q(runner, """
            SELECT * FROM (VALUES (1), (2)) AS t(x)
            MATCH_RECOGNIZE (
              ORDER BY x
              MEASURES CLASSIFIER() AS cls, count(*) AS n
              ONE ROW PER MATCH
              PATTERN (a | b b)
              DEFINE a AS true, b AS true
            )
        """)
        assert rows == [("a", 1), ("a", 1)]

    def test_optional_element(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1, 5), (2, 3), (3, 9)) AS t(day, v)
            MATCH_RECOGNIZE (
              ORDER BY day
              MEASURES count(*) AS n, CLASSIFIER() AS last_cls
              ONE ROW PER MATCH
              PATTERN (lo hi?)
              DEFINE lo AS lo.v < 6, hi AS hi.v > 6
            )
        """)
        # day1 (lo), day2..3 (lo hi)
        assert rows == [(1, "lo"), (2, "hi")]


class TestSkipModes:
    def test_skip_to_last_var(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1), (2), (3), (4), (5)) AS t(x)
            MATCH_RECOGNIZE (
              ORDER BY x
              MEASURES FIRST(a.x) AS fa, LAST(b.x) AS lb
              ONE ROW PER MATCH
              AFTER MATCH SKIP TO LAST a
              PATTERN (a a b)
              DEFINE a AS true, b AS true
            )
        """)
        # match 1: rows 1,2(a) 3(b); skip to last a = row 2 -> match 2: 2,3(a) 4(b)...
        assert rows == [(1, 3), (2, 4), (3, 5)]


class TestSubsetsAndAggregates:
    def test_subset_union_and_aggregates(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1, 10), (2, 20), (3, 30), (4, 40)) AS t(day, v)
            MATCH_RECOGNIZE (
              ORDER BY day
              MEASURES sum(u.v) AS s, avg(u.v) AS a, count(u.v) AS c,
                       min(b.v) AS mb, max(b.v) AS xb, sum(v) AS total
              ONE ROW PER MATCH
              PATTERN (a b b c)
              SUBSET u = (a, c)
              DEFINE a AS true, b AS true, c AS true
            )
        """)
        # u = rows {1, 4}: sum 50, avg 25, count 2; b rows {2,3}
        assert rows == [(50, 25.0, 2, 20, 30, 100)]


class TestEmptyAndUnmatched:
    def test_empty_match_produces_row(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1, 5), (2, 50)) AS t(day, v)
            MATCH_RECOGNIZE (
              ORDER BY day
              MEASURES MATCH_NUMBER() AS mno, count(*) AS n
              ONE ROW PER MATCH
              PATTERN (big*)
              DEFINE big AS big.v > 10
            )
        """)
        # day1: empty match (mno 1, 0 rows); day2: big (mno 2, 1 row)
        assert rows == [(1, 0), (2, 1)]

    def test_no_match_no_rows(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1, 5), (2, 6)) AS t(day, v)
            MATCH_RECOGNIZE (
              ORDER BY day
              MEASURES count(*) AS n
              ONE ROW PER MATCH
              PATTERN (big+)
              DEFINE big AS big.v > 10
            )
        """)
        assert rows == []


class TestNavigationInMeasures:
    def test_prev_next_physical(self, runner):
        rows = q(runner, """
            SELECT * FROM (VALUES (1, 10), (2, 20), (3, 30)) AS t(day, v)
            MATCH_RECOGNIZE (
              ORDER BY day
              MEASURES PREV(LAST(m.v)) AS before_last, NEXT(FIRST(m.v)) AS after_first
              ONE ROW PER MATCH
              AFTER MATCH SKIP PAST LAST ROW
              PATTERN (s m)
              DEFINE s AS true, m AS true
            )
        """)
        # match rows 1(s),2(m): LAST(m.v) at row2 -> PREV = v@row1 = 10;
        # FIRST(m.v) at row2 -> NEXT = v@row3 = 30 (physical, outside match)
        assert rows == [(10, 30)]

    def test_classifier_and_running_semantics_all_rows(self, runner):
        rows = q(runner, """
            SELECT day, cls, run_sum FROM
              (VALUES (1, 10), (2, 20), (3, 30)) AS t(day, v)
            MATCH_RECOGNIZE (
              ORDER BY day
              MEASURES CLASSIFIER() AS cls, sum(v) AS run_sum
              ALL ROWS PER MATCH
              PATTERN (a+)
              DEFINE a AS true
            )
        """)
        # RUNNING sum in ALL ROWS mode: prefix sums
        assert rows == [(1, "a", 10), (2, "a", 30), (3, "a", 60)]


class TestOverTpchData:
    def test_increasing_price_runs(self, runner):
        # runs of strictly increasing o_totalprice per customer ordered by
        # orderkey — verified against a host recomputation
        rows = q(runner, """
            SELECT c, n FROM orders
            MATCH_RECOGNIZE (
              PARTITION BY o_custkey ORDER BY o_orderkey
              MEASURES o_custkey AS c, count(*) AS n
              ONE ROW PER MATCH
              PATTERN (strt up+)
              DEFINE up AS up.o_totalprice > PREV(up.o_totalprice)
            ) ORDER BY c, n
        """)
        base = runner.execute(
            "SELECT o_custkey, o_orderkey, o_totalprice FROM orders "
            "ORDER BY o_custkey, o_orderkey"
        ).rows
        # host recomputation of greedy non-overlapping increasing runs >= 2
        want = []
        i = 0
        while i < len(base):
            j = i
            while (
                j + 1 < len(base)
                and base[j + 1][0] == base[j][0]
                and base[j + 1][2] > base[j][2]
            ):
                j += 1
            if j > i:
                want.append((base[i][0], j - i + 1))
                i = j
            else:
                i += 1
        assert rows == sorted(want)


class TestSkipToNonAdvancing:
    def test_skip_to_last_at_match_start_raises(self, runner):
        # ADVICE r3 (medium): SKIP TO LAST A where the last A row is the
        # match start must raise (reference: infinite-loop guard), not spin
        # re-matching the same position until the backtrack limit.
        from trino_tpu.runtime.match_recognize import MatchError

        with pytest.raises(MatchError) as ei:
            q(runner, """
                SELECT * FROM (VALUES (1), (2), (3), (4)) AS t(x)
                MATCH_RECOGNIZE (
                  ORDER BY x
                  MEASURES count(*) AS n
                  ONE ROW PER MATCH
                  AFTER MATCH SKIP TO LAST a
                  PATTERN (a b+)
                  DEFINE a AS true, b AS true
                )
            """)
        assert "would not advance" in str(ei.value)
