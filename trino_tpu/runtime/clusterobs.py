"""Cluster observability plane: cross-node trace assembly, federated
metrics, and persisted query profiles with dominant-cost diagnosis.

PR 12's serving fabric made the engine a fleet — two coordinators behind a
leased failover, elastic workers, FTE attempts hopping nodes — but every
observability surface (FlightRecorder, Prometheus registry, queryStats)
stayed process-local. "Query Processing on Tensor Computation Runtimes"
(arXiv:2203.01877) shows dispatch/compile attribution is the lever for
finding where tensor-runtime queries actually spend time, and "Near Data
Processing in Taurus Database" (PAPERS.md) motivates shipping health/cost
signals along existing data-plane channels — here the heartbeat
announcements — instead of standing up a new collection service. Three
layers:

- **Cross-node trace assembly.** Workers serve their FlightRecorder ring
  filtered by query id (``GET /v1/flightrecorder?query_id=``); the
  coordinator estimates each node's monotonic-clock offset from heartbeat
  RTT midpoints (:class:`ClockSync` — the announcement carries the sender's
  monotonic timestamp plus the last observed announce round-trip, and the
  NTP-style midpoint ``local - (remote + rtt/2)`` maps that node's clock
  onto the coordinator's), and :func:`assemble_cluster_trace` merges the
  per-node segments into ONE Perfetto timeline: one process lane per node,
  deterministic tids from sorted (node, thread-name), timestamps
  skew-aligned and clamped monotonic per lane, and — after an HA failover —
  spans from BOTH leader epochs stitched together with the dispatch
  journal's records rendered as instant markers on their own lane.

- **Federated metrics.** Workers piggyback a BOUNDED metric snapshot on
  their announcements (:func:`announcement_metrics`; overflow is dropped
  and counted via ``trino_tpu_announcement_metrics_dropped_total`` so a
  heartbeat can never bloat past the suspect-timeout budget). The
  coordinator folds the snapshots into :class:`ClusterMetrics`, queryable
  as ``system.metrics.cluster_counters`` / ``cluster_histograms`` (with a
  ``node`` column) and rendered as a fleet-wide Prometheus exposition at
  ``GET /v1/metrics/cluster`` — per-node labels, HELP preserved once per
  family, histogram buckets additionally merged across nodes under
  ``node="all"``.

- **Persisted query profiles.** On completion the coordinator writes a
  self-contained JSON bundle (:func:`build_profile` ->
  :class:`ProfileStore` under ``$TRINO_TPU_QUERY_PROFILE_DIR``): plan,
  per-operator est->actual, cache/batching provenance, retry + blacklist
  history, and the per-stage queue/compile/device/host/exchange breakdown
  a :class:`StageBreakdown` accumulates around the FTE stage loop. The
  bundle is queryable as ``system.runtime.query_profiles`` and
  ``GET /v1/query/{id}/profile``, and :func:`dominant_cost` renders the
  one-line diagnosis ("stage 2: 61% exchange pull") that EXPLAIN ANALYZE
  VERBOSE appends. Persistence auto-triggers for queries at or above the
  ``slow_query_threshold`` session knob (0 = every completed query).

Everything is gated on ``cluster_obs`` (session property, default off) for
query-level behavior and ``$TRINO_TPU_CLUSTER_OBS`` (env flag, default off)
for server-level behavior (announcement riders, the new HTTP routes): with
both off the execution path and every pre-existing response is
byte-identical to the ungated engine.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .. import knobs

ANNOUNCE_DROPPED_HELP = (
    "metric series dropped from announcement snapshots by the size bound"
)
PROFILE_VERSION = 1

# span names that open a query-attribution WINDOW on their thread: every
# event nested inside a matching window belongs to that query (operator
# spans and exchange instants carry no query id of their own)
_WINDOW_ARG_KEYS = ("query_id", "task_id", "task")


def _now_us() -> int:
    return time.monotonic_ns() // 1000


# --------------------------------------------------------------------------- #
# gating
# --------------------------------------------------------------------------- #


def server_enabled() -> bool:
    """Server-process gate (workers/coordinators have no session): the
    ``$TRINO_TPU_CLUSTER_OBS`` flag turns on announcement riders and the
    cluster observability HTTP routes. Default off — a flag-off server's
    responses are byte-identical to the pre-plane engine."""
    return knobs.env_flag("TRINO_TPU_CLUSTER_OBS", False)


def session_enabled(session) -> bool:
    """Query-level gate: the ``cluster_obs`` session property."""
    if session is None:
        return False
    try:
        return bool(session.get("cluster_obs"))
    except KeyError:
        return False


def profile_dir() -> Optional[str]:
    return knobs.env_path("TRINO_TPU_QUERY_PROFILE_DIR")


# --------------------------------------------------------------------------- #
# clock synchronization (heartbeat RTT midpoints)
# --------------------------------------------------------------------------- #


class ClockSync:
    """Per-node monotonic clock offsets estimated from announcement RTT
    midpoints.

    Each announcement carries the sender's monotonic timestamp at send time
    (``mono_us``) and the round-trip it observed for its PREVIOUS
    announcement (``rtt_us``). The receiver computes the NTP-style midpoint
    offset ``local_recv - (remote_send + rtt/2)``, which maps the sender's
    monotonic clock onto the local one; the sample with the smallest RTT
    wins (lower RTT = tighter bound on the true offset). A worker restart
    starts a FRESH monotonic epoch — detected as the remote clock running
    backwards — and discards the stale best sample, so segments recorded
    after the restart align with the new clock, not the dead one's.
    """

    # a remote clock regressing more than this is a fresh monotonic epoch
    # (restart), not jitter
    RESTART_SLACK_US = 1_000_000
    # a sample whose sender had not yet measured an RTT (first announcement,
    # rtt_us=None on the wire): usable as a provisional offset, but ranked
    # worse than ANY measured sample so the first real RTT supersedes it —
    # a literal rtt=0 would win the min-RTT rule forever
    UNMEASURED_RTT_US = 2**62

    def __init__(self):
        self._lock = threading.Lock()
        # node -> {"offset_us", "rtt_us", "remote_mono_us", "samples"}
        self._nodes: Dict[str, Dict[str, int]] = {}

    def observe(
        self,
        node_id: str,
        remote_mono_us: int,
        rtt_us: Optional[int] = 0,
        local_mono_us: Optional[int] = None,
    ) -> int:
        """Fold one announcement sample; returns the node's current offset.
        ``rtt_us=None`` means the sender has no RTT measurement yet."""
        local = _now_us() if local_mono_us is None else int(local_mono_us)
        remote = int(remote_mono_us)
        if rtt_us is None:
            rtt = self.UNMEASURED_RTT_US
            offset = local - remote  # no midpoint correction to apply
        else:
            rtt = max(int(rtt_us), 0)
            offset = local - (remote + rtt // 2)
        with self._lock:
            cur = self._nodes.get(node_id)
            if cur is not None and remote < cur["remote_mono_us"] - self.RESTART_SLACK_US:
                cur = None  # fresh monotonic epoch: the old offset is dead
            if cur is None or rtt <= cur["rtt_us"]:
                self._nodes[node_id] = {
                    "offset_us": offset,
                    "rtt_us": rtt,
                    "remote_mono_us": remote,
                    "samples": (cur or {}).get("samples", 0) + 1,
                }
            else:
                cur["remote_mono_us"] = remote
                cur["samples"] += 1
            return self._nodes[node_id]["offset_us"]

    def observe_announcement(
        self, node_id: str, clock, local_mono_us: Optional[int] = None
    ) -> Optional[int]:
        """Parse the announcement's ``clock`` rider ({"mono_us", "rtt_us"})."""
        if not isinstance(clock, dict) or "mono_us" not in clock:
            return None
        try:
            rtt = clock.get("rtt_us")
            return self.observe(
                node_id,
                int(clock["mono_us"]),
                None if rtt is None else int(rtt),
                local_mono_us=local_mono_us,
            )
        except (TypeError, ValueError):
            return None

    def offset_us(self, node_id: str) -> int:
        with self._lock:
            cur = self._nodes.get(node_id)
            return cur["offset_us"] if cur else 0

    def offsets(self) -> Dict[str, int]:
        with self._lock:
            return {n: c["offset_us"] for n, c in self._nodes.items()}

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"node": n, **dict(c)} for n, c in sorted(self._nodes.items())
            ]


# --------------------------------------------------------------------------- #
# trace filtering + deterministic export + cluster assembly
# --------------------------------------------------------------------------- #


def _event_matches(ev: dict, qids: Sequence[str]) -> bool:
    args = ev.get("args")
    if not isinstance(args, dict):
        return False
    for key in _WINDOW_ARG_KEYS:
        v = args.get(key)
        if isinstance(v, str) and any(
            v == q or v.startswith(q + "_") for q in qids
        ):
            return True
    return False


def filter_events_for_query(
    events: Iterable[dict], query_ids: Iterable[str]
) -> List[dict]:
    """The ring's events belonging to any of ``query_ids``: spans whose args
    name the query (``query_exec``/``task``/``task_attempt`` windows) plus
    everything NESTED inside such a window on the same thread — operator
    spans and exchange/spill instants carry no query id of their own, so
    attribution rides the enclosing window. B/E pairing is preserved by
    construction: an E event is included exactly when its B was."""
    qids = [q for q in set(query_ids) if q]
    if not qids:
        return []
    out: List[dict] = []
    stacks: Dict[tuple, List[bool]] = {}
    for ev in events:
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        stack = stacks.setdefault(key, [])
        active = bool(stack) and stack[-1]
        if ph == "B":
            inc = active or _event_matches(ev, qids)
            stack.append(inc)
            if inc:
                out.append(ev)
        elif ph == "E":
            inc = stack.pop() if stack else False
            if inc:
                out.append(ev)
        elif ph == "M":
            continue  # metadata is regenerated at export
        else:  # i / X / C
            if active or _event_matches(ev, qids):
                out.append(ev)
    return out


def local_segment(
    query_ids: Iterable[str], recorder=None
) -> dict:
    """This process's flight-recorder segment for ``query_ids`` as a chrome
    trace dict (full-ring export when ``query_ids`` is empty/None)."""
    from .observability import RECORDER

    rec = recorder if recorder is not None else RECORDER
    events = rec.events()
    qids = [q for q in (query_ids or []) if q]
    if qids:
        events = filter_events_for_query(events, qids)
    names = rec.thread_names()
    meta: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": "trino-tpu"}}
    ]
    used = sorted({ev.get("tid") for ev in events if "tid" in ev})
    for tid in used:
        meta.append(
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": names.get(tid, f"tid-{tid}")}}
        )
    return {
        "traceEvents": meta + sorted(events, key=lambda e: e["ts"]),
        "displayTimeUnit": "ms",
        "droppedEvents": rec.dropped_events,
    }


def _lanes_of(trace: dict) -> Tuple[List[dict], Dict[tuple, str]]:
    """(non-meta events, (pid, tid) -> thread name) of a chrome trace."""
    events = [e for e in trace.get("traceEvents", []) if e.get("ph") != "M"]
    names: Dict[tuple, str] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            names[(e.get("pid"), e.get("tid"))] = str(
                (e.get("args") or {}).get("name", "")
            )
    return events, names


def _canonical_lane_order(
    events: List[dict], names: Dict[tuple, str]
) -> List[tuple]:
    """Lanes ordered by (thread-name, first-activity): the DETERMINISTIC tid
    assignment — arrival-order tids vary run to run with thread scheduling,
    but thread names and the order of each name's first activity do not."""
    first_ts: Dict[tuple, int] = {}
    for e in events:
        key = (e.get("pid"), e.get("tid"))
        if key not in first_ts:
            first_ts[key] = e.get("ts", 0)
    return sorted(
        first_ts,
        key=lambda k: (names.get(k, f"tid-{k[1]}"), first_ts[k], str(k[1])),
    )


def canonicalize_trace(trace: dict, process_name: str = "trino-tpu") -> dict:
    """Rewrite a chrome trace with tids derived from sorted (thread-name,
    first-activity) instead of thread-arrival order, so repeated exports of
    the same ring are byte-identical (the tools/query_trace.py contract)."""
    events, names = _lanes_of(trace)
    order = _canonical_lane_order(events, names)
    remap = {key: i + 1 for i, key in enumerate(order)}
    meta: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": process_name}}
    ]
    for key in order:
        meta.append(
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": remap[key],
             "args": {"name": names.get(key, f"tid-{key[1]}")}}
        )
    out = []
    for e in sorted(events, key=lambda e: e["ts"]):
        e2 = dict(e)
        e2["pid"] = 1
        e2["tid"] = remap[(e.get("pid"), e.get("tid"))]
        out.append(e2)
    merged = dict(trace)
    merged["traceEvents"] = meta + out
    return merged


def assemble_cluster_trace(
    segments: Dict[str, dict],
    offsets: Optional[Dict[str, int]] = None,
    journal_records: Optional[List[dict]] = None,
) -> dict:
    """Merge per-node flight-recorder segments into ONE Perfetto timeline.

    ``segments``: node name -> chrome trace dict (as served by
    ``/v1/flightrecorder?query_id=``). Each node becomes its own process
    lane (pid assigned by sorted node name), tids are deterministic from
    sorted (node, thread-name, first-activity), and every event's timestamp
    is skew-aligned onto the reference clock by the node's ``offsets``
    entry (from :class:`ClockSync`; missing = 0) then CLAMPED monotonic per
    lane — a restarted worker's fresh monotonic epoch can land an aligned
    timestamp before its lane's last event, and Perfetto's per-track
    ordering contract must survive that.

    ``journal_records``: the query's dispatch-journal records (HA plane);
    rendered as instant markers on a dedicated ``dispatch-journal`` lane so
    one timeline shows both leader epochs of a failover — the journal's
    wall-clock timestamps are anchored to the merged timeline's start
    (advisory stitching, exact within the journal itself).
    """
    offsets = offsets or {}
    meta: List[dict] = []
    merged: List[dict] = []
    dropped = 0
    node_order = sorted(n for n, t in segments.items() if t)
    for pid, node in enumerate(node_order, start=1):
        trace = segments[node]
        dropped += int(trace.get("droppedEvents", 0) or 0)
        events, names = _lanes_of(trace)
        order = _canonical_lane_order(events, names)
        remap = {key: i + 1 for i, key in enumerate(order)}
        meta.append(
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": node}}
        )
        for key in order:
            meta.append(
                {"name": "thread_name", "ph": "M", "pid": pid,
                 "tid": remap[key],
                 "args": {"name": names.get(key, f"tid-{key[1]}")}}
            )
        off = int(offsets.get(node, 0) or 0)
        last_ts: Dict[int, int] = {}
        for e in sorted(events, key=lambda e: e["ts"]):
            e2 = dict(e)
            e2["pid"] = pid
            tid = remap[(e.get("pid"), e.get("tid"))]
            e2["tid"] = tid
            ts = int(e["ts"]) + off
            if tid in last_ts and ts < last_ts[tid]:
                ts = last_ts[tid]  # clamp: per-lane monotonicity survives
            last_ts[tid] = ts
            e2["ts"] = ts
            merged.append(e2)
    if journal_records:
        jpid = len(node_order) + 1
        meta.append(
            {"name": "process_name", "ph": "M", "pid": jpid, "tid": 0,
             "args": {"name": "dispatch-journal"}}
        )
        meta.append(
            {"name": "thread_name", "ph": "M", "pid": jpid, "tid": 1,
             "args": {"name": "journal"}}
        )
        stamped = [r for r in journal_records if isinstance(r.get("ts"), (int, float))]
        anchor_wall = min((r["ts"] for r in stamped), default=0.0)
        anchor_us = min((e["ts"] for e in merged), default=0)
        for rec in stamped:
            args = {k: v for k, v in rec.items() if k not in ("ts",)}
            merged.append({
                "name": f"journal:{rec.get('kind', '?')}",
                "cat": "journal", "ph": "i", "s": "t",
                "ts": anchor_us + int((rec["ts"] - anchor_wall) * 1e6),
                "pid": jpid, "tid": 1, "args": args,
            })
    merged.sort(key=lambda e: e["ts"])  # stable: per-lane order preserved
    return {
        "traceEvents": meta + merged,
        "displayTimeUnit": "ms",
        "droppedEvents": dropped,
        "nodes": node_order,
    }


# --------------------------------------------------------------------------- #
# federated metrics
# --------------------------------------------------------------------------- #


def _json_safe_series(entry: dict) -> dict:
    """A registry ``collect()`` entry as a strict-JSON-safe dict: the +Inf
    histogram bucket bound becomes ``None`` on the wire."""
    out = {
        "name": entry["name"],
        "labels": dict(entry.get("labels") or {}),
        "type": entry.get("type", "gauge"),
        "help": entry.get("help", ""),
    }
    if entry.get("type") == "histogram":
        out["buckets"] = [
            [None if math.isinf(le) else float(le), int(cum)]
            for le, cum in entry.get("buckets", [])
        ]
        out["sum"] = float(entry.get("sum", 0.0))
        out["count"] = int(entry.get("count", 0))
    else:
        out["value"] = float(entry.get("value", 0.0))
    return out


def announcement_metrics(
    registry=None, max_series: Optional[int] = None
) -> Tuple[List[dict], int]:
    """The BOUNDED metric snapshot a worker piggybacks on its announcement:
    at most ``max_series`` series (``$TRINO_TPU_ANNOUNCE_METRICS_MAX``,
    default 256); overflow is dropped deterministically (collect() is
    name-sorted, so the alphabetical tail goes first) and counted via
    ``trino_tpu_announcement_metrics_dropped_total`` — heartbeats must
    never bloat past the suspect-timeout budget. Returns (series, dropped).
    """
    if registry is None:
        from .metrics import REGISTRY as registry  # noqa: N813
    if max_series is None:
        max_series = knobs.env_int("TRINO_TPU_ANNOUNCE_METRICS_MAX", 256)
    max_series = max(int(max_series), 0)
    entries = registry.collect()
    dropped = max(0, len(entries) - max_series)
    series = [_json_safe_series(e) for e in entries[:max_series]]
    if dropped:
        registry.counter(
            "trino_tpu_announcement_metrics_dropped_total",
            help=ANNOUNCE_DROPPED_HELP,
        ).inc(dropped)
    return series, dropped


class ClusterMetrics:
    """Coordinator-side fold of the per-node announcement snapshots.

    Backs ``system.metrics.cluster_counters`` / ``cluster_histograms`` (one
    row set per node, ``node`` column) and the fleet-wide Prometheus
    exposition at ``GET /v1/metrics/cluster``: HELP/TYPE once per family,
    every series re-labeled with its node, histogram buckets additionally
    merged across nodes under ``node="all"`` when the bounds agree.

    A node that stops announcing (drained, scaled down, dead) is evicted
    after ``ttl_secs`` without an ingest — otherwise its frozen last
    snapshot would be served in the exposition and the SQL tables forever,
    and the ``node="all"`` merged histograms would keep the dead node's
    buckets in every fleet-wide quantile. ``ttl_secs<=0`` keeps forever.
    """

    def __init__(self, ttl_secs: float = 300.0):
        self._lock = threading.Lock()
        self._ttl_secs = float(ttl_secs)
        self._nodes: Dict[str, List[dict]] = {}
        self._updated: Dict[str, float] = {}

    def ingest(self, node_id: str, series) -> int:
        """Fold one node's announcement snapshot; returns series kept."""
        if not isinstance(series, list):
            return 0
        kept = [s for s in series if isinstance(s, dict) and s.get("name")]
        with self._lock:
            self._nodes[node_id] = kept
            self._updated[node_id] = time.time()
        return len(kept)

    def _prune_locked(self) -> None:
        if self._ttl_secs <= 0:
            return
        cutoff = time.time() - self._ttl_secs
        for node in [n for n, t in self._updated.items() if t < cutoff]:
            self._nodes.pop(node, None)
            self._updated.pop(node, None)

    def _all_nodes(self, local_registry, local_node: str) -> Dict[str, List[dict]]:
        out: Dict[str, List[dict]] = {}
        if local_registry is not None:
            out[local_node] = [
                _json_safe_series(e) for e in local_registry.collect()
            ]
        with self._lock:
            self._prune_locked()
            for node, series in self._nodes.items():
                out.setdefault(node, series)
        return out

    # ------------------------------------------------------------ SQL rows

    def counters_rows(
        self, local_registry=None, local_node: str = "coordinator"
    ) -> List[tuple]:
        rows = []
        for node, series in sorted(
            self._all_nodes(local_registry, local_node).items()
        ):
            for s in series:
                if s.get("type") == "histogram":
                    continue
                rows.append((
                    s["name"],
                    json.dumps(s["labels"]) if s.get("labels") else None,
                    node,
                    s.get("type", "gauge"),
                    float(s.get("value", 0.0)),
                    s.get("help") or None,
                ))
        rows.sort(key=lambda r: (r[0], r[2], r[1] or ""))
        return rows

    def histograms_rows(
        self, local_registry=None, local_node: str = "coordinator"
    ) -> List[tuple]:
        rows = []
        for node, series in sorted(
            self._all_nodes(local_registry, local_node).items()
        ):
            for s in series:
                if s.get("type") != "histogram":
                    continue
                labels = json.dumps(s["labels"]) if s.get("labels") else None
                for le, cum in s.get("buckets", []):
                    rows.append((
                        s["name"], labels, node,
                        math.inf if le is None else float(le),
                        int(cum),
                        float(s.get("sum", 0.0)), int(s.get("count", 0)),
                        s.get("help") or None,
                    ))
        rows.sort(key=lambda r: (r[0], r[2], r[1] or "", r[3]))
        return rows

    # ---------------------------------------------------------- exposition

    @staticmethod
    def _label_str(labels: Dict[str, str], node: str) -> str:
        from .metrics import _escape_label_value

        pairs = sorted(labels.items()) + [("node", node)]
        return ",".join(
            f'{k}="{_escape_label_value(v)}"' for k, v in pairs
        )

    @staticmethod
    def _fmt(v: float) -> str:
        from .metrics import _format_value

        return _format_value(v)

    def _render_histogram(
        self, lines: List[str], name: str, labels: Dict[str, str],
        node: str, buckets, sum_: float, count: int,
    ) -> None:
        base = self._label_str(labels, node)
        for le, cum in buckets:
            le_text = "+Inf" if le is None else f"{le:g}"
            lines.append(f'{name}_bucket{{{base},le="{le_text}"}} {int(cum)}')
        lines.append(f"{name}_sum{{{base}}} {self._fmt(sum_)}")
        lines.append(f"{name}_count{{{base}}} {int(count)}")

    def render(
        self, local_registry=None, local_node: str = "coordinator"
    ) -> str:
        """Fleet-wide Prometheus text exposition: per-node labeled series
        grouped by family (HELP/TYPE once, first non-empty HELP wins), plus
        a cross-node merged histogram under ``node="all"`` when more than
        one node reports the family with agreeing bucket bounds."""
        nodes = self._all_nodes(local_registry, local_node)
        families: Dict[str, List[Tuple[str, dict]]] = {}
        for node, series in sorted(nodes.items()):
            for s in series:
                families.setdefault(s["name"], []).append((node, s))
        lines: List[str] = []
        for name in sorted(families):
            entries = families[name]
            help_ = next((s.get("help") for _, s in entries if s.get("help")), "")
            type_ = entries[0][1].get("type", "gauge")
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {type_}")
            hist_entries = []
            for node, s in entries:
                if s.get("type") == "histogram":
                    self._render_histogram(
                        lines, name, s.get("labels") or {}, node,
                        s.get("buckets", []), float(s.get("sum", 0.0)),
                        int(s.get("count", 0)),
                    )
                    hist_entries.append(s)
                else:
                    base = self._label_str(s.get("labels") or {}, node)
                    lines.append(f"{name}{{{base}}} {self._fmt(s.get('value', 0.0))}")
            if len(hist_entries) > 1:
                bounds = [tuple(le for le, _ in s.get("buckets", []))
                          for s in hist_entries]
                if all(b == bounds[0] for b in bounds) and bounds[0]:
                    merged = [
                        [le, sum(s["buckets"][i][1] for s in hist_entries)]
                        for i, (le, _) in enumerate(hist_entries[0]["buckets"])
                    ]
                    self._render_histogram(
                        lines, name, {}, "all", merged,
                        sum(float(s.get("sum", 0.0)) for s in hist_entries),
                        sum(int(s.get("count", 0)) for s in hist_entries),
                    )
        return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------- #
# stage breakdown (the FTE stage loop's time accounting)
# --------------------------------------------------------------------------- #

STAGE_COMPONENT_KEYS = (
    "queue_secs", "compile_secs", "device_secs", "host_secs",
    "exchange_pull_secs", "exchange_push_secs",
)

_COMPONENT_DISPLAY = {
    "queue_secs": "queue",
    "compile_secs": "compile",
    "device_secs": "device",
    "host_secs": "host",
    "exchange_pull_secs": "exchange pull",
    "exchange_push_secs": "exchange push",
}


class StageBreakdown:
    """Per-stage wall + component accounting for the FTE stage loop.

    Stage WALL times are measured contiguously around each stage's loop
    iteration (plus named phases: planning, root read), so their sum tracks
    the query's wall time to within loop overhead — the profile's
    sums-to-wall contract. Component times (queue/compile/device/host/
    exchange) are summed across the stage's concurrent task attempts and
    rendered as SHARES of the stage wall: attempts overlap, so component
    seconds can exceed the wall and only their ratio is meaningful.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.stages: Dict[int, Dict[str, float]] = {}
        self.phases: Dict[str, float] = {}

    def _stage(self, fid: int) -> Dict[str, float]:
        st = self.stages.get(fid)
        if st is None:
            st = self.stages[fid] = {"wall_secs": 0.0}
            st.update({k: 0.0 for k in STAGE_COMPONENT_KEYS})
        return st

    @contextmanager
    def stage(self, fid: int):
        t0 = time.monotonic()
        try:
            yield
        finally:
            secs = time.monotonic() - t0
            with self._lock:
                self._stage(fid)["wall_secs"] += secs

    def add(self, fid: int, **secs: float) -> None:
        """Thread-safe component accumulation (attempt threads call this)."""
        with self._lock:
            st = self._stage(fid)
            for key, v in secs.items():
                st[key] = st.get(key, 0.0) + max(float(v), 0.0)

    @contextmanager
    def phase(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_phase(name, time.monotonic() - t0)

    def add_phase(self, name: str, secs: float) -> None:
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + max(float(secs), 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "stages": {
                    str(fid): dict(st) for fid, st in sorted(self.stages.items())
                },
                "phases": dict(self.phases),
            }


def dominant_cost(
    entries: Sequence[Tuple[str, float, Dict[str, float]]]
) -> Optional[str]:
    """The one-line diagnosis: which entry (stage/operator) dominates the
    query's time and which component dominates that entry — e.g.
    ``"stage 2: 61% exchange pull"``. The percentage is the share of TOTAL
    time attributable to that component of that entry (entry share x
    component share within the entry). None when nothing was measured."""
    total = sum(max(w, 0.0) for _, w, _ in entries)
    if total <= 0.0:
        return None
    label, wall, comps = max(entries, key=lambda e: e[1])
    positive = {k: v for k, v in (comps or {}).items() if v > 0.0}
    if not positive:
        return f"{label}: {100.0 * wall / total:.0f}% of query time"
    comp, comp_secs = max(positive.items(), key=lambda kv: kv[1])
    share = (wall / total) * (comp_secs / sum(positive.values()))
    name = _COMPONENT_DISPLAY.get(comp, comp.replace("_secs", "").replace("_", " "))
    return f"{label}: {100.0 * share:.0f}% {name}"


def _profile_entries(profile_stages: dict, times: dict) -> List[tuple]:
    entries = []
    for fid, st in (profile_stages or {}).items():
        comps = {k: st.get(k, 0.0) for k in STAGE_COMPONENT_KEYS}
        entries.append((f"stage {fid}", st.get("wall_secs", 0.0), comps))
    if entries:
        return entries
    times = times or {}
    comps = {
        "device_secs": times.get("device_busy_secs", 0.0),
        "host_secs": times.get("host_wait_secs", 0.0),
        "compile_secs": times.get("compile_secs", 0.0),
    }
    wall = sum(comps.values())
    return [("query", wall, comps)] if wall > 0 else []


# --------------------------------------------------------------------------- #
# persisted query profiles
# --------------------------------------------------------------------------- #


def build_profile(
    query_id: str,
    sql: str,
    state: str = "FINISHED",
    user: str = "",
    wall_secs: float = 0.0,
    query_stats: Optional[dict] = None,
    plan: Optional[str] = None,
    created: Optional[float] = None,
    ended: Optional[float] = None,
) -> dict:
    """The self-contained postmortem bundle: plan, per-operator est->actual
    (the stats plane's planNodes), cache/batching provenance, retry +
    blacklist history (attached to ``query_stats`` by the FTE runner), the
    per-stage time breakdown, and the dominant-cost diagnosis line."""
    qs = query_stats or {}
    stages = qs.get("stages") or {}
    diagnosis = dominant_cost(_profile_entries(stages, qs.get("times")))
    return {
        "version": PROFILE_VERSION,
        "queryId": query_id,
        "query": sql,
        "state": state,
        "user": user,
        "wallSecs": round(float(wall_secs), 6),
        "createdAt": created,
        "endedAt": ended,
        "plan": plan,
        "stages": stages,
        "phases": qs.get("phases") or {},
        "times": qs.get("times") or {},
        "counts": qs.get("counts") or {},
        "operators": qs.get("operators") or {},
        "planNodes": qs.get("planNodes") or {},
        "cache": {
            "tier": qs.get("cacheHitTier"),
            "provenance": qs.get("cacheProvenance"),
        },
        "retries": qs.get("retries") or [],
        "blacklist": qs.get("blacklist") or [],
        "journal": qs.get("journal") or [],
        "fteQueryId": qs.get("fteQueryId"),
        "diagnosis": diagnosis,
    }


def profile_breakdown_secs(profile: dict) -> float:
    """Sum of the profile's contiguously-measured segments (stage walls +
    named phases) — the number the acceptance contract compares against the
    query's wall time (within 5%)."""
    total = 0.0
    for st in (profile.get("stages") or {}).values():
        total += float(st.get("wall_secs", 0.0))
    for secs in (profile.get("phases") or {}).values():
        total += float(secs)
    return total


class ProfileStore:
    """One JSON bundle per query id under a root directory (atomic rename
    publish, tolerant reads)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, query_id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in query_id)
        return os.path.join(self.root, f"{safe}.json")

    def write(self, profile: dict) -> str:
        path = self._path(str(profile.get("queryId", "unknown")))
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(profile, f, sort_keys=True)
        os.replace(tmp, path)
        from .metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_query_profiles_persisted_total",
            help="query profile bundles persisted to the profile store",
        ).inc()
        return path

    def read(self, query_id: str) -> Optional[dict]:
        try:
            with open(self._path(query_id), "r") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        return data if isinstance(data, dict) else None

    def list(self) -> List[dict]:
        out: List[dict] = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, name), "r") as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(data, dict):
                data["_path"] = os.path.join(self.root, name)
                out.append(data)
        return out


_STORES: Dict[str, ProfileStore] = {}
_STORES_LOCK = threading.Lock()


def profile_store(root: Optional[str] = None) -> Optional[ProfileStore]:
    """The process's profile store over ``$TRINO_TPU_QUERY_PROFILE_DIR``
    (or an explicit root); None when no directory is configured."""
    root = root or profile_dir()
    if not root:
        return None
    with _STORES_LOCK:
        store = _STORES.get(root)
        if store is None:
            store = ProfileStore(root)
            _STORES[root] = store
        return store


def slow_query_threshold(session) -> float:
    try:
        return float(session.get("slow_query_threshold"))
    except (KeyError, TypeError, ValueError):
        return 0.0


def maybe_persist_profile(
    session,
    query_id: str,
    sql: str,
    state: str = "FINISHED",
    user: str = "",
    wall_secs: float = 0.0,
    query_stats: Optional[dict] = None,
    plan: Optional[str] = None,
    created: Optional[float] = None,
    ended: Optional[float] = None,
) -> Optional[str]:
    """Auto-persistence hook (the QueryManager calls this on every terminal
    transition): with ``cluster_obs`` on, a configured profile dir, and the
    query at or above ``slow_query_threshold`` (0 = persist everything),
    write the bundle. Returns the written path or None."""
    if not session_enabled(session):
        return None
    if float(wall_secs) < slow_query_threshold(session):
        return None
    store = profile_store()
    if store is None:
        return None
    return store.write(build_profile(
        query_id, sql, state=state, user=user, wall_secs=wall_secs,
        query_stats=query_stats, plan=plan, created=created, ended=ended,
    ))
