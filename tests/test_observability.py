"""Metrics, tracing spans, and the spool SPI.

Model: the reference's spi/metrics + JMX exposure, its OpenTelemetry span
instrumentation (TracingMetadata planning spans), and spi/spool
SpoolingManager + the spooled client protocol (protocol/spooling).
"""

import json
import urllib.request

import pytest


@pytest.fixture(scope="module")
def server():
    from trino_tpu.runtime import LocalQueryRunner
    from trino_tpu.server.coordinator import CoordinatorServer

    r = LocalQueryRunner.tpch(scale=0.001)
    srv = CoordinatorServer(r)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    from trino_tpu.client.client import StatementClient

    return StatementClient(f"http://{server.address}")


class TestMetrics:
    def test_prometheus_rendering(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("test_total", help="a test counter").inc(3)
        reg.gauge("test_gauge", {"pool": "a"}).set(7)
        text = reg.render()
        assert "# TYPE test_total counter" in text
        assert "test_total 3" in text
        assert 'test_gauge{pool="a"} 7' in text

    def test_endpoint_counts_queries(self, server, client):
        client.execute("SELECT 1")
        text = (
            urllib.request.urlopen(f"http://{server.address}/v1/metrics")
            .read()
            .decode()
        )
        assert "trino_tpu_queries_submitted_total" in text
        assert "trino_tpu_queries_finished_total" in text


class TestTracing:
    def test_span_tree(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with tr.span("root") as root:
            with tr.span("child"):
                pass
        spans = tr.trace(root.trace_id)
        assert [s["name"] for s in spans] == ["root", "child"]
        child = spans[1]
        assert child["parentSpanId"] == spans[0]["spanId"]
        assert child["durationMs"] is not None

    def test_error_recorded(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.span("boom") as s:
                raise ValueError("nope")
        assert "ValueError" in s.attributes["error"]

    def test_query_trace_endpoint(self, server, client):
        res = client.execute("SELECT count(*) FROM nation")
        info = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/v1/query/{res.query_id}/trace"
            ).read()
        )
        names = [s["name"] for s in info["spans"]]
        assert names == ["query", "planner", "optimizer", "execution"]


class TestSpool:
    def test_manager_roundtrip(self, tmp_path):
        from trino_tpu.runtime.spool import FileSystemSpoolingManager

        m = FileSystemSpoolingManager(str(tmp_path))
        h = m.create_segment(b"payload", rows=3)
        assert m.get_segment(h.segment_id) == b"payload"
        m.delete_segment(h.segment_id)
        assert m.get_segment(h.segment_id) is None

    def test_ttl_eviction(self, tmp_path):
        from trino_tpu.runtime.spool import FileSystemSpoolingManager

        m = FileSystemSpoolingManager(str(tmp_path), ttl_secs=0.0)
        h1 = m.create_segment(b"a", rows=1)
        m.create_segment(b"b", rows=1)  # triggers eviction of h1
        assert h1.segment_id not in m.list_segments()

    def test_spooled_protocol_matches_inline(self, client):
        inline = client.execute(
            "SELECT n_nationkey, n_name FROM nation ORDER BY n_nationkey"
        )
        spooled = client.execute(
            "SELECT n_nationkey, n_name FROM nation ORDER BY n_nationkey",
            data_encoding="json",
        )
        assert spooled.rows == inline.rows

    def test_spooled_lz4(self, client):
        from trino_tpu.native import native_available

        if not native_available():
            pytest.skip("native lz4 unavailable")
        spooled = client.execute(
            "SELECT n_nationkey FROM nation ORDER BY n_nationkey",
            data_encoding="json+lz4",
        )
        assert len(spooled.rows) == 25

    def test_segments_acked_and_freed(self, server, client):
        client.execute("SELECT n_name FROM nation", data_encoding="json")
        # the client acks (DELETEs) every segment it fetched
        assert server.spooling.list_segments() == []


class TestMetricsPrecision:
    def test_large_counter_full_precision(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("big_total").inc(12_345_678)
        assert "big_total 12345678" in reg.render()


class TestPrometheusConformance:
    """Text exposition format conformance (the scrape contract)."""

    def test_help_and_type_lines_once_per_name(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("multi_total", {"shard": "a"}, help="a multi counter").inc()
        reg.counter("multi_total", {"shard": "b"}).inc(2)
        text = reg.render()
        assert text.count("# HELP multi_total a multi counter") == 1
        assert text.count("# TYPE multi_total counter") == 1
        assert '# HELP' not in text.split("# TYPE multi_total counter")[1]

    def test_label_escaping(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.gauge("esc_gauge", {"q": 'a"b\\c\nd'}).set(1)
        text = reg.render()
        assert 'q="a\\"b\\\\c\\nd"' in text

    def test_counter_monotonic_across_scrapes(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("mono_total")
        values = []
        for _ in range(5):
            c.inc(3)
            line = [
                l for l in reg.render().splitlines()
                if l.startswith("mono_total ")
            ][0]
            values.append(float(line.split()[1]))
        assert values == sorted(values)
        with pytest.raises(ValueError):
            c.inc(-1)  # counters never go down

    def test_metrics_endpoint_content_type(self, server):
        resp = urllib.request.urlopen(f"http://{server.address}/v1/metrics")
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in resp.headers["Content-Type"]

    def test_counter_and_gauge_thread_safety(self):
        import threading

        from trino_tpu.runtime.metrics import Counter, Gauge, Histogram

        c, g, h = Counter(), Gauge(), Histogram(buckets=[0.5, 1.0])
        n, k = 8, 5000

        def work():
            for _ in range(k):
                c.inc()
                g.inc(2)
                g.dec()
                h.observe(0.25)

        threads = [threading.Thread(target=work) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n * k
        assert g.value == n * k
        assert h.count == n * k
        assert h.bucket_counts[0] == n * k


class TestHistogram:
    def test_exposition_cumulative_buckets(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        h = reg.histogram(
            "lat_secs", {"stage": "x"}, help="latency", buckets=[0.1, 1.0, 10.0]
        )
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        text = reg.render()
        assert "# TYPE lat_secs histogram" in text
        assert 'lat_secs_bucket{stage="x",le="0.1"} 1' in text
        assert 'lat_secs_bucket{stage="x",le="1"} 3' in text
        assert 'lat_secs_bucket{stage="x",le="10"} 4' in text
        assert 'lat_secs_bucket{stage="x",le="+Inf"} 5' in text
        assert 'lat_secs_count{stage="x"} 5' in text
        assert 'lat_secs_sum{stage="x"} 56.05' in text

    def test_exponential_buckets(self):
        from trino_tpu.runtime.metrics import exponential_buckets

        assert exponential_buckets(0.001, 2.0, 4) == (0.001, 0.002, 0.004, 0.008)

    def test_boundary_lands_in_bucket(self):
        from trino_tpu.runtime.metrics import Histogram

        h = Histogram(buckets=[1.0, 2.0])
        h.observe(1.0)  # le="1" is inclusive
        assert h.bucket_counts[0] == 1

    def test_quantile_interpolation(self):
        import math

        from trino_tpu.runtime.metrics import histogram_quantile

        # 10 observations uniform in (0, 1], 10 in (1, 2]
        buckets = [(1.0, 10), (2.0, 20), (math.inf, 20)]
        assert histogram_quantile(buckets, 20, 0.5) == 1.0
        assert histogram_quantile(buckets, 20, 0.25) == 0.5
        assert abs(histogram_quantile(buckets, 20, 0.95) - 1.9) < 1e-9
        # empty series -> None; rank past the last finite bound clamps to it
        assert histogram_quantile(buckets, 0, 0.5) is None
        assert histogram_quantile([(1.0, 0), (math.inf, 5)], 5, 0.5) == 1.0


class TestTraceContextPropagation:
    def test_pool_thread_spans_join_parent_trace(self):
        """Spans opened on a pooled thread re-parent into the submitting
        thread's trace via capture()/attach() (the OOC prefetcher / FTE
        task-thread fix) instead of starting an orphan trace."""
        from concurrent.futures import ThreadPoolExecutor

        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        pool = ThreadPoolExecutor(max_workers=1)
        try:
            with tr.span("query") as root:
                ctx = tr.capture()

                def job():
                    with tr.attach(ctx):
                        with tr.span("prefetch") as child:
                            return child

                child = pool.submit(job).result()
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            spans = tr.trace(root.trace_id)
            assert [s["name"] for s in spans] == ["query", "prefetch"]
        finally:
            pool.shutdown()

    def test_wrap_captures_at_wrap_time(self):
        from concurrent.futures import ThreadPoolExecutor

        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        pool = ThreadPoolExecutor(max_workers=1)
        try:
            with tr.span("query") as root:
                def job():
                    with tr.span("inner") as s:
                        return s

                wrapped = tr.wrap(job)
            # runs AFTER the parent closed — parentage still holds
            child = pool.submit(wrapped).result()
            assert child.trace_id == root.trace_id
        finally:
            pool.shutdown()

    def test_remote_ids_cross_wire_boundary(self):
        """capture_ids()/attach_remote(): trace parentage shipped in a task
        descriptor over HTTP (the FTE task-thread path — a same-process
        capture can't carry it)."""
        from trino_tpu.runtime.tracing import Tracer
        from trino_tpu.server.worker import (
            TaskDescriptor,
            decode_task,
            encode_task,
        )

        tr = Tracer()
        with tr.span("query") as root:
            ids = tr.capture_ids()
        assert ids == {"trace_id": root.trace_id, "span_id": root.span_id}
        desc = decode_task(encode_task(TaskDescriptor(trace=ids)))
        assert desc.trace == ids
        with tr.attach_remote(desc.trace):
            with tr.span("task") as s:
                pass
        assert s.trace_id == root.trace_id
        assert s.parent_id == root.span_id
        assert tr.capture_ids() is None  # phantom popped cleanly

    def test_attach_none_is_noop(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with tr.attach(tr.capture()):  # nothing current -> no parent
            with tr.span("solo") as s:
                pass
        assert s.parent_id is None

    def test_ooc_prefetch_spans_join_query_trace(self):
        """End-to-end: the OOC bucket prefetcher's pool-side spans land in
        the enclosing query trace."""
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.runtime.ooc import OutOfCoreRunner
        from trino_tpu.runtime.tracing import TRACER

        r = LocalQueryRunner.tpch(scale=0.001)
        plan = r.plan_sql(
            "SELECT o_custkey, count(*) FROM orders, lineitem "
            "WHERE o_orderkey = l_orderkey GROUP BY o_custkey"
        )
        with TRACER.span("query") as root:
            ooc = OutOfCoreRunner(
                plan, r.metadata, r.session, n_buckets=4, split_batch=2
            )
            ooc.execute()
        names = [s["name"] for s in TRACER.trace(root.trace_id)]
        assert "ooc.prefetch" in names


class TestFlightRecorder:
    def test_disabled_records_nothing(self):
        from trino_tpu.runtime.observability import FlightRecorder

        rec = FlightRecorder()
        with rec.span("x", "test"):
            rec.instant("y", "test")
        assert rec.events() == []

    def test_bounded_ring(self):
        from trino_tpu.runtime.observability import FlightRecorder

        rec = FlightRecorder(capacity=16)
        rec.enable()
        for i in range(100):
            rec.instant(f"e{i}", "test")
        events = rec.events()
        assert len(events) == 16
        assert events[-1]["name"] == "e99"

    def test_dropped_events_counted(self):
        """Ring truncation is visible: dropped_events counts overflow and
        rides the chrome_trace export (never silent loss)."""
        from trino_tpu.runtime.observability import FlightRecorder

        rec = FlightRecorder(capacity=16)
        rec.enable()
        for i in range(100):
            rec.instant(f"e{i}", "test")
        assert rec.dropped_events == 84
        assert rec.chrome_trace()["droppedEvents"] == 84
        rec.clear()
        assert rec.dropped_events == 0
        rec.instant("after", "test")
        assert rec.chrome_trace()["droppedEvents"] == 0

    def test_ring_capacity_from_env(self, monkeypatch):
        from trino_tpu.runtime.observability import FlightRecorder

        monkeypatch.setenv("TRINO_TPU_FLIGHT_RING", "32")
        rec = FlightRecorder()
        assert rec._buf.maxlen == 32
        monkeypatch.setenv("TRINO_TPU_FLIGHT_RING", "not-a-number")
        assert FlightRecorder()._buf.maxlen == 65536
        monkeypatch.delenv("TRINO_TPU_FLIGHT_RING")
        assert FlightRecorder()._buf.maxlen == 65536

    def test_chrome_trace_validates(self):
        from trino_tpu.runtime.observability import (
            FlightRecorder,
            validate_chrome_trace,
        )

        rec = FlightRecorder()
        rec.enable()
        with rec.span("outer", "test", tag=1):
            with rec.span("inner", "test"):
                rec.instant("point", "test", bytes=7)
        rec.complete("compile", "test", 0.001)
        trace = rec.chrome_trace()
        assert validate_chrome_trace(trace) == []
        names = [e["name"] for e in trace["traceEvents"]]
        assert "process_name" in names and "thread_name" in names

    def test_validator_catches_unpaired_and_nonmonotonic(self):
        from trino_tpu.runtime.observability import validate_chrome_trace

        meta = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "p"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "t"}},
        ]
        unpaired = meta + [
            {"name": "a", "cat": "c", "ph": "B", "ts": 10, "pid": 1, "tid": 1}
        ]
        assert any("unclosed" in p for p in validate_chrome_trace(
            {"traceEvents": unpaired}
        ))
        backwards = meta + [
            {"name": "a", "cat": "c", "ph": "i", "ts": 10, "pid": 1, "tid": 1},
            {"name": "b", "cat": "c", "ph": "i", "ts": 5, "pid": 1, "tid": 1},
        ]
        assert any("monotonic" in p for p in validate_chrome_trace(
            {"traceEvents": backwards}
        ))
        unknown_tid = meta + [
            {"name": "a", "cat": "c", "ph": "i", "ts": 1, "pid": 1, "tid": 9}
        ]
        assert any("undeclared tid" in p for p in validate_chrome_trace(
            {"traceEvents": unknown_tid}
        ))

    def test_flightrecorder_endpoint(self, server, client):
        from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

        RECORDER.clear()
        RECORDER.enable()
        try:
            client.execute("SELECT count(*) FROM region")
        finally:
            RECORDER.disable()
        info = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/v1/flightrecorder"
            ).read()
        )
        assert validate_chrome_trace(info) == []
        cats = {e.get("cat") for e in info["traceEvents"]}
        assert "query" in cats


class TestQueryStatsPlane:
    def test_explain_analyze_verbose_reports_attribution(self):
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        res = r.execute(
            "EXPLAIN ANALYZE VERBOSE "
            "SELECT n_name, count(*) FROM supplier, nation "
            "WHERE s_nationkey = n_nationkey GROUP BY n_name"
        )
        text = "\n".join(line for (line,) in res.rows)
        assert "Join" in text
        assert "device=" in text and "host=" in text and "compile=" in text
        # plain ANALYZE keeps the compact annotation
        res2 = r.execute(
            "EXPLAIN ANALYZE SELECT count(*) FROM nation"
        )
        text2 = "\n".join(line for (line,) in res2.rows)
        assert "time=" in text2 and "device=" not in text2

    def test_query_stats_collected_async(self):
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        res = r.execute("SELECT count(*) FROM lineitem")
        qs = res.query_stats
        assert qs is not None and not qs["syncMode"]
        assert qs["times"]["dispatch_secs"] > 0

    def test_query_stats_sync_mode_per_operator(self):
        from trino_tpu.metadata import Session
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        r.session.set("query_stats_sync", True)
        res = r.execute("SELECT l_returnflag, count(*) FROM lineitem GROUP BY l_returnflag")
        qs = res.query_stats
        assert qs["syncMode"]
        assert "AggregationNode" in qs["operators"]
        agg = qs["operators"]["AggregationNode"]
        assert agg["invocations"] >= 1 and agg["rows"] >= 1

    def test_v1_query_exposes_plane_fields(self, server, client):
        res = client.execute("SELECT count(*) FROM nation")
        info = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/v1/query/{res.query_id}"
            ).read()
        )
        qs = info["queryStats"]
        for field in (
            "deviceBusyTime", "hostWaitTime", "analysisTime",
            "spilledDataSize", "internalNetworkInputDataSize",
            "internalNetworkOutputDataSize", "compileCount",
        ):
            assert field in qs, field

    def test_spill_counters_reach_plane(self):
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        r.session.set("spill_operator_threshold_bytes", 1024)
        res = r.execute(
            "SELECT o_custkey, count(*) FROM orders GROUP BY o_custkey"
        )
        qs = res.query_stats
        assert qs["counts"]["spill_write_bytes"] > 0
        assert qs["counts"]["spill_read_bytes"] > 0


class TestSmokeCheck:
    """The tier-1 observability smoke check (satellite: CI/tooling)."""

    def test_smoke_check_passes(self):
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_smoke() == []

    def test_exchange_smoke_passes(self):
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_exchange_smoke() == []

    def test_memory_smoke_passes(self):
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_memory_smoke() == []

    def test_kernelcost_smoke_passes(self):
        """The kernel cost plane smoke: roofline lines in EXPLAIN ANALYZE
        VERBOSE, hbm_watermark counter track + paired kernel_cost spans in
        a valid Perfetto export (counter-event conformance mutation-checked
        inside the smoke), schema-checked system.runtime.kernel_costs with
        a federated fold."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_kernelcost_smoke() == []

    def test_hostprof_smoke_passes(self):
        """The host-path observability plane smoke: session-scoped sampler
        with named-thread collapsed stacks, valid speedscope export, paired
        proto_* phase spans, schema-checked system.runtime.host_profile,
        host-thread gauges, and a numeric contention-probe summary."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_hostprof_smoke() == []

    def test_stats_smoke_passes(self):
        """The statistics-feedback-plane smoke: paired/monotonic
        cardinality_misestimate events + schema-checked operator_stats."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_stats_smoke() == []

    def test_cache_smoke_passes(self):
        """The warm-path cache-plane smoke: paired cache_lookup/cache_store/
        cache_invalidate spans with hit/miss outcomes, schema-checked
        system.runtime.caches, HELP-linted tier counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_cache_smoke() == []

    def test_batching_smoke_passes(self):
        """The device-batching-plane smoke: paired batch_admit/batch_launch/
        batch_demux spans with lane counts and packed rows on the E-args,
        bit-identical concurrent burst, shared-scan elimination, HELP-linted
        batching metrics."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_batching_smoke() == []

    def test_megakernel_smoke_passes(self):
        """The megakernel-plane smoke: paired pallas_compile/pallas_launch
        spans with shape class + fused-op list on the E-args, bit-identical
        fused vs serial run, strictly fewer device programs, HELP-linted
        launch/fallback counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_megakernel_smoke() == []

    def test_tensor_smoke_passes(self):
        """The tensor-plane smoke: paired vector_kernel/topk_fusion spans
        with rows/dim/k on the E-args, fused top-k bit-identical to the
        serial pair, strictly fewer device programs, HELP-linted
        launch/fallback counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_tensor_smoke() == []

    def test_vector_serving_smoke_passes(self):
        """The vector-serving-plane smoke: concurrent same-shape vector
        top-k statements coalesce into stacked launches (paired
        vector_batch_launch spans, strictly fewer device programs,
        bit-identical per query), an ANN probe leaves a paired ann_probe
        span plus an on-schema system.runtime.ann_recall row, and the
        three serving counters pass the HELP lint."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_vector_serving_smoke() == []

    def test_ha_smoke_passes(self):
        """The serving-fabric-plane smoke: paired leader_lease/
        dispatch_replay/worker_drain spans, lease takeover under chaos
        expiry, a crash->resume round trip bit-identical to the oracle,
        torn-tail journal recovery, HELP-linted failover/renewal/torn
        counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_ha_smoke() == []

    def test_objectstore_smoke_passes(self):
        """The object-store-substrate smoke: lease takeover, warm-tier
        publish, and a crash->resume round trip all on the rename-free
        object backend with throttle/torn-put/list-lag chaos armed —
        paired object_store_request spans with ok + recovered outcomes,
        HELP-linted trino_tpu_object_store_* counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_objectstore_smoke() == []

    def test_fleet_smoke_passes(self):
        """The coordinator-fleet-plane smoke: a three-node fleet converges,
        a non-owner 307s to the owner (client follows to a correct result),
        a mid-run owner kill lapses its heartbeat and reassigns ONLY the
        dead hash range, a follower serves the dead owner's query status
        during failover, paired proto_route/fleet_reassign spans, and
        HELP-linted fleet counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_fleet_smoke() == []


class TestSchemaFilterRules:
    def test_table_scoped_deny_does_not_hide_schema(self):
        from trino_tpu.spi.security import RuleBasedAccessControl

        ac = RuleBasedAccessControl.from_config(
            {
                "tables": [
                    {"schema": "sales", "table": "secret", "privileges": []},
                    {"schema": "sales", "privileges": ["SELECT"]},
                ]
            }
        )
        assert ac.filter_schemas("bob", "c", ["sales"]) == ["sales"]

    def test_whole_schema_deny_hides(self):
        from trino_tpu.spi.security import RuleBasedAccessControl

        ac = RuleBasedAccessControl.from_config(
            {
                "tables": [
                    {"user": "bob", "schema": "secret", "privileges": []},
                    {"privileges": ["SELECT"]},
                ]
            }
        )
        assert ac.filter_schemas("bob", "c", ["secret", "open"]) == ["open"]
        assert ac.filter_schemas("alice", "c", ["secret"]) == ["secret"]


class TestClusterSmoke:
    def test_cluster_smoke_passes(self):
        """The cluster-observability-plane smoke: two leased coordinators +
        two real workers, coordinator_crash chaos mid-query, standby resume
        -> ONE merged Perfetto trace (>=2 worker lanes, both leader epochs,
        skew-aligned monotonic), HELP-linted federated exposition, and a
        persisted profile whose stage breakdown sums to within 5% of wall
        time."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_cluster_smoke() == []


class TestClockSync:
    """Clock-skew alignment edges (satellite): zero-RTT, negative offset,
    min-RTT sample selection, and a worker restart's fresh monotonic
    epoch."""

    def test_zero_rtt_exact_offset(self):
        from trino_tpu.runtime.clusterobs import ClockSync

        cs = ClockSync()
        assert cs.observe("w", 1_000, rtt_us=0, local_mono_us=5_000) == 4_000
        assert cs.offset_us("w") == 4_000

    def test_negative_offset_remote_clock_ahead(self):
        from trino_tpu.runtime.clusterobs import ClockSync

        cs = ClockSync()
        # the remote monotonic clock reads AHEAD of ours: offset negative
        assert cs.observe("w", 9_000, rtt_us=0, local_mono_us=1_000) == -8_000

    def test_min_rtt_sample_wins(self):
        from trino_tpu.runtime.clusterobs import ClockSync

        cs = ClockSync()
        cs.observe("w", 1_000, rtt_us=100, local_mono_us=5_000)
        tight = cs.offset_us("w")
        # a later, LOOSER (higher-RTT) sample must not displace the tight one
        cs.observe("w", 2_000, rtt_us=50_000, local_mono_us=9_000)
        assert cs.offset_us("w") == tight

    def test_worker_restart_resets_monotonic_epoch(self):
        from trino_tpu.runtime.clusterobs import ClockSync

        cs = ClockSync()
        cs.observe("w", 50_000_000, rtt_us=10, local_mono_us=60_000_000)
        # restart: the remote clock REGRESSES far past jitter slack — the
        # stale best sample must be discarded even at a worse RTT, or every
        # post-restart segment would be aligned with the dead clock
        off = cs.observe("w", 1_000, rtt_us=40_000, local_mono_us=61_000_000)
        assert off == 61_000_000 - (1_000 + 20_000)
        assert cs.offset_us("w") == off

    def test_unmeasured_first_rtt_never_locks_in(self):
        """A worker's FIRST announcement has no RTT yet (rtt_us=None on the
        wire). It must yield a provisional offset but rank below ANY later
        measured sample — a claimed rtt=0 would win the min-RTT rule
        forever, freezing an offset biased by the full one-way delay."""
        from trino_tpu.runtime.clusterobs import ClockSync

        cs = ClockSync()
        # provisional: no midpoint correction applied, offset = local-remote
        assert cs.observe_announcement(
            "w", {"mono_us": 1_000, "rtt_us": None}, local_mono_us=42_000
        ) == 41_000
        # the first MEASURED sample supersedes it despite its nonzero RTT
        off = cs.observe("w", 2_000, rtt_us=10_000, local_mono_us=48_000)
        assert off == 48_000 - (2_000 + 5_000)
        assert cs.offset_us("w") == off


class TestTraceAssembly:
    """Deterministic tids (satellite regression), query filtering, and
    skew-aligned merging."""

    @staticmethod
    def _ring_with_threads(order):
        """A FlightRecorder ring whose named threads START in ``order`` —
        the arrival-order tid assignment differs per order, the canonical
        export must not. Every thread is held alive until all have
        recorded: CPython reuses thread idents after join, which would
        collapse the lanes."""
        import threading

        from trino_tpu.runtime.observability import FlightRecorder

        rec = FlightRecorder()
        rec.enabled = True
        hold = threading.Event()
        threads = []
        for name in order:
            recorded = threading.Event()

            def work(name=name, recorded=recorded):
                with rec.span("op", "operator", who=name):
                    pass
                recorded.set()
                hold.wait()

            t = threading.Thread(target=work, name=name)
            t.start()
            recorded.wait()  # serialize span order across threads
            threads.append(t)
        hold.set()
        for t in threads:
            t.join()
        return rec

    def test_repeated_export_of_same_ring_byte_identical(self):
        import json

        from trino_tpu.runtime.clusterobs import canonicalize_trace, local_segment

        rec = self._ring_with_threads(["beta", "alpha"])
        t1 = canonicalize_trace(local_segment([], recorder=rec))
        t2 = canonicalize_trace(local_segment([], recorder=rec))
        assert json.dumps(t1, sort_keys=True) == json.dumps(t2, sort_keys=True)

    def test_tids_derive_from_thread_names_not_arrival(self):
        from trino_tpu.runtime.clusterobs import canonicalize_trace, local_segment

        for order in (["beta", "alpha"], ["alpha", "beta"]):
            rec = self._ring_with_threads(order)
            trace = canonicalize_trace(local_segment([], recorder=rec))
            names = {
                e["tid"]: e["args"]["name"]
                for e in trace["traceEvents"]
                if e.get("ph") == "M" and e.get("name") == "thread_name"
            }
            # sorted (thread-name) -> tid regardless of start order
            assert names == {1: "alpha", 2: "beta"}

    def test_filter_keeps_window_nested_events_and_pairing(self):
        from trino_tpu.runtime.clusterobs import filter_events_for_query

        events = [
            {"name": "task", "cat": "task", "ph": "B", "ts": 1, "pid": 1,
             "tid": 1, "args": {"task_id": "q1_f0_p0"}},
            {"name": "op", "cat": "operator", "ph": "B", "ts": 2, "pid": 1,
             "tid": 1},
            {"name": "spill_write", "cat": "spill", "ph": "i", "ts": 3,
             "pid": 1, "tid": 1},
            {"name": "op", "cat": "operator", "ph": "E", "ts": 4, "pid": 1,
             "tid": 1},
            {"name": "task", "cat": "task", "ph": "E", "ts": 5, "pid": 1,
             "tid": 1},
            # another query's task on another thread: excluded entirely
            {"name": "task", "cat": "task", "ph": "B", "ts": 2, "pid": 1,
             "tid": 2, "args": {"task_id": "q2_f0_p0"}},
            {"name": "task", "cat": "task", "ph": "E", "ts": 6, "pid": 1,
             "tid": 2},
            # stray instant outside any window, no query reference
            {"name": "noise", "cat": "x", "ph": "i", "ts": 7, "pid": 1,
             "tid": 1},
        ]
        kept = filter_events_for_query(events, ["q1"])
        assert [e["name"] for e in kept] == [
            "task", "op", "spill_write", "op", "task"
        ]
        b = sum(1 for e in kept if e["ph"] == "B")
        e_ = sum(1 for e in kept if e["ph"] == "E")
        assert b == e_ == 2

    def test_merge_aligns_negative_offset_and_stays_monotonic(self):
        from trino_tpu.runtime.clusterobs import assemble_cluster_trace
        from trino_tpu.runtime.observability import validate_chrome_trace

        def seg(ts0):
            return {"traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                 "args": {"name": "x"}},
                {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
                 "args": {"name": "t"}},
                {"name": "s", "ph": "B", "ts": ts0, "pid": 1, "tid": 1},
                {"name": "s", "ph": "E", "ts": ts0 + 10, "pid": 1, "tid": 1},
            ]}

        merged = assemble_cluster_trace(
            {"worker-a": seg(1_000_000), "worker-b": seg(500)},
            offsets={"worker-a": -999_000, "worker-b": 1_500},
        )
        assert validate_chrome_trace(merged) == []
        by_node = {}
        lanes = {
            e["pid"]: e["args"]["name"]
            for e in merged["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        for e in merged["traceEvents"]:
            if e.get("ph") == "B":
                by_node[lanes[e["pid"]]] = e["ts"]
        assert by_node == {"worker-a": 1_000, "worker-b": 2_000}

    def test_merge_clamps_regressed_timestamps_per_lane(self):
        """A restarted worker's ring can hold two monotonic epochs; after
        alignment the lane must still satisfy Perfetto's per-track order."""
        from trino_tpu.runtime.clusterobs import assemble_cluster_trace
        from trino_tpu.runtime.observability import validate_chrome_trace

        seg = {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "w"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "t"}},
            {"name": "a", "ph": "B", "ts": 10_000, "pid": 1, "tid": 1},
            {"name": "a", "ph": "E", "ts": 10_010, "pid": 1, "tid": 1},
            # fresh monotonic epoch after restart: clock regressed
            {"name": "b", "ph": "B", "ts": 5, "pid": 1, "tid": 1},
            {"name": "b", "ph": "E", "ts": 15, "pid": 1, "tid": 1},
        ]}
        merged = assemble_cluster_trace({"worker": seg})
        assert validate_chrome_trace(merged) == []

    def test_journal_records_become_their_own_lane(self):
        from trino_tpu.runtime.clusterobs import assemble_cluster_trace

        seg = {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "c"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "t"}},
            {"name": "q", "ph": "i", "ts": 100, "pid": 1, "tid": 1},
        ]}
        merged = assemble_cluster_trace(
            {"coordinator": seg},
            journal_records=[
                {"kind": "begin", "epoch": 1, "ts": 10.0, "query_id": "q"},
                {"kind": "finished", "epoch": 2, "ts": 11.0},
            ],
        )
        marks = [e for e in merged["traceEvents"]
                 if e.get("cat") == "journal"]
        assert [m["name"] for m in marks] == [
            "journal:begin", "journal:finished"
        ]
        assert {m["args"]["epoch"] for m in marks} == {1, 2}

    def test_merged_trace_monotonic_under_lease_expire_failover(
        self, tmp_path, monkeypatch
    ):
        """Satellite: mid-query the leader's renewal forfeits under
        ``lease_expire`` chaos (a GC pause), the standby claims epoch 2,
        and the fenced old leader aborts at its next journal append; the
        standby resumes from the orphaned journal and the merged cluster
        trace stays monotonic per lane with ``task_attempt`` spans from
        BOTH leader epochs."""
        import time

        from trino_tpu.parallel.runner import DistributedQueryRunner
        from trino_tpu.runtime.clusterobs import (
            assemble_cluster_trace,
            local_segment,
        )
        from trino_tpu.runtime.failure import ChaosInjector
        from trino_tpu.runtime.ha import (
            DispatchJournal,
            FencedWriteError,
            LeaderLease,
            orphaned_journals,
            resume_fte_query,
        )
        from trino_tpu.runtime.observability import (
            RECORDER,
            validate_chrome_trace,
        )

        sql = ("SELECT count(*) FROM lineitem JOIN orders "
               "ON l_orderkey = o_orderkey")
        exdir = str(tmp_path / "ex")
        hadir = str(tmp_path / "ha")

        def make_runner(lease):
            r = DistributedQueryRunner.tpch(scale=0.0005, n_workers=2)
            r.session.set("retry_policy", "TASK")
            r.session.set("join_distribution_type", "PARTITIONED")
            r.session.set("target_partition_rows", 500)
            r.session.set("fte_exchange_dir", exdir)
            r.session.set("ha_plane", True)
            r.session.set("cluster_obs", True)
            r.ha_lease = lease
            return r

        lease_a = LeaderLease(hadir, "coord-a", ttl=0.2)
        lease_b = LeaderLease(hadir, "coord-b", ttl=10.0)
        assert lease_a.acquire() and lease_a.epoch == 1

        orig_stage_done = DispatchJournal.stage_done
        failed_over = []

        def stage_done_with_failover(journal, fid):
            if not failed_over:
                failed_over.append(True)
                # the GC pause: lease_expire chaos forfeits the renewal,
                # the lease lapses, the standby takes epoch 2 — the
                # delegated append below is then fenced
                with ChaosInjector() as chaos:
                    chaos.arm("lease_expire", times=1)
                    assert not lease_a.renew()
                time.sleep(0.25)
                assert lease_b.acquire() and lease_b.epoch == 2
            return orig_stage_done(journal, fid)

        monkeypatch.setattr(
            DispatchJournal, "stage_done", stage_done_with_failover
        )
        RECORDER.clear()
        RECORDER.enable()
        try:
            with pytest.raises(FencedWriteError):
                make_runner(lease_a).execute(sql)
            orphans = orphaned_journals(exdir)
            assert len(orphans) == 1
            result = resume_fte_query(make_runner(lease_b), orphans[0])
            assert result.rows and result.rows[0][0]
            journal_records = (result.query_stats or {}).get("journal") or []
            qid = next(
                str(r["query_id"]) for r in journal_records
                if r.get("kind") == "begin"
            )
            merged = assemble_cluster_trace(
                {"coordinator": local_segment([qid])},
                journal_records=journal_records,
            )
        finally:
            RECORDER.disable()
            RECORDER.clear()
        assert validate_chrome_trace(merged) == []  # paired B/E + monotonic
        epochs = {
            (e.get("args") or {}).get("epoch")
            for e in merged["traceEvents"]
            if e.get("name") == "task_attempt" and e.get("ph") == "B"
        }
        assert {1, 2} <= epochs


class TestFederatedMetrics:
    def test_announcement_snapshot_bounded_and_drop_counted(self):
        """Satellite: the piggybacked snapshot is capped; overflow is
        dropped and counted, so heartbeats never bloat."""
        from trino_tpu.runtime.clusterobs import announcement_metrics
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        for i in range(6):
            reg.counter(f"m{i}_total", help="a counter").inc()
        series, dropped = announcement_metrics(reg, max_series=4)
        assert len(series) == 4
        assert dropped == 2
        drop_counter = reg.counter(
            "trino_tpu_announcement_metrics_dropped_total",
            help="metric series dropped from announcement snapshots by the "
                 "size bound",
        )
        assert drop_counter.value == 2

    def test_render_preserves_help_adds_node_labels_merges_buckets(self):
        from trino_tpu.runtime.clusterobs import (
            ClusterMetrics,
            announcement_metrics,
        )
        from trino_tpu.runtime.metrics import MetricsRegistry

        cm = ClusterMetrics()
        for node, n in (("w1", 2), ("w2", 3)):
            reg = MetricsRegistry()
            reg.counter("jobs_total", help="jobs processed").inc(n)
            h = reg.histogram(
                "lat_secs", help="latency", buckets=[0.1, 1.0]
            )
            for _ in range(n):
                h.observe(0.05)
            series, _ = announcement_metrics(reg, max_series=100)
            cm.ingest(node, series)
        text = cm.render()
        assert text.count("# HELP jobs_total jobs processed") == 1
        assert 'jobs_total{node="w1"} 2' in text
        assert 'jobs_total{node="w2"} 3' in text
        # cross-node merged histogram under node="all": bucket-wise sums
        assert 'lat_secs_bucket{node="all",le="0.1"} 5' in text
        assert 'lat_secs_count{node="all"} 5' in text

    def test_cluster_tables_sql_queryable(self):
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.runtime.clusterobs import (
            ClusterMetrics,
            announcement_metrics,
        )
        from trino_tpu.runtime.metrics import MetricsRegistry

        runner = LocalQueryRunner.tpch(scale=0.001)
        cm = ClusterMetrics()
        reg = MetricsRegistry()
        reg.counter("remote_things_total", help="things").inc(7)
        series, _ = announcement_metrics(reg, max_series=100)
        cm.ingest("worker-9", series)
        runner.metadata.system_context.cluster_metrics = cm
        res = runner.execute(
            "SELECT node, value FROM system.metrics.cluster_counters "
            "WHERE name = 'remote_things_total'"
        )
        assert ("worker-9", 7.0) in res.rows
        hist = runner.execute(
            "SELECT count(*) FROM system.metrics.cluster_histograms "
            "WHERE node = 'coordinator'"
        )
        # the coordinator's own histograms fold in with a node column
        assert hist.rows[0][0] >= 0

    def test_departed_node_snapshot_evicted_after_ttl(self):
        """A node that stops announcing (drained/dead) must age out of the
        fold — not serve its frozen last snapshot in the exposition and
        SQL tables forever."""
        import time

        from trino_tpu.runtime.clusterobs import ClusterMetrics

        cm = ClusterMetrics(ttl_secs=0.05)
        cm.ingest("gone", [{"name": "x_total", "type": "counter",
                            "value": 1.0, "help": "x", "labels": {}}])
        assert any(r[2] == "gone" for r in cm.counters_rows())
        time.sleep(0.1)
        cm.ingest("alive", [{"name": "x_total", "type": "counter",
                             "value": 2.0, "help": "x", "labels": {}}])
        nodes = {r[2] for r in cm.counters_rows()}
        assert nodes == {"alive"}
        assert 'node="gone"' not in cm.render()
        # ttl<=0 keeps forever (the default store is long-lived regardless)
        keep = ClusterMetrics(ttl_secs=0)
        keep.ingest("gone", [{"name": "x_total", "type": "counter",
                              "value": 1.0, "help": "x", "labels": {}}])
        time.sleep(0.02)
        assert any(r[2] == "gone" for r in keep.counters_rows())


class TestQueryProfiles:
    def test_query_manager_auto_persists_over_threshold(self, tmp_path,
                                                        monkeypatch):
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.runtime.clusterobs import profile_store
        from trino_tpu.runtime.query_manager import QueryManager, QueryState

        import threading

        monkeypatch.setenv("TRINO_TPU_QUERY_PROFILE_DIR", str(tmp_path))
        runner = LocalQueryRunner.tpch(scale=0.001)
        runner.session.set("cluster_obs", True)
        mgr = QueryManager(runner.execute)
        # profile persistence happens BEFORE query_completed dispatch, so a
        # completion listener is the hook-finished synchronization point
        completed = threading.Event()
        mgr.add_listener(lambda _q: completed.set())
        q = mgr.submit("SELECT count(*) FROM nation")
        assert q.wait_done(120) and q.state is QueryState.FINISHED
        assert completed.wait(30)
        store = profile_store(str(tmp_path))
        profile = store.read(q.query_id)
        assert profile is not None
        assert profile["queryId"] == q.query_id
        assert profile["state"] == "FINISHED"
        assert profile["version"] == 1
        # a threshold above the query's wall time suppresses persistence
        runner.session.set("slow_query_threshold", 3600.0)
        completed.clear()
        q2 = mgr.submit("SELECT count(*) FROM region")
        assert q2.wait_done(120) and q2.state is QueryState.FINISHED
        assert completed.wait(30)
        assert store.read(q2.query_id) is None

    def test_profiles_sql_table_and_gate_off_path(self, tmp_path,
                                                  monkeypatch):
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.runtime.clusterobs import build_profile, profile_store
        from trino_tpu.runtime.query_manager import QueryManager, QueryState

        monkeypatch.setenv("TRINO_TPU_QUERY_PROFILE_DIR", str(tmp_path))
        store = profile_store(str(tmp_path))
        store.write(build_profile(
            "q_profiled", "SELECT 1", wall_secs=0.5,
            query_stats={"times": {"device_busy_secs": 0.3,
                                   "host_wait_secs": 0.1}},
        ))
        runner = LocalQueryRunner.tpch(scale=0.001)
        res = runner.execute(
            "SELECT query_id, diagnosis FROM system.runtime.query_profiles"
        )
        assert any(r[0] == "q_profiled" for r in res.rows)
        diag = next(r[1] for r in res.rows if r[0] == "q_profiled")
        assert "device" in diag
        # cluster_obs OFF: a completed query persists nothing
        mgr = QueryManager(runner.execute)
        q = mgr.submit("SELECT count(*) FROM nation")
        assert q.wait_done(120) and q.state is QueryState.FINISHED
        assert store.read(q.query_id) is None

    def test_explain_analyze_verbose_diagnosis_line(self):
        from trino_tpu.runtime import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.001)
        sql = ("EXPLAIN ANALYZE VERBOSE SELECT l_returnflag, count(*) "
               "FROM lineitem GROUP BY 1")
        plain = "\n".join(r[0] for r in runner.execute(sql).rows)
        assert "dominant cost" not in plain  # gated off by default
        runner.session.set("cluster_obs", True)
        verbose = "\n".join(r[0] for r in runner.execute(sql).rows)
        assert "dominant cost — " in verbose
        tail = verbose.split("dominant cost — ", 1)[1]
        assert "%" in tail

    def test_dominant_cost_renders_stage_and_component(self):
        from trino_tpu.runtime.clusterobs import dominant_cost

        line = dominant_cost([
            ("stage 1", 1.0, {"device_secs": 0.8, "host_secs": 0.2}),
            ("stage 2", 3.0, {"exchange_pull_secs": 2.5,
                              "device_secs": 0.5}),
        ])
        assert line.startswith("stage 2: ")
        assert line.endswith("% exchange pull")
        assert dominant_cost([]) is None


class TestClusterEndpoints:
    def test_worker_announcement_off_path_byte_identical(self, monkeypatch):
        from trino_tpu.metadata import CatalogManager
        from trino_tpu.server.worker import WorkerServer

        monkeypatch.delenv("TRINO_TPU_CLUSTER_OBS", raising=False)
        w = WorkerServer(CatalogManager())
        assert set(w.announcement_body()) == {
            "uri", "version", "device", "memory"
        }
        monkeypatch.setenv("TRINO_TPU_CLUSTER_OBS", "1")
        body = w.announcement_body()
        assert isinstance(body["metrics"], list)
        assert "mono_us" in body["clock"] and "rtt_us" in body["clock"]

    def test_worker_flightrecorder_route_gated_and_signed(self, monkeypatch):
        import urllib.error
        import urllib.request

        from trino_tpu.metadata import CatalogManager
        from trino_tpu.server.worker import (
            SIGNATURE_HEADER,
            WorkerServer,
            sign,
        )

        monkeypatch.delenv("TRINO_TPU_CLUSTER_OBS", raising=False)
        w = WorkerServer(CatalogManager(), secret="obs-secret").start()
        try:
            url = f"http://{w.address}/v1/flightrecorder"
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=10)
            assert err.value.code == 404  # flag off: route absent
            monkeypatch.setenv("TRINO_TPU_CLUSTER_OBS", "1")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url, timeout=10)
            assert err.value.code == 401  # unsigned
            req = urllib.request.Request(url + "?query_id=qx")
            req.add_header(
                SIGNATURE_HEADER, sign("obs-secret", "GET", "/v1/flightrecorder")
            )
            payload = json.loads(
                urllib.request.urlopen(req, timeout=10).read()
            )
            assert payload["node"]
            assert "traceEvents" in payload["trace"]
        finally:
            w.stop()

    def test_coordinator_cluster_routes_gated(self, monkeypatch):
        import urllib.error
        import urllib.request

        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.server.coordinator import CoordinatorServer

        monkeypatch.delenv("TRINO_TPU_CLUSTER_OBS", raising=False)
        srv = CoordinatorServer(LocalQueryRunner.tpch(scale=0.001)).start()
        try:
            for rel in ("/v1/metrics/cluster", "/v1/query/qx/profile"):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(
                        f"http://{srv.address}{rel}", timeout=10
                    )
                assert err.value.code == 404
            monkeypatch.setenv("TRINO_TPU_CLUSTER_OBS", "1")
            text = urllib.request.urlopen(
                f"http://{srv.address}/v1/metrics/cluster", timeout=10
            ).read().decode()
            assert 'node="coordinator"' in text
            assert "# HELP" in text
        finally:
            srv.stop()

    def test_coordinator_query_id_filter_gated_off(self, monkeypatch):
        """With the flag off the coordinator's /v1/flightrecorder ignores
        ?query_id= (unknown params always were ignored) — the response is
        byte-identical to the pre-plane full-ring export."""
        import urllib.request

        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.server.coordinator import CoordinatorServer

        monkeypatch.delenv("TRINO_TPU_CLUSTER_OBS", raising=False)
        srv = CoordinatorServer(LocalQueryRunner.tpch(scale=0.001)).start()
        try:
            base = f"http://{srv.address}/v1/flightrecorder"
            plain = urllib.request.urlopen(base, timeout=10).read()
            filtered = urllib.request.urlopen(
                base + "?query_id=qx", timeout=10
            ).read()
            assert filtered == plain
            # flag on: the same request returns the filtered segment
            monkeypatch.setenv("TRINO_TPU_CLUSTER_OBS", "1")
            seg = json.loads(urllib.request.urlopen(
                base + "?query_id=qx", timeout=10
            ).read())
            # nothing recorded for qx: metadata-only export
            assert [e for e in seg["traceEvents"] if e.get("ph") != "M"] == []
        finally:
            srv.stop()

    def test_announcement_riders_feed_clock_and_metrics(self, monkeypatch):
        import urllib.request

        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.server.coordinator import CoordinatorServer

        srv = CoordinatorServer(LocalQueryRunner.tpch(scale=0.001)).start()
        try:
            body = json.dumps({
                "uri": "http://w:1", "clock": {"mono_us": 10, "rtt_us": 4},
                "metrics": [{"name": "x_total", "type": "counter",
                             "value": 2.0, "help": "x", "labels": {}}],
            }).encode()
            req = urllib.request.Request(
                f"http://{srv.address}/v1/announcement/w-obs",
                data=body, method="PUT",
            )
            urllib.request.urlopen(req, timeout=10)
            assert srv.clock_sync.offset_us("w-obs") != 0
            rows = srv.cluster_metrics.counters_rows()
            assert any(r[0] == "x_total" and r[2] == "w-obs" for r in rows)
        finally:
            srv.stop()
