"""trino_tpu — a TPU-native distributed SQL query engine.

A ground-up reimplementation of the capabilities of trinodb/trino (the Java MPP SQL
engine) with a JAX/XLA/Pallas execution substrate: SQL is parsed/analyzed/planned in
Python (cold path, like Trino's coordinator), and query fragments execute as compiled
XLA programs over device-resident columnar Pages, sharded across a TPU mesh with XLA
collectives playing the role of Trino's HTTP shuffle.

See SURVEY.md at the repo root for the reference blueprint this build follows.
"""

import jax as _jax

# 64-bit types are part of the SQL contract (BIGINT/DOUBLE/DECIMAL sums). On TPU,
# int64/float64 are emulated but correct; hot kernels downcast where types allow.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from .spi.types import (  # noqa: E402,F401
    BOOLEAN,
    TINYINT,
    SMALLINT,
    INTEGER,
    BIGINT,
    REAL,
    DOUBLE,
    VARCHAR,
    DATE,
    TIMESTAMP,
    UNKNOWN,
    Type,
    decimal_type,
    varchar_type,
    parse_type,
)
from .spi.page import Column, Dictionary, Page  # noqa: E402,F401
from . import native  # noqa: E402,F401
