"""Cluster memory arbitration (runtime/memory.py): context accounting +
rollback, blocking pool reservations (backpressure), revocable spill, the
low-memory killer, resource-group soft memory limits, the system tables, and
the overload chaos suite (N >> pool concurrent queries: killer fires,
survivors bit-identical, zero wedges)."""

import threading
import time

import numpy as np
import pytest

from trino_tpu.runtime.failure import ChaosInjector
from trino_tpu.runtime.local import LocalQueryRunner
from trino_tpu.runtime.memory import (
    AggregatedMemoryContext,
    ClusterMemoryManager,
    ExceededMemoryLimitError,
    MemoryPool,
    NoneLowMemoryKiller,
    QueryKilledError,
    QueryMemoryInfo,
    TotalReservationLowMemoryKiller,
    TotalReservationOnBlockedNodesLowMemoryKiller,
    memory_scope,
    page_bytes,
    parse_bytes,
)
from trino_tpu.runtime.observability import RECORDER
from trino_tpu.runtime.query_manager import QueryManager, QueryState

SCALE = 0.001

# the sustained-concurrency mix (Q1/Q3/Q6/Q13 shapes): deterministic orders
# so solo-vs-overload results compare bit-identically
Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity), count(*)
FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus ORDER BY l_returnflag, l_linestatus
"""
Q3 = """
SELECT o_orderkey, sum(l_extendedprice)
FROM lineitem JOIN orders ON l_orderkey = o_orderkey
WHERE o_orderdate < DATE '1995-03-15'
GROUP BY o_orderkey ORDER BY 2 DESC, 1 LIMIT 10
"""
Q6 = """
SELECT sum(l_extendedprice * l_discount)
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24
"""
Q13 = """
SELECT c_custkey, count(o_orderkey)
FROM customer LEFT JOIN orders ON c_custkey = o_custkey
GROUP BY c_custkey ORDER BY 2 DESC, 1 LIMIT 10
"""
MIX = [Q1, Q3, Q6, Q13]


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(scope="module")
def solo(runner):
    """Solo baselines + the per-query pool peak, measured on an unbounded
    accounting pool — the overload pool is sized from these."""
    baselines = {}
    peaks = []
    for i, sql in enumerate(MIX):
        probe = MemoryPool(0, name=f"probe{i}")
        with memory_scope(f"probe{i}", probe):
            res = runner.execute(sql)
        baselines[sql] = res.rows
        peaks.append(probe.peak_bytes)
    assert min(peaks) > 0, "accounting recorded nothing"
    return baselines, max(peaks)


# --------------------------------------------------------------------------- #
# contexts (satellite: rollback regression, concurrency, peaks, page_bytes)
# --------------------------------------------------------------------------- #


class TestMemoryContexts:
    def test_limit_exceed_rolls_back(self):
        # regression: the old _update mutated _bytes before raising, leaving
        # the query (and the child local) permanently inflated — spill/retry
        # paths then saw phantom usage
        root = AggregatedMemoryContext(limit_bytes=1000)
        a = root.new_local("op_a")
        a.set_bytes(800)
        b = root.new_local("op_b")
        with pytest.raises(ExceededMemoryLimitError):
            b.set_bytes(500)
        assert root.reserved_bytes == 800
        assert b.get_bytes() == 0
        # usage is true, so a smaller reservation still fits
        b.set_bytes(150)
        assert root.reserved_bytes == 950

    def test_limit_exceed_rolls_back_pool(self):
        pool = MemoryPool(0, name="p")
        root = AggregatedMemoryContext(limit_bytes=100, pool=pool, owner="q")
        with pytest.raises(ExceededMemoryLimitError):
            root.new_local("op").set_bytes(200)
        assert pool.reserved_bytes == 0

    def test_concurrent_reservations(self):
        root = AggregatedMemoryContext()
        pool = MemoryPool(0, name="c")
        attached = AggregatedMemoryContext(pool=pool, owner="q")
        n_threads, n_iters = 8, 200

        def work(ctx):
            local = ctx.new_local("op")
            for i in range(n_iters):
                local.add_bytes(7)
            local.add_bytes(-3 * n_iters)

        threads = [
            threading.Thread(target=work, args=(ctx,))
            for ctx in (root, attached)
            for _ in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        expected = n_threads * n_iters * 4
        assert root.reserved_bytes == expected
        assert attached.reserved_bytes == expected
        assert pool.reserved_bytes == expected
        assert root.peak_bytes >= expected

    def test_peak_tracking(self):
        root = AggregatedMemoryContext()
        a = root.new_local("a")
        a.set_bytes(900)
        a.set_bytes(100)
        assert root.reserved_bytes == 100
        assert root.peak_bytes == 900

    def test_revocable_separate_and_exempt_from_limit(self):
        root = AggregatedMemoryContext(limit_bytes=100)
        r = root.new_local("parked", revocable=True)
        r.set_bytes(1_000_000)  # revocable is not charged to the query limit
        assert root.revocable_bytes == 1_000_000
        assert root.reserved_bytes == 0
        assert root.total_bytes == 1_000_000

    def test_close_frees_pool(self):
        pool = MemoryPool(0, name="f")
        ctx = AggregatedMemoryContext(pool=pool, owner="q")
        ctx.new_local("a").set_bytes(500)
        ctx.new_local("b", revocable=True).set_bytes(300)
        assert pool.reserved_bytes == 500 and pool.revocable_bytes == 300
        ctx.close()
        assert pool.reserved_bytes == 0 and pool.revocable_bytes == 0

    def test_page_bytes_plain(self):
        from trino_tpu.spi.page import Column, Page
        from trino_tpu.spi.types import BIGINT

        import jax.numpy as jnp

        col = Column.from_numpy(BIGINT, np.arange(100), capacity=128)
        page = Page((col,), jnp.asarray(np.arange(128) < 100))
        # 128*8 data + 128 valid + 128 active
        assert page_bytes(page) == 128 * 8 + 128 + 128

    def test_page_bytes_dictionary_encoded(self):
        from trino_tpu.spi.page import Column, Page

        import jax.numpy as jnp

        col = Column.from_strings(["aa", "bb", "aa", None], capacity=8)
        page = Page((col,), jnp.asarray(np.arange(8) < 4))
        n = page_bytes(page)
        # int32 codes + valid + active + the host dictionary values
        assert n >= 8 * 4 + 8 + 8 + len("aa") + len("bb")
        # two columns SHARING one dictionary count it once
        col2 = Column.from_strings(
            ["aa", "bb", "bb", None], capacity=8, dictionary=col.dictionary
        )
        page2 = Page((col, col2), jnp.asarray(np.arange(8) < 4))
        assert page_bytes(page2) == n + 8 * 4 + 8

    def test_page_bytes_zero_row_page(self):
        from trino_tpu.spi.page import Column, Page
        from trino_tpu.spi.types import BIGINT

        import jax.numpy as jnp

        col = Column.from_numpy(BIGINT, np.zeros(0, dtype=np.int64),
                                capacity=1)
        page = Page((col,), jnp.zeros((1,), dtype=jnp.bool_))
        assert page_bytes(page) == 8 + 1 + 1

    def test_parse_bytes(self):
        assert parse_bytes("512MB") == 512 << 20
        assert parse_bytes("2GB") == 2 << 30
        assert parse_bytes("4096") == 4096
        assert parse_bytes("1.5kB") == 1536
        assert parse_bytes("") == 0
        assert parse_bytes("nonsense") == 0

    def test_query_max_memory_env_is_late_bound(self, monkeypatch):
        # the env default must take effect even when set AFTER import
        # (monkeypatch/embedding apps), like the pool-size knob
        from trino_tpu.metadata import Session

        s = Session()
        assert s.get("query_max_memory_bytes") == 0
        monkeypatch.setenv("TRINO_TPU_QUERY_MAX_MEMORY", "64MB")
        assert s.get("query_max_memory_bytes") == 64 << 20
        s.set("query_max_memory_bytes", 123)  # explicit SET wins over env
        assert s.get("query_max_memory_bytes") == 123

    def test_page_bytes_dictionary_size_memoized(self):
        from trino_tpu.spi.page import Column, Page

        import jax.numpy as jnp

        col = Column.from_strings(["xx", "yyy"], capacity=4)
        page = Page((col,), jnp.asarray(np.arange(4) < 2))
        n1 = page_bytes(page)
        assert col.dictionary._host_bytes == len("xx") + len("yyy")
        assert page_bytes(page) == n1  # cached sweep, same answer


# --------------------------------------------------------------------------- #
# the pool: blocking, dooming, revoking
# --------------------------------------------------------------------------- #


class TestMemoryPool:
    def test_blocking_reserve_unblocks_on_peer_free(self):
        pool = MemoryPool(1000, name="b", reserve_timeout=10)
        pool.reserve("qa", 800)
        granted = threading.Event()

        def blocked():
            pool.reserve("qb", 600)  # blocks: 800 + 600 > 1000
            granted.set()

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.1)
        assert not granted.is_set()
        assert pool.snapshot()["blockedReservations"] == 1
        pool.reserve("qa", -700)  # peer releases
        assert granted.is_set() or granted.wait(5)
        t.join()
        assert pool.reserved_bytes == 100 + 600

    def test_blocking_reserve_times_out(self):
        pool = MemoryPool(100, name="t")
        pool.reserve("qa", 100)
        t0 = time.monotonic()
        with pytest.raises(ExceededMemoryLimitError, match="exhausted"):
            pool.reserve("qb", 50, timeout=0.2)
        assert time.monotonic() - t0 >= 0.15
        assert pool.reserved_bytes == 100  # nothing booked for qb

    def test_doom_aborts_blocked_reservation(self):
        pool = MemoryPool(100, name="d", reserve_timeout=10)
        pool.reserve("qa", 100)
        failed = []

        def blocked():
            try:
                pool.reserve("qb", 50)
            except QueryKilledError as e:
                failed.append(str(e))

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.05)
        pool.doom("qb", "killed by test")
        t.join(5)
        assert failed == ["killed by test"]
        # new reservations are refused until the owner is freed
        with pytest.raises(QueryKilledError):
            pool.reserve("qb", 1)
        pool.free_owner("qb")
        pool.reserve("qa", -100)
        pool.reserve("qb", 1)  # re-admitted after the sweep

    def test_revocable_never_blocks(self):
        pool = MemoryPool(100, name="r")
        pool.reserve("qa", 90)
        pool.reserve("qa", 500, revocable=True)  # overcommits, returns at once
        assert pool.revocable_bytes == 500

    def test_request_revoke_frees_via_revoker(self):
        pool = MemoryPool(1000, name="rv")
        ctx = AggregatedMemoryContext(pool=pool, owner="qa")
        parked = ctx.new_local("parked", revocable=True)
        parked.set_bytes(600)

        class Revoker:
            def revoke(self, nbytes):
                freed = parked.get_bytes()
                parked.set_bytes(0)
                return freed

        rv = Revoker()
        pool.add_revoker(rv)
        freed = pool.request_revoke(100)
        assert freed == 600
        assert pool.revocable_bytes == 0

    def test_free_owner_sweeps_everything(self):
        pool = MemoryPool(0, name="s")
        pool.reserve("qa", 100)
        pool.reserve("qa", 50, revocable=True)
        assert pool.free_owner("qa") == 150
        assert pool.reserved_bytes == 0 and pool.revocable_bytes == 0

    def test_memory_pressure_chaos_blocks_then_completes(self):
        # the memory_pressure site at pool level: phantom pressure fills the
        # pool, the real reservation BLOCKS (flight span), the phantom
        # releases, the reservation is granted — backpressure, not failure
        pool = MemoryPool(1000, name="chaos", reserve_timeout=10)
        RECORDER.clear()
        RECORDER.enable()
        try:
            with ChaosInjector() as chaos:
                chaos.arm("memory_pressure", times=1, bytes=1000, hold=0.2)
                t0 = time.monotonic()
                pool.reserve("qa", 500)
                waited = time.monotonic() - t0
        finally:
            RECORDER.disable()
        assert chaos.fired.get("memory_pressure") == 1
        assert waited >= 0.1, "reservation did not block under pressure"
        assert pool.reserved_bytes == 500
        events = RECORDER.events()
        RECORDER.clear()
        b = [e for e in events
             if e["name"] == "memory_reserve_blocked" and e["ph"] == "B"]
        e_ = [e for e in events
              if e["name"] == "memory_reserve_blocked" and e["ph"] == "E"]
        assert len(b) == 1 and len(e_) == 1, "blocked span missing/unpaired"
        assert e_[0]["args"]["outcome"] == "granted"


# --------------------------------------------------------------------------- #
# killer policies + cluster manager
# --------------------------------------------------------------------------- #


def _info(owner, user=0, revocable=0, blocked=0, seq=0, doomed=False,
          system=False):
    return QueryMemoryInfo(owner, user, revocable, blocked, seq, doomed, system)


class TestLowMemoryKiller:
    def test_total_reservation_picks_biggest(self):
        k = TotalReservationLowMemoryKiller()
        assert k.choose_victim(
            [_info("a", 100), _info("b", 900), _info("c", 500)]
        ) == "b"

    def test_tie_breaks_to_youngest(self):
        k = TotalReservationLowMemoryKiller()
        assert k.choose_victim(
            [_info("old", 500, seq=1), _info("young", 500, seq=9)]
        ) == "young"

    def test_blocked_nodes_variant_needs_blocked(self):
        k = TotalReservationOnBlockedNodesLowMemoryKiller()
        infos = [_info("a", 900), _info("b", 100)]
        assert k.choose_victim(infos) is None  # nothing blocked: no kill
        infos.append(_info("c", 0, blocked=1))
        assert k.choose_victim(infos) == "a"

    def test_excludes_system_doomed_and_empty(self):
        k = TotalReservationLowMemoryKiller()
        assert k.choose_victim([
            _info("_chaos_pressure", 9999, system=True),
            _info("dying", 5000, doomed=True),
            _info("waiting", 0, blocked=1),
            _info("real", 10),
        ]) == "real"

    def test_none_killer(self):
        assert NoneLowMemoryKiller().choose_victim([_info("a", 1)]) is None


class TestClusterMemoryManager:
    def test_escalation_revoke_then_kill(self):
        # single-threaded: the blocked reserver itself drives the arbiter —
        # first the revoker spills, later the killer sheds the biggest query
        pool = MemoryPool(1000, name="esc", reserve_timeout=10)
        killed = []
        cm = ClusterMemoryManager(
            pool,
            kill_fn=lambda q, r: (killed.append((q, r)), pool.free_owner(q)),
            spill_after=0.0, kill_after=0.05,
        )
        ctx_a = AggregatedMemoryContext(pool=pool, owner="qa")
        parked = ctx_a.new_local("parked", revocable=True)
        parked.set_bytes(600)

        class Revoker:
            def revoke(self, nbytes):
                freed = parked.get_bytes()
                parked.set_bytes(0)
                return freed

        rv = Revoker()
        pool.add_revoker(rv)
        # blocks (600 revocable + 700 > 1000) -> arbiter revokes qa -> fits
        AggregatedMemoryContext(pool=pool, owner="qb").new_local("op").set_bytes(700)
        assert pool.revocable_bytes == 0 and not killed
        # blocks (700 + 700 > 1000), nothing left to revoke -> killer sheds
        # the biggest holder (qb)
        AggregatedMemoryContext(pool=pool, owner="qc").new_local("op").set_bytes(700)
        assert [q for q, _ in killed] == ["qb"]
        assert "low-memory killer" in killed[0][1]
        assert cm.kills_total == 1
        assert pool.reserved_bytes == 700  # qc granted after the kill

    def test_killer_skips_unkillable_owners(self):
        # a shared process pool can hold owners kill_fn cannot act on (e.g.
        # worker TASK ids): kill_fn raising must mark them unkillable — not
        # doom them — and the next poke picks the next-biggest real query
        pool = MemoryPool(1000, name="uk", reserve_timeout=10)
        killed = []

        def kill_fn(owner, reason):
            if owner.startswith("task"):
                raise KeyError(owner)  # not a query this manager tracks
            killed.append(owner)
            pool.free_owner(owner)

        ClusterMemoryManager(
            pool, kill_fn=kill_fn, spill_after=0.0, kill_after=0.02
        )
        pool.reserve("task1", 600)  # biggest owner, but not a query
        pool.reserve("qa", 350)
        # qb blocks: the killer tries task1 (biggest), learns it is
        # unkillable, then sheds qa — and task1 is never doomed
        AggregatedMemoryContext(pool=pool, owner="qb").new_local(
            "op"
        ).set_bytes(300)
        assert killed == ["qa"]
        assert pool.reserved_bytes == 600 + 300
        pool.reserve("task1", 1)  # not doomed: still reserves fine

    def test_pool_listeners_do_not_pin_managers(self):
        # bound-method listeners are held weakly: the process default pool
        # outlives any one QueryManager and must not leak dead ones
        import gc
        import weakref

        pool = MemoryPool(0, name="wl")

        class Owner:
            def __init__(self):
                self.calls = []

            def on_change(self, owner, delta, revocable):
                self.calls.append(delta)

        o = Owner()
        pool.add_listener(o.on_change)
        pool.reserve("q", 10)
        assert o.calls == [10]
        ref = weakref.ref(o)
        del o
        gc.collect()
        assert ref() is None, "pool listener pinned its owner"
        pool.reserve("q", 5)  # dead listener pruned without error


# --------------------------------------------------------------------------- #
# resource groups: soft memory limit
# --------------------------------------------------------------------------- #


class TestResourceGroupSoftMemory:
    def make(self, soft=1000):
        from trino_tpu.runtime.resource_groups import (
            ResourceGroupManager,
            ResourceGroupSpec,
            SelectorSpec,
        )

        spec = ResourceGroupSpec(
            name="g", hard_concurrency_limit=4, max_queued=10,
            soft_memory_limit_bytes=soft,
        )
        return ResourceGroupManager([spec], [SelectorSpec(group=("g",))])

    def test_over_memory_stops_dequeue_release_restarts(self):
        m = self.make(soft=1000)
        t1 = m.submit("u")
        assert t1.admitted
        m.note_memory("g", 1500)  # over the share: queue, don't run
        t2 = m.submit("u")
        assert not t2.admitted
        m.note_memory("g", -600)  # 900 < 1000: dequeue restarts on release
        assert t2.event.wait(1) and t2.admitted
        m.finish(t2)
        m.finish(t1)
        assert m.info()["subGroups"][0]["memoryUsageBytes"] == 900

    def test_from_config_parses_soft_limit(self):
        from trino_tpu.runtime.resource_groups import ResourceGroupManager

        m = ResourceGroupManager.from_config({
            "rootGroups": [{
                "name": "etl", "hardConcurrencyLimit": 2,
                "softMemoryLimit": "1MB",
            }],
            "selectors": [{"group": "etl"}],
        })
        t = m.submit("u")
        assert t.admitted
        m.note_memory("etl", 1 << 20)
        assert not m.submit("u").admitted  # memory-parked at exactly the limit
        m.finish(t)

    def test_flat_info_rows(self):
        m = self.make()
        t = m.submit("u")
        rows = {r["id"]: r for r in m.flat_info()}
        assert rows["g"]["running"] == 1
        assert rows["g"]["softMemoryLimitBytes"] == 1000
        m.finish(t)


# --------------------------------------------------------------------------- #
# revocable spiller integration
# --------------------------------------------------------------------------- #


def _make_page(rows=100, cap=128):
    import jax.numpy as jnp

    from trino_tpu.spi.page import Column, Page
    from trino_tpu.spi.types import BIGINT

    col = Column.from_numpy(BIGINT, np.arange(rows), capacity=cap)
    return Page((col,), jnp.asarray(np.arange(cap) < rows))


class TestRevocableSpiller:
    def test_parked_pages_revoke_under_pressure(self):
        from trino_tpu.runtime.spiller import Spiller, _SpilledPage

        page = _make_page()
        need = page_bytes(page)
        pool = MemoryPool(need + 64, name="park", reserve_timeout=5)
        ctx = AggregatedMemoryContext(pool=pool, owner="qa")
        sp = Spiller(0, memory=ctx)
        try:
            entries = sp.maybe_spill([page])
            assert pool.revocable_bytes == need
            ClusterMemoryManager(pool, kill_fn=None, spill_after=0.0,
                                 kill_after=99.0)
            # qb's blocked reservation triggers the revoke escalation: qa's
            # parked page spills to host instead of qb failing
            AggregatedMemoryContext(pool=pool, owner="qb").new_local(
                "op"
            ).set_bytes(need)
            assert pool.revocable_bytes == 0
            assert sp.spill_count == 1 and sp.revoked_bytes == need
            assert isinstance(entries[0], _SpilledPage)
            loaded = Spiller.load(entries[0])
            assert np.array_equal(
                np.asarray(loaded.columns[0].data)[:100], np.arange(100)
            )
        finally:
            sp.detach()


# --------------------------------------------------------------------------- #
# acceptance: blocking backpressure end to end
# --------------------------------------------------------------------------- #


class TestBackpressureEndToEnd:
    def test_query_blocks_then_completes(self, runner, solo):
        baselines, peak = solo
        pool = MemoryPool(max(2 * peak, 4096), name="bp", reserve_timeout=30)
        cm = ClusterMemoryManager(pool, killer=NoneLowMemoryKiller())
        mgr = QueryManager(runner.execute, max_workers=2, cluster_memory=cm)
        RECORDER.clear()
        RECORDER.enable()
        try:
            with ChaosInjector() as chaos:
                chaos.arm(
                    "memory_pressure", times=1,
                    bytes=pool.max_bytes, hold=0.3,
                )
                q = mgr.submit(Q6)
                assert q.wait_done(120), "query wedged under memory pressure"
        finally:
            RECORDER.disable()
        assert chaos.fired.get("memory_pressure") == 1
        assert q.state is QueryState.FINISHED, (q.error_type, q.error)
        assert q.rows == baselines[Q6]
        events = RECORDER.events()
        RECORDER.clear()
        b = [e for e in events
             if e["name"] == "memory_reserve_blocked" and e["ph"] == "B"]
        e_ = [e for e in events
              if e["name"] == "memory_reserve_blocked" and e["ph"] == "E"]
        assert b, "no memory_reserve_blocked span: the query never blocked"
        assert len(b) == len(e_), "blocked spans unpaired"
        assert any(
            (ev.get("args") or {}).get("outcome") == "granted" for ev in e_
        ), "no blocked reservation was granted after the peer released"


# --------------------------------------------------------------------------- #
# acceptance: overload chaos — killer fires, survivors bit-identical, no wedge
# --------------------------------------------------------------------------- #


class TestOverloadChaos:
    N_QUERIES = 32

    def test_overload_survives(self, runner, solo):
        baselines, peak = solo
        # a pool sized for ~4 complete queries, hit with 32 concurrent;
        # near-zero escalation delays so the killer fires on the first
        # arbiter poke of any blocked reservation — warm-cache queries are
        # fast enough that realistic delays would let the pool drain
        # kill-free on a lucky schedule (the production defaults stay 0.05/
        # 0.25 s; the test pins the escalation ORDER, not its tempo)
        pool = MemoryPool(4 * peak, name="overload", reserve_timeout=120)
        cm = ClusterMemoryManager(
            pool, killer=TotalReservationOnBlockedNodesLowMemoryKiller(),
            spill_after=0.0, kill_after=0.001,
        )
        mgr = QueryManager(runner.execute, max_workers=16, cluster_memory=cm)
        qs = [mgr.submit(MIX[i % len(MIX)]) for i in range(self.N_QUERIES)]
        for q in qs:
            assert q.wait_done(300), f"query {q.query_id} WEDGED: {q.state}"
        finished = [q for q in qs if q.state is QueryState.FINISHED]
        killed = [q for q in qs if q.error_type == "AdministrativelyKilled"]
        unexpected = [
            q for q in qs
            if q.state is not QueryState.FINISHED
            and q.error_type != "AdministrativelyKilled"
        ]
        assert not unexpected, (
            f"non-kill failures under overload: "
            f"{[(q.error_type, q.error) for q in unexpected]}"
        )
        # the killer fired (32 queries cannot fit a 4-query pool) ...
        assert cm.kills_total >= 1 and killed
        # ... with the low-memory reason on every victim
        for q in killed:
            assert "low-memory killer" in (q.error or ""), q.error
        # ... and the survivors' results are BIT-IDENTICAL to their solo runs
        assert finished, "everything was killed — the pool never drained"
        for q in finished:
            assert q.rows == baselines[q.sql], f"survivor {q.query_id} diverged"
        # the pool drained completely: nothing leaked past free_owner
        assert pool.reserved_bytes == 0 and pool.revocable_bytes == 0


# --------------------------------------------------------------------------- #
# system tables
# --------------------------------------------------------------------------- #


class TestSystemTables:
    def test_memory_pool_and_resource_groups_tables(self, runner):
        from trino_tpu.runtime.resource_groups import ResourceGroupManager

        pool = MemoryPool(1 << 30, name="general")
        mgr = QueryManager(
            runner.execute, memory_pool=pool,
            resource_groups=ResourceGroupManager.default(8),
        )
        warm = mgr.submit("SELECT count(*) FROM nation")
        assert warm.wait_done(120) and warm.state is QueryState.FINISHED

        q = mgr.submit(
            "SELECT node_id, pool, max_bytes, reserved_bytes, "
            "revocable_bytes, blocked_queries, low_memory_kills "
            "FROM system.runtime.memory_pool"
        )
        assert q.wait_done(120) and q.state is QueryState.FINISHED, q.error
        rows = {r[0]: r for r in q.rows}
        assert "local" in rows
        local = rows["local"]
        assert local[1] == "general" and local[2] == 1 << 30
        assert isinstance(local[3], int) and local[3] >= 0
        assert local[6] == 0  # no kills

        g = mgr.submit(
            "SELECT id, hard_concurrency_limit, max_queued, running, queued, "
            "memory_usage_bytes FROM system.runtime.resource_groups"
        )
        assert g.wait_done(120) and g.state is QueryState.FINISHED, g.error
        by_id = {r[0]: r for r in g.rows}
        assert "global" in by_id
        # the scan itself runs in the global group
        assert any(r[3] >= 1 for r in g.rows)
        assert all(isinstance(r[5], int) for r in g.rows)

    def test_memory_pool_table_shows_announced_workers(self, runner):
        from trino_tpu.runtime.nodes import InternalNodeManager

        pool = MemoryPool(1 << 20, name="general")
        mgr = QueryManager(runner.execute, memory_pool=pool)
        nodes = InternalNodeManager()
        ctx = runner.metadata.system_context
        prev = ctx.node_manager
        ctx.node_manager = nodes
        try:
            nodes.announce(
                "w1", "http://w1:8080",
                memory={"maxBytes": 4096, "reservedBytes": 1234,
                        "revocableBytes": 5, "peakBytes": 2000,
                        "blockedQueries": 1},
            )
            q = mgr.submit(
                "SELECT node_id, max_bytes, reserved_bytes, blocked_queries "
                "FROM system.runtime.memory_pool WHERE node_id = 'w1'"
            )
            assert q.wait_done(120) and q.state is QueryState.FINISHED, q.error
            assert q.rows == [("w1", 4096, 1234, 1)]
        finally:
            ctx.node_manager = prev
